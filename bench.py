"""Serving benchmark — the framework's north-star measurement harness.

Reproduces the reference's batch-mode benchmarking (launch/dynamo-run
input/batch.rs:42-105: per-request tokens_in/tokens_out/elapsed + aggregate
throughput) against this framework's serving chain: OpenAIPreprocessor →
Backend → JaxEngine (continuous batching, paged KV, prefix cache).

Workload: ShareGPT-like synthetic conversations (lognormal ISL centered
~512, OSL ~128) issued concurrently. Reports output-token throughput as the
headline metric plus req/s and p50/p99 TTFT & ITL, and prints the ONE JSON
line the driver records.

Run on the real TPU chip (default) or CPU smoke mode:
    python bench.py [--requests N] [--concurrency N] [--cpu] [--model 1b|tiny]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--isl", type=int, default=512, help="mean input len")
    ap.add_argument("--osl", type=int, default=128, help="output len")
    ap.add_argument("--cpu", action="store_true", help="CPU smoke mode")
    ap.add_argument("--model", default="1b", choices=["1b", "tiny"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--decode-steps", type=int, default=16,
                    help="fused decode window (amortizes dispatch latency)")
    ap.add_argument("--scenario", default="sharegpt",
                    choices=["sharegpt", "multiturn"],
                    help="multiturn = conversations with growing shared "
                         "prefixes (the KV-offload TTFT scenario, "
                         "reference docs/architecture.md:91-96)")
    ap.add_argument("--host-pages", type=int, default=0,
                    help="host-DRAM offload tier size (multiturn scenario)")
    ap.add_argument("--users", type=int, default=16)
    ap.add_argument("--turns", type=int, default=4)
    ap.add_argument("--max-batch", type=int, default=None,
                    help="override engine max_batch (and batch buckets)")
    return ap.parse_args()


def build_engine(args):
    import jax

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    if args.model == "tiny":
        cfg = ModelConfig.tiny()
        ecfg = EngineConfig(page_size=16, num_pages=256, max_batch=16,
                            prefill_chunk=128, prefill_buckets=(128,),
                            batch_buckets=(4, 16), page_buckets=(16,),
                            decode_steps=args.decode_steps)
    else:
        # Llama-3.2-1B-shaped: ~2.5 GB bf16 params + KV pool on one v5e chip
        cfg = ModelConfig(vocab_size=128256, hidden_size=2048,
                          intermediate_size=8192, num_layers=16,
                          num_heads=32, num_kv_heads=8, head_dim=64,
                          dtype="bfloat16")
        # KV pool: 1536 pages x 64 tok = 96K cached tokens (~3.2 GB);
        # headroom for the decode window's pool gather transients
        ecfg = EngineConfig(page_size=64, num_pages=1536, max_batch=32,
                            prefill_chunk=1024, prefill_buckets=(1024,),
                            batch_buckets=(8, 32), page_buckets=(32,),
                            decode_steps=args.decode_steps,
                            host_pages=args.host_pages)
    if args.max_batch:
        ecfg.max_batch = args.max_batch
        ecfg.batch_buckets = (8, args.max_batch)
    if args.scenario == "multiturn":
        # size the HBM pool BELOW the conversation working set so turns
        # evict each other; the host tier is what keeps TTFT low
        # (~10 pages/user HBM vs histories growing past 17 pages)
        ecfg.num_pages = min(ecfg.num_pages, 10 * args.users)
        ecfg.host_pages = args.host_pages
    print(f"devices: {jax.devices()}", file=sys.stderr)
    engine = JaxEngine(cfg, ecfg, seed=args.seed)
    return engine, cfg


def synth_requests(args, vocab: int):
    """ShareGPT-like synthetic prompts: lognormal input lengths."""
    import numpy as np

    rng = np.random.RandomState(args.seed)
    reqs = []
    for i in range(args.requests):
        isl = int(np.clip(rng.lognormal(mean=np.log(args.isl), sigma=0.6),
                          32, 3072))
        token_ids = rng.randint(1, min(vocab - 10, 255), size=isl).tolist()
        reqs.append((token_ids, args.osl))
    return reqs


async def run_multiturn(args):
    """Multi-turn conversations with shared growing prefixes: each user
    alternates ~turns requests whose prompt = full history + new chunk.
    Measures per-turn TTFT; with --host-pages the evicted histories
    restore from the host tier instead of recomputing (reference KV
    offload '+40% TTFT', docs/architecture.md:91-96)."""
    import numpy as np

    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    engine, cfg = build_engine(args)
    print("warming up (compiling bucket grid)...", file=sys.stderr)
    engine.warmup()
    rng = np.random.RandomState(args.seed)
    histories = [rng.randint(1, 255, 512).tolist()
                 for _ in range(args.users)]
    ttfts = []

    async def one_turn(u):
        req = PreprocessedRequest(
            token_ids=list(histories[u]), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=args.osl, ignore_eos=True),
            eos_token_ids=[])
        t0 = time.monotonic()
        first = None
        out_toks = []
        async for out in engine.generate(req, Context()):
            if out.token_ids and first is None:
                first = time.monotonic() - t0
            out_toks.extend(out.token_ids)
            if out.finish_reason:
                break
        ttfts.append(first)
        histories[u] = histories[u] + out_toks + \
            rng.randint(1, 255, 128).tolist()

    bench_t0 = time.monotonic()
    for turn in range(args.turns):
        await asyncio.gather(*(one_turn(u) for u in range(args.users)))
        print(f"turn {turn + 1}/{args.turns} done", file=sys.stderr)
    wall = time.monotonic() - bench_t0
    await engine.stop()

    later = sorted(t for t in ttfts[args.users:] if t is not None)
    stats = engine.stats()
    report = {
        "scenario": "multiturn", "users": args.users, "turns": args.turns,
        "host_pages": args.host_pages, "wall_s": round(wall, 2),
        "ttft_later_turns_p50_ms":
            round(later[len(later) // 2] * 1000, 1) if later else None,
        "prefix_hit_rate": round(stats["gpu_prefix_cache_hit_rate"], 4),
        "host_restores": stats["host_restore_pages_total"],
        "host_offloads": stats["host_offload_pages_total"],
    }
    print(json.dumps(report), file=sys.stderr)
    return report


async def run_bench(args):
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    engine, cfg = build_engine(args)
    print("warming up (compiling bucket grid)...", file=sys.stderr)
    t0 = time.monotonic()
    engine.warmup()
    print(f"warmup done in {time.monotonic()-t0:.1f}s", file=sys.stderr)

    reqs = synth_requests(args, cfg.vocab_size)
    sem = asyncio.Semaphore(args.concurrency)
    results = []

    async def one(req_idx, token_ids, osl):
        async with sem:
            pre = PreprocessedRequest(
                token_ids=token_ids,
                sampling=SamplingOptions(),  # greedy
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
                eos_token_ids=[])
            ctx = Context()
            t_start = time.monotonic()
            t_first = None
            stamps = []
            n_out = 0
            async for out in engine.generate(pre, ctx):
                now = time.monotonic()
                if out.token_ids:
                    if t_first is None:
                        t_first = now
                    stamps.extend([now] * len(out.token_ids))
                    n_out += len(out.token_ids)
                if out.finish_reason:
                    break
            t_end = time.monotonic()
            # window-amortized ITL: the fused decode window emits K tokens
            # per host sync, so raw inter-arrival gaps are 0 within a
            # window and ~window-time at boundaries (the r1/r2 itl_p50=0
            # artifact). The honest per-request number is the mean
            # inter-token interval over the whole stream.
            itl = ((stamps[-1] - stamps[0]) / (n_out - 1)
                   if n_out > 1 else None)
            results.append({
                "tokens_in": len(token_ids), "tokens_out": n_out,
                "ttft": (t_first - t_start) if t_first else None,
                "elapsed": t_end - t_start, "itl": itl,
            })

    bench_t0 = time.monotonic()
    await asyncio.gather(*(one(i, t, o) for i, (t, o) in enumerate(reqs)))
    wall = time.monotonic() - bench_t0
    await engine.stop()

    total_out = sum(r["tokens_out"] for r in results)
    total_in = sum(r["tokens_in"] for r in results)
    ttfts = sorted(r["ttft"] for r in results if r["ttft"] is not None)
    itls = sorted(r["itl"] for r in results if r["itl"] is not None)

    def pct(v, p):
        return v[min(int(len(v) * p / 100), len(v) - 1)] if v else None

    report = {
        "requests": len(results), "wall_s": round(wall, 3),
        "req_per_s": round(len(results) / wall, 3),
        "output_tok_per_s": round(total_out / wall, 1),
        "total_tok_per_s": round((total_in + total_out) / wall, 1),
        "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 1) if ttfts else None,
        "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 1) if ttfts else None,
        "itl_p50_ms": round(pct(itls, 50) * 1000, 2) if itls else None,
        "itl_p99_ms": round(pct(itls, 99) * 1000, 2) if itls else None,
        "prefix_hit_rate": round(engine.stats()["gpu_prefix_cache_hit_rate"], 4),
    }
    print(json.dumps(report), file=sys.stderr)
    return report


def main():
    args = parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.scenario == "multiturn":
        report = asyncio.run(run_multiturn(args))
        print(json.dumps({
            "metric": f"TTFT p50 (later turns), multiturn "
                      f"{args.users}u x {args.turns}t, host_pages="
                      f"{args.host_pages}",
            "value": report["ttft_later_turns_p50_ms"],
            "unit": "ms", "vs_baseline": 1.0, "detail": report}))
        return
    report = asyncio.run(run_bench(args))
    # the ONE line the driver records (vs_baseline: reference publishes no
    # absolute numbers — BASELINE.json.published == {} — so round-over-round
    # ratio starts at 1.0)
    prev = None
    for path in ("BENCH_prev.json",):
        if os.path.exists(path):
            try:
                with open(path) as f:
                    prev = json.load(f).get("value")
            except Exception:
                prev = None
    value = report["output_tok_per_s"]
    vs = round(value / prev, 3) if prev else 1.0
    print(json.dumps({
        "metric": "output tokens/s, synthetic ShareGPT "
                  f"(ISL~{args.isl}/OSL {args.osl}, {args.requests} reqs, "
                  f"conc {args.concurrency}, {args.model} llama, 1 chip)",
        "value": value,
        "unit": "tok/s",
        "vs_baseline": vs,
        "detail": report,
    }))


if __name__ == "__main__":
    main()
