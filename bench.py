"""Serving benchmark — the framework's north-star measurement harness.

Reproduces the reference's batch-mode benchmarking (launch/dynamo-run
input/batch.rs:42-105: per-request tokens_in/tokens_out/elapsed + aggregate
throughput) against this framework's serving chain: OpenAIPreprocessor →
Backend → JaxEngine (continuous batching, paged KV, prefix cache).

Workload: ShareGPT-like synthetic conversations (lognormal ISL centered
~512, OSL ~128) issued concurrently. Reports output-token throughput as the
headline metric plus req/s and p50/p99 TTFT & ITL, and prints the ONE JSON
line the driver records.

Run on the real TPU chip (default) or CPU smoke mode:
    python bench.py [--requests N] [--concurrency N] [--cpu] [--model 1b|tiny]
"""

from __future__ import annotations

import argparse
import asyncio
import faulthandler
import json
import os
import signal
import statistics
import sys
import time

# kill -USR1 <pid> dumps every thread's stack to stderr — the first tool
# to reach for when a scenario wedges on the relay-attached chip
faulthandler.register(signal.SIGUSR1)


def _model_tag(args) -> str:
    dt = getattr(args, "dtype", "bf16")
    return args.model if dt == "bf16" else f"{args.model}-{dt}"


def metric_name(args) -> str:
    """The driver-facing metric label — built in ONE place so success and
    chip-unavailable records for the same invocation always match."""
    if getattr(args, "spec", False):
        smoke = ("cpu smoke" if getattr(args, "_cpu_smoke", False)
                 else "1 chip")
        return ("output tokens/s with speculative decoding, spec on/off "
                f"A/B on a repetitive workload (K={args.spec_tokens}, "
                f"ISL~{args.isl}/OSL {args.osl}, {args.requests} reqs, "
                f"{_model_tag(args)} llama, {smoke})")
    if getattr(args, "sweep", None):
        return ("output tokens/s, best of batch-geometry sweep "
                f"(ISL~{args.isl}/OSL {args.osl}, {_model_tag(args)} "
                "llama, 1 chip)")
    if args.scenario == "multiturn":
        tier = str(args.host_pages) + (
            "-int8" if getattr(args, "host_tier_int8", False) else "")
        return (f"TTFT p50 (later turns), multiturn {args.users}u x "
                f"{args.turns}t, host_pages={tier}")
    if args.scenario == "disagg":
        from dynamo_tpu.runtime.config import env_bool
        x8 = ", kv-int8" if env_bool("DYN_KV_TRANSFER_INT8") else ""
        ch = (f", kv-chunks {args.kv_chunk_pages}"
              if getattr(args, "kv_chunk_pages", None) else "")
        sp = (", shared-prefix A/B"
              if getattr(args, "shared_prefix", False) else "")
        return (f"disagg/agg req/s ratio (1-chip time-shared, threshold "
                f"{args.disagg_threshold}{x8}{ch}{sp})")
    if args.scenario == "sharded":
        smoke = "cpu smoke" if getattr(args, "cpu", False) else "chip"
        return (f"output tokens/s, {args.dp_replicas}x mesh-sharded "
                f"replicas ({getattr(args, 'mesh', None) or 'model=2'}) "
                f"behind the KV router vs one unsharded engine, identical "
                f"workload (ISL~{args.isl}/OSL {args.osl}, "
                f"{args.requests} reqs, {_model_tag(args)} llama, {smoke})")
    if args.scenario == "shared" and getattr(args, "cache_ab", False):
        smoke = "cpu smoke" if getattr(args, "cpu", False) else "1 chip"
        tier = str(args.host_pages) + (
            "-fp16" if getattr(args, "host_tier_fp16", False) else "-int8")
        return (f"realized hit rate + TTFT p95, dynaheat cache A/B "
                f"(arms: lru/serial control, cost-evict, overlap-restore, "
                f"cost+overlap; shared "
                f"{getattr(args, 'shared_shape', 'multi_tenant')}, "
                f"host_pages={tier}, {args.users}u x {args.turns}w, "
                f"{_model_tag(args)} llama, {smoke})")
    if args.scenario == "shared":
        smoke = "cpu smoke" if getattr(args, "cpu", False) else "1 chip"
        return (f"prefix-cache hit rate, shared-prefix workloads "
                f"({getattr(args, 'shared_shape', 'multi_tenant')}) through "
                f"the real HTTP->KV-router->engine stack "
                f"({args.users}u x {args.turns}w, {_model_tag(args)} "
                f"llama, {smoke})")
    if args.scenario == "failover":
        smoke = "cpu smoke" if getattr(args, "cpu", False) else "1 chip"
        return (f"goodput tok/s under mid-burst worker kill with "
                f"mid-stream failover (2 workers, ISL~{args.isl}/OSL "
                f"{args.osl}, {args.requests} reqs) + shed rate under 2x "
                f"overload ({_model_tag(args)} llama, {smoke})")
    if args.scenario == "hotpath":
        smoke = "cpu smoke" if getattr(args, "cpu", False) else "1 chip"
        arm = ("legacy" if getattr(args, "hotpath_legacy", False)
               else "overhauled")
        return (f"ITL raw-chunk p99 ms, decode-heavy hot path ({arm} arm, "
                f"ISL~{args.isl}/OSL {args.osl}, {args.requests} reqs, "
                f"conc {args.concurrency}, K={args.decode_steps}, "
                f"{_model_tag(args)} llama, {smoke})")
    return ("output tokens/s, synthetic ShareGPT "
            f"(ISL~{args.isl}/OSL {args.osl}, {args.requests} reqs, "
            f"conc {args.concurrency}, {_model_tag(args)} llama, 1 chip)")


def metric_unit(args) -> str:
    """Companion to metric_name(): the record's unit, with the same
    sweep-outranks-scenario precedence — ONE encoding of which record
    shape an invocation emits (success, sweep, and chip-unavailable
    paths all call this)."""
    if getattr(args, "spec", False) or getattr(args, "sweep", None):
        return "tok/s"
    return {"multiturn": "ms", "disagg": "ratio", "shared": "rate",
            "sharded": "tok/s", "failover": "tok/s",
            "hotpath": "ms"}.get(args.scenario, "tok/s")


def emit_unavailable(args, reason: str) -> None:
    """Print the ONE parseable JSON record the driver expects, flagging the
    chip as unavailable instead of dying with a stack trace (round-3 gate
    failure mode: BENCH_r03.json rc=1, parsed=null)."""
    print(json.dumps({
        "metric": metric_name(args),
        "value": None, "unit": metric_unit(args), "vs_baseline": None,
        "error": f"chip unavailable: {reason}",
    }))


def probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Initialize the JAX backend in a time-boxed SUBPROCESS first.

    On this testbed the TPU is reached through a relay tunnel that, when
    wedged, blocks backend init (and any later ``jax.devices()``) forever.
    A child process is the only way to bound that: if it hangs we stop it
    and report, instead of eating the driver's whole timeout in-process.
    The stop MUST be SIGTERM with a grace period — SIGKILLing a process
    mid-TPU-init is exactly what wedges the remote lease + relay for the
    rest of the session (round-3 incident)."""
    import subprocess

    code = ("import jax, json, sys;"
            "ds = jax.devices();"
            "print(json.dumps({'n': len(ds),"
            " 'platform': ds[0].platform}))")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()  # SIGTERM — never SIGKILL a chip-touching child
        try:
            proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            print("probe child ignored SIGTERM; leaving it to exit on its "
                  "own rather than SIGKILL-wedging the relay",
                  file=sys.stderr)
        return False, f"backend init exceeded {timeout_s:.0f}s (relay wedged?)"
    if proc.returncode != 0:
        tail = (err or "").strip().splitlines()
        return False, tail[-1][:300] if tail else f"probe rc={proc.returncode}"
    try:
        info = json.loads(out.strip().splitlines()[-1])
    except Exception:
        return False, f"unparseable probe output: {out[:200]!r}"
    if info.get("platform") == "cpu":
        # silent CPU fallback would publish a CPU number as the TPU headline
        return False, "probe found CPU-only backend (no TPU attached)"
    print(f"backend probe ok: {info}", file=sys.stderr)
    return True, ""


def arm_watchdog(args, budget_s: float):
    """Last-resort wall-clock bound: if the whole bench (compile included)
    overruns, emit the structured unavailable record and exit — the driver
    must always get a parseable line, even when the chip wedges mid-run.
    Returns the timer; cancel it once the real record has been printed.

    Exit is via self-SIGTERM (the one signal the chip relay tolerates —
    see memory/tpu-relay-gotchas); os._exit is only the fallback if the
    process survives the SIGTERM for 30s."""
    import threading

    def fire():
        emit_unavailable(args, f"bench exceeded {budget_s:.0f}s wall budget")
        sys.stdout.flush()
        faulthandler.dump_traceback(file=sys.stderr)
        threading.Timer(30, lambda: os._exit(3)).start()
        os.kill(os.getpid(), signal.SIGTERM)

    t = threading.Timer(budget_s, fire)
    t.daemon = True
    t.start()
    return t


def parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=32)
    ap.add_argument("--isl", type=int, default=512, help="mean input len")
    ap.add_argument("--osl", type=int, default=128, help="output len")
    ap.add_argument("--cpu", action="store_true", help="CPU smoke mode")
    ap.add_argument("--model", default="1b", choices=["1b", "8b", "tiny"])
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "int8"],
                    help="int8 = weight-only quantization (models/quant.py);"
                         " required for --model 8b on a 16 GB chip")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--decode-steps", type=int, default=16,
                    help="fused decode window (amortizes dispatch latency)")
    ap.add_argument("--scenario", default="sharegpt",
                    choices=["sharegpt", "multiturn", "disagg", "shared",
                             "sharded", "failover", "hotpath"],
                    help="multiturn = conversations with growing shared "
                         "prefixes (the KV-offload TTFT scenario, "
                         "reference docs/architecture.md:91-96); "
                         "disagg = A/B of disaggregated prefill/decode vs "
                         "aggregated on the same workload (the BASELINE.md "
                         "north-star, reference docs/architecture.md:57-61); "
                         "shared = dynacache shared-prefix workloads "
                         "driven through the REAL HTTP->KV-router->engine "
                         "stack, share vs no-share A/B per shape with the "
                         "router/engine/host-tier attribution breakdown; "
                         "sharded = dynashard A/B: an unsharded single "
                         "engine vs --dp-replicas mesh-sharded replicas "
                         "behind the real HTTP frontend + KV router at "
                         "identical workload (tok/s, mesh_shape, "
                         "per-replica device_time_fraction, compile "
                         "counts); "
                         "failover = dynarevive robustness bench: a "
                         "2-worker pool behind the KV router with one "
                         "worker killed mid-burst (goodput under churn + "
                         "resume-stall p99 via mid-stream failover) and a "
                         "2x-overload wave against SLO-aware admission "
                         "control (shed rate + admitted TTFT p99); "
                         "hotpath = dynaturbo decode hot-path record: "
                         "decode-heavy/small-batch/long-generation mix "
                         "reporting itl_raw_chunk_p99_ms + the per-bucket "
                         "cost table + loop-lag p99 + the compile fence "
                         "in ONE record (forces --prof-sample 2 when "
                         "unset); --hotpath-legacy runs the same workload "
                         "with every hot-path optimization off for A/B")
    ap.add_argument("--hotpath-legacy", action="store_true",
                    help="hotpath scenario A/B arm: disable the dynaturbo "
                         "optimizations (idle-prefill overlap, coalesced "
                         "window emissions, sampler-param cache, in-step "
                         "admission, async detok) and restore the legacy "
                         "per-iteration event-loop yield")
    ap.add_argument("--mesh", default=None,
                    help="sharded scenario: per-replica mesh as 'axis=N' "
                         "pairs (e.g. 'model=2'; default DYN_MESH_SHAPE "
                         "or model=2)")
    ap.add_argument("--dp-replicas", type=int, default=2,
                    help="sharded scenario: data-parallel replicas behind "
                         "the KV router")
    ap.add_argument("--shared-shape", default="multi_tenant",
                    choices=["multi_tenant", "rag", "agent", "all"],
                    help="shared scenario workload shape: multi_tenant = "
                         "per-tenant shared system prompts; rag = one long "
                         "common context + distinct questions; agent = "
                         "per-agent growing histories re-sent every turn; "
                         "all = run each in sequence")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="disagg scenario: add a shared-prefix leg (same "
                         "lengths, common 2/3-ISL prompt prefix) so the "
                         "transfer-vs-reuse interaction is measurable — "
                         "decode-side reservations prefix-hit and skip "
                         "transferring the shared pages")
    ap.add_argument("--disagg-threshold", type=int, default=256,
                    help="max local prefill length for the disagg router")
    ap.add_argument("--kv-chunk-pages", default=None,
                    help="disagg scenario: pages per streamed KV chunk "
                         "frame; 0 = legacy single bulk frame. Sweepable "
                         "as a comma list (e.g. '0,4,16') — each value is "
                         "measured as its own disagg leg against the same "
                         "engines, with a transfer-plane stage breakdown "
                         "(extract/compress/wire/inject) per leg")
    ap.add_argument("--prefill-token-budget", type=int, default=None,
                    help="chunked-prefill mixing: cap prefill tokens per "
                         "iteration, interleave decode windows")
    ap.add_argument("--host-pages", type=int, default=0,
                    help="host-DRAM offload tier size (multiturn scenario)")
    ap.add_argument("--host-tier-int8", action="store_true",
                    help="int8-compress the host tier: half the D2H/H2D "
                         "bytes per page move (lossy; "
                         "engine/kv_compress.py). Now the DEFAULT when "
                         "the tier is on — kept for invocation compat")
    ap.add_argument("--host-tier-fp16", action="store_true",
                    help="keep the host tier at pool precision (the "
                         "lossless fallback arm for the int8-default "
                         "A/B)")
    ap.add_argument("--evict-policy", default=None,
                    choices=["lru", "cost"],
                    help="KV eviction policy override for both cache "
                         "tiers (default: engine default = cost; lru is "
                         "the A/B control)")
    ap.add_argument("--restore-overlap", default=None,
                    choices=["on", "off"],
                    help="override the pipelined host-tier restore "
                         "drain (default: engine default = on; off is "
                         "the serial A/B control)")
    ap.add_argument("--cache-ab", action="store_true",
                    help="shared scenario: run the dynaheat four-arm "
                         "cache A/B — lru/serial control, cost-evict, "
                         "overlap-restore, cost+overlap — same workload "
                         "per arm, fresh engine each, HBM pool sized "
                         "below the working set so eviction policy "
                         "actually decides; quotes per-arm TTFT "
                         "p50/p95, realized hit rate, restore wait and "
                         "evict fate split")
    ap.add_argument("--users", type=int, default=16)
    ap.add_argument("--turns", type=int, default=4)
    ap.add_argument("--max-batch", type=int, default=None,
                    help="override engine max_batch (and batch buckets)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative-decoding A/B: run the headline "
                         "workload (made repetitive — the regime prompt-"
                         "lookup drafting targets) with spec_decode off "
                         "then on, report both tok/s plus acceptance "
                         "stats; degrades to a CPU smoke A/B when no "
                         "chip is available")
    ap.add_argument("--spec-tokens", type=int, default=4,
                    help="max draft tokens verified per step (K)")
    ap.add_argument("--prof-sample", type=int, default=0,
                    help="dynaprof: profile every Nth engine step with a "
                         "timed dispatch (device/host split + per-bucket "
                         "cost table in the report). 0 = off: the hot "
                         "path stays sync-free and the report's "
                         "device_time_fraction/bucket_cost stay empty")
    ap.add_argument("--trace", action="store_true",
                    help="dyntrace: record a trace per benched request "
                         "(sampling forced to 1.0) and dump a per-request "
                         "stage breakdown (route/prefill/kv_transfer/"
                         "decode span durations) plus a stage rollup "
                         "after the run")
    ap.add_argument("--trip-incident", action="store_true",
                    help="dynablack: after the workload finishes, trip a "
                         "manual flight-recorder capture in-process and "
                         "write the incident bundle next to --report-out "
                         "(<stem>.incident.json), recording id/workers "
                         "in the report's blackbox block — the chip-"
                         "session step that proves the armed recorder "
                         "produces a renderable bundle mid-bench")
    ap.add_argument("--report-out", default=None, metavar="PATH",
                    help="also write the full machine-readable record "
                         "(the BENCH_r*.json shape: metric/value/unit/"
                         "vs_baseline + the complete detail report, now "
                         "incl. the dynaslo goodput/per-role-quantile "
                         "block) to PATH, so every round lands in the "
                         "perf trajectory instead of living in stderr")
    ap.add_argument("--sweep", default=None,
                    help="batch-geometry sweep (VERDICT r3 task 3): comma-"
                         "separated conc:max_batch:decode_steps triples, "
                         "e.g. '32:64:4,64:64:8,128:128:16' — runs the "
                         "headline workload at each point, prints one "
                         "result line per point to stderr and a summary "
                         "table, then the best point's record as THE line")
    return ap.parse_args()


def engine_setup(args):
    """The bench engine-config assembly, shared by the single-engine
    build and the dynashard replica set: (model_cfg, engine_cfg, params,
    quant)."""
    from dynamo_tpu.engine.jax_engine import EngineConfig
    from dynamo_tpu.models.config import ModelConfig

    if args.model == "tiny":
        cfg = ModelConfig.tiny()
        ecfg = EngineConfig(page_size=16, num_pages=256, max_batch=16,
                            prefill_chunk=128, prefill_buckets=(128,),
                            batch_buckets=(4, 16), page_buckets=(16,),
                            decode_steps=args.decode_steps,
                            host_pages=args.host_pages)
    elif args.model == "8b":
        # Llama-3-8B-shaped — the size BASELINE.md's north-star metric is
        # defined at. bf16 weights (16 GB) exceed a v5e's HBM, so this
        # config requires --dtype int8 (~8 GB weights + scales).
        if args.dtype != "int8":
            raise SystemExit("--model 8b needs --dtype int8 on a 16 GB "
                             "chip (bf16 weights alone are 16 GB)")
        cfg = ModelConfig(vocab_size=128256, hidden_size=4096,
                          intermediate_size=14336, num_layers=32,
                          num_heads=32, num_kv_heads=8, head_dim=128,
                          rope_theta=500000.0, dtype="bfloat16")
        # KV: 2*32L*8KV*128hd*2B = 128 KB/token → 512 pages x 64 tok
        # = 32K cached tokens ≈ 4 GB; ~8 GB weights + ~4 GB KV leaves
        # headroom for decode-window transients on 16 GB
        ecfg = EngineConfig(page_size=64, num_pages=512, max_batch=16,
                            prefill_chunk=1024, prefill_buckets=(512, 1024),
                            batch_buckets=(8, 16), page_buckets=(16, 32),
                            decode_steps=args.decode_steps,
                            host_pages=args.host_pages)
    else:
        # Llama-3.2-1B-shaped: ~2.5 GB bf16 params + KV pool on one v5e chip
        cfg = ModelConfig(vocab_size=128256, hidden_size=2048,
                          intermediate_size=8192, num_layers=16,
                          num_heads=32, num_kv_heads=8, head_dim=64,
                          dtype="bfloat16")
        # KV pool: 1536 pages x 64 tok = 96K cached tokens (~3.2 GB);
        # headroom for the decode window's pool gather transients.
        # Two prefill T buckets + two page buckets: a 512-token prompt
        # pays 512x1024 attention instead of 1024x2048 (bucket-
        # homogeneous prefill batching keeps batches on their bucket)
        ecfg = EngineConfig(page_size=64, num_pages=1536, max_batch=32,
                            prefill_chunk=1024, prefill_buckets=(512, 1024),
                            batch_buckets=(8, 32), page_buckets=(16, 32),
                            decode_steps=args.decode_steps,
                            host_pages=args.host_pages)
    if args.max_batch:
        ecfg.max_batch = args.max_batch
        ecfg.batch_buckets = (8, args.max_batch)
    if getattr(args, "prof_sample", 0):
        ecfg.prof_sample = args.prof_sample
    if getattr(args, "_spec_on", False):
        ecfg.spec_decode = True
        ecfg.spec_tokens = args.spec_tokens
    if args.prefill_token_budget is not None:
        ecfg.prefill_token_budget = args.prefill_token_budget
    if getattr(args, "hotpath_legacy", False):
        # dynaturbo A/B "before" arm: every hot-path toggle off (the env
        # side — DYN_LOOP_YIELD / DYN_ASYNC_DETOK — is set in main()
        # before the engine loop starts)
        ecfg.overlap_idle_prefill = False
        ecfg.coalesce_window_emissions = False
        ecfg.cache_sampler_params = False
        ecfg.admit_in_step = False
    if args.scenario == "multiturn":
        # size the HBM pool BELOW the conversation working set so turns
        # evict each other; the host tier is what keeps TTFT low
        # (~10 pages/user HBM vs histories growing past 17 pages)
        ecfg.num_pages = min(ecfg.num_pages, 10 * args.users)
        ecfg.host_pages = args.host_pages
    if args.scenario == "shared" and args.host_pages:
        # dynaheat cache A/B: same pool-pressure setup — an HBM pool
        # below the working set makes the eviction policy (and the
        # host-tier restore pipeline) the thing being measured
        ecfg.num_pages = min(ecfg.num_pages, 10 * args.users)
        ecfg.host_pages = args.host_pages
    if args.host_tier_int8:
        ecfg.host_tier_int8 = True
    if getattr(args, "host_tier_fp16", False):
        ecfg.host_tier_int8 = False
    if getattr(args, "evict_policy", None):
        ecfg.evict_policy = args.evict_policy
    if getattr(args, "restore_overlap", None) is not None:
        ecfg.restore_overlap = args.restore_overlap == "on"
    params = None
    if args.model == "8b":
        # 8B Gaussian host-init costs minutes of single-core time the
        # chip session can't spare; throughput never reads the values —
        # synthesize the int8 tree instantly (models/quant.py)
        from dynamo_tpu.models import llama
        from dynamo_tpu.models.quant import synthetic_int8_params

        params = synthetic_int8_params(llama, cfg)
    quant = ("int8" if args.dtype == "int8" and params is None else None)
    return cfg, ecfg, params, quant


def build_engine(args):
    import jax

    from dynamo_tpu.engine.jax_engine import JaxEngine

    cfg, ecfg, params, quant = engine_setup(args)
    print(f"devices: {jax.devices()}", file=sys.stderr)
    engine = JaxEngine(cfg, ecfg, seed=args.seed, params=params,
                       quant=quant)
    return engine, cfg


def synth_requests(args, vocab: int, cap_tokens: int = 1 << 30):
    """ShareGPT-like synthetic prompts: lognormal input lengths, clipped
    to the engine's grid capacity (a deployment router rejects over-
    capacity prompts up front; letting them error-finish here would
    inflate req/s with zero-work requests)."""
    import numpy as np

    rng = np.random.RandomState(args.seed)
    hi = max(32, min(3072, cap_tokens - args.osl - 8))
    repetitive = getattr(args, "spec", False)
    reqs = []
    for i in range(args.requests):
        isl = int(np.clip(rng.lognormal(mean=np.log(args.isl), sigma=0.6),
                          32, hi))
        if repetitive:
            # --spec A/B: per-request repeated motif — the structured-
            # text regime prompt-lookup drafting targets (code, RAG
            # quotes, JSON); pure random tokens would measure only the
            # verify overhead
            motif = rng.randint(1, min(vocab - 10, 255), size=24).tolist()
            token_ids = (motif * (isl // len(motif) + 1))[:isl]
        else:
            token_ids = rng.randint(1, min(vocab - 10, 255),
                                    size=isl).tolist()
        reqs.append((token_ids, args.osl))
    return reqs


async def run_multiturn(args):
    """Multi-turn conversations with shared growing prefixes: each user
    alternates ~turns requests whose prompt = full history + new chunk.
    Measures per-turn TTFT; with --host-pages the evicted histories
    restore from the host tier instead of recomputing (reference KV
    offload '+40% TTFT', docs/architecture.md:91-96)."""
    import numpy as np

    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    engine, cfg = build_engine(args)
    print("warming up (compiling bucket grid)...", file=sys.stderr)
    engine.warmup()
    rng = np.random.RandomState(args.seed)
    histories = [rng.randint(1, 255, 512).tolist()
                 for _ in range(args.users)]
    ttfts = []

    errors = [0]

    async def one_turn(u):
        # histories grow ~256 tokens/turn; keep them inside the engine's
        # warmed-grid capacity (over-capacity prompts error-finish at
        # admission and would silently drop out of the TTFT sample)
        histories[u] = histories[u][-max(engine.cap_tokens - args.osl - 8,
                                         64):]
        req = PreprocessedRequest(
            token_ids=list(histories[u]), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=args.osl, ignore_eos=True),
            eos_token_ids=[])
        t0 = time.monotonic()
        first = None
        out_toks = []
        async for out in engine.generate(req, Context()):
            if out.token_ids and first is None:
                first = time.monotonic() - t0
            out_toks.extend(out.token_ids)
            if out.finish_reason:
                if out.finish_reason == "error":
                    errors[0] += 1
                break
        ttfts.append(first)
        histories[u] = histories[u] + out_toks + \
            rng.randint(1, 255, 128).tolist()

    bench_t0 = time.monotonic()
    for turn in range(args.turns):
        await asyncio.gather(*(one_turn(u) for u in range(args.users)))
        print(f"turn {turn + 1}/{args.turns} done", file=sys.stderr)
    wall = time.monotonic() - bench_t0
    await engine.stop()

    later = sorted(t for t in ttfts[args.users:] if t is not None)
    stats = engine.stats()
    report = {
        "scenario": "multiturn", "users": args.users, "turns": args.turns,
        "errors": errors[0],
        "host_pages": args.host_pages, "wall_s": round(wall, 2),
        "ttft_later_turns_p50_ms":
            round(later[len(later) // 2] * 1000, 1) if later else None,
        "prefix_hit_rate": round(stats["gpu_prefix_cache_hit_rate"], 4),
        "host_restores": stats["host_restore_pages_total"],
        "host_offloads": stats["host_offload_pages_total"],
        "post_warmup_compiles": stats["post_warmup_compiles_total"],
        "loop_lag_p99_ms": round(
            stats["loop_lag_p99_seconds"] * 1000, 2),
        "device_time_fraction": stats["device_time_fraction"],
        "bucket_cost": stats["bucket_cost"],
    }
    print(json.dumps(report), file=sys.stderr)
    return report


# ------------------------------------------------ dynacache shared-prefix


def _word_text(rng, nchars: int) -> str:
    """Deterministic filler text of ~nchars (byte tokenizer: 1 char =
    1 token) — the fleet/traffic.py word-soup idiom."""
    words = ("alpha bravo charlie delta echo foxtrot golf hotel india "
             "juliet kilo lima mike november oscar papa quebec romeo "
             "sierra tango uniform victor whiskey xray yankee zulu").split()
    out = []
    n = 0
    while n < nchars:
        w = words[rng.randint(0, len(words) - 1)]
        out.append(w)
        n += len(w) + 1
    return " ".join(out)[:nchars]


async def _shared_settle(publisher, kvr) -> None:
    """Between waves: flush the engine's stored-block events onto the bus,
    let the router's subscription drain them, refresh worker stats."""
    await publisher.flush()
    await asyncio.sleep(0.05)
    await kvr.scrape_once()


async def _shared_wave(http, port, reqs, osl: int, rows: list) -> dict:
    """Issue one wave of completions concurrently over the REAL HTTP
    frontend; returns {rid: completion_text} (agent histories grow by
    it). Each request pins its X-Request-Id so /v1/traces/{rid} can be
    joined afterwards."""
    import json as _json

    texts = {}

    async def one(rid, prompt):
        t0 = time.monotonic()
        first = None
        text = []
        async with http.post(
                f"http://127.0.0.1:{port}/v1/completions",
                json={"model": "bench", "prompt": prompt,
                      "stream": True, "max_tokens": osl},
                headers={"X-Request-Id": rid}) as resp:
            if resp.status != 200:
                rows.append({"rid": rid, "ttft": None, "error": True})
                return
            async for raw in resp.content:
                line = raw.strip()
                if line == b"data: [DONE]":
                    break
                if not line.startswith(b"data: "):
                    continue
                chunk = _json.loads(line[len(b"data: "):])
                for c in chunk.get("choices", []):
                    piece = c.get("text") or ""
                    if piece:
                        if first is None:
                            first = time.monotonic() - t0
                        text.append(piece)
        texts[rid] = "".join(text)
        rows.append({"rid": rid, "ttft": first, "error": False,
                     "e2e": time.monotonic() - t0})

    await asyncio.gather(*(one(rid, p) for rid, p in reqs))
    return texts


async def _run_shared_leg(args, shape: str, share: bool, http, port,
                          publisher, kvr, cap_tokens: int,
                          leg_tag: str) -> list:
    """One leg of a shape: waves of requests whose prompts share (or —
    the A/B control — do not share) prefixes. Returns the per-request
    rows; wave boundaries settle the event/stats planes so followers can
    actually route onto and hit the blocks the leaders committed."""
    import numpy as np

    rng = np.random.RandomState(args.seed ^ (0xCA if share else 0x5E))
    budget = max(cap_tokens - args.osl - 16, 96)
    prefix_chars = min(max(int(args.isl * 2 // 3), 48), int(budget * 0.6))
    suffix_chars = max(min(args.isl - prefix_chars, budget - prefix_chars
                           - 16), 8)
    rows: list = []
    n_req = 0

    def rid_for():
        nonlocal n_req
        n_req += 1
        return f"{leg_tag}-{n_req:04d}"

    if shape == "multi_tenant":
        # per-tenant shared system prompt; wave 0 seeds each tenant's
        # chain, later waves re-use it with unique question suffixes
        prefixes = {t: _word_text(rng, prefix_chars)
                    for t in range(args.users)}
        for wave in range(max(args.turns, 2)):
            reqs = []
            for t in range(args.users):
                prefix = (prefixes[t] if share
                          else _word_text(rng, prefix_chars))
                suffix = f" q{wave}: " + _word_text(rng, suffix_chars)
                reqs.append((rid_for(), prefix + suffix))
            await _shared_wave(http, port, reqs, args.osl, rows)
            await _shared_settle(publisher, kvr)
    elif shape == "rag":
        # one long common context; wave 0 = a single seeding question,
        # then concurrent distinct questions over the same context
        context = _word_text(rng, prefix_chars)
        seed_req = [(rid_for(),
                     (context if share else _word_text(rng, prefix_chars))
                     + " q0: " + _word_text(rng, suffix_chars))]
        await _shared_wave(http, port, seed_req, args.osl, rows)
        await _shared_settle(publisher, kvr)
        for wave in range(1, max(args.turns, 2)):
            reqs = []
            for u in range(args.users):
                ctx = context if share else _word_text(rng, prefix_chars)
                reqs.append((rid_for(), ctx + f" q{wave}.{u}: "
                             + _word_text(rng, suffix_chars)))
            await _shared_wave(http, port, reqs, args.osl, rows)
            await _shared_settle(publisher, kvr)
    elif shape == "agent":
        # agent loop: each turn re-sends the full growing history (prior
        # prompt + the model's own answer + a new instruction)
        histories = {a: _word_text(rng, prefix_chars)
                     for a in range(args.users)}
        for turn in range(max(args.turns, 2)):
            reqs = []
            rid_by_agent = {}
            for a in range(args.users):
                if not share:
                    # control: same lengths, no reuse across turns
                    histories[a] = _word_text(rng, len(histories[a]))
                if len(histories[a]) + args.osl + 24 > budget:
                    continue  # history hit the warmed-grid capacity
                prompt = histories[a] + f" step{turn}: " \
                    + _word_text(rng, 16)
                rid = rid_for()
                rid_by_agent[a] = (rid, prompt)
                reqs.append((rid, prompt))
            if not reqs:
                break
            texts = await _shared_wave(http, port, reqs, args.osl, rows)
            for a, (rid, prompt) in rid_by_agent.items():
                histories[a] = prompt + texts.get(rid, "")
            await _shared_settle(publisher, kvr)
    else:
        raise ValueError(f"unknown shared shape {shape!r}")
    return rows


async def _shared_cost_split(http, port, rows) -> dict:
    """Join the per-request cost blocks from /v1/traces/{rid}: the
    router-predicted vs engine-realized vs host-tier attribution
    breakdown summed over the leg."""
    split = {"requests_with_cost": 0, "prompt_blocks": 0,
             "router_overlap_blocks": 0, "device_hit_blocks": 0,
             "host_restored_blocks": 0, "fresh_blocks": 0,
             "restore_wait_ms": 0.0}
    for row in rows:
        if row.get("error"):
            continue
        async with http.get(
                f"http://127.0.0.1:{port}/v1/traces/{row['rid']}") as resp:
            if resp.status != 200:
                continue
            cost = (await resp.json()).get("cost")
        if not cost or "device_hit_blocks" not in cost:
            continue
        split["requests_with_cost"] += 1
        pb = int(cost.get("prompt_blocks", 0))
        dh = int(cost.get("device_hit_blocks", 0))
        hr = int(cost.get("host_restored_blocks", 0))
        split["prompt_blocks"] += pb
        split["router_overlap_blocks"] += int(
            cost.get("router_overlap_blocks", 0))
        split["device_hit_blocks"] += dh
        split["host_restored_blocks"] += hr
        split["fresh_blocks"] += pb - dh - hr
        split["restore_wait_ms"] += float(cost.get("restore_wait_ms", 0.0))
    split["restore_wait_ms"] = round(split["restore_wait_ms"], 3)
    return split


async def run_shared(args):
    """dynacache tentpole workloads: shared-prefix traffic driven through
    the REAL stack (aiohttp -> HttpService -> Processor -> KvRouter ->
    token worker -> JaxEngine), each shape A/B'd against a no-sharing
    control of identical lengths. The report quotes, per shape:
    the engine prefix hit rate (windowed counters delta), the TTFT delta
    vs no-sharing, and the router-predicted vs engine-realized vs
    host-restored attribution breakdown from the per-request cost
    blocks."""
    import aiohttp

    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.processor import Processor
    from dynamo_tpu.llm.worker import serve_token_model
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    engine, cfg = build_engine(args)
    print("warming up (compiling bucket grid)...", file=sys.stderr)
    engine.warmup()

    drt = await DistributedRuntime.detached()
    service = None
    kvr = None
    token_client = None
    publisher = None
    try:
        mdc = ModelDeploymentCard(name="bench", tokenizer_kind="byte",
                                  kv_block_size=engine.ecfg.page_size,
                                  model_type="completions")
        _handle, publisher = await serve_token_model(
            drt, mdc, engine, namespace="bench", component="w")
        kvr = KvRouter(drt, "bench", "w",
                       block_size=engine.ecfg.page_size, seed=args.seed)
        await kvr.start(run_loop=False)
        await kvr.scrape_once()
        token_client = await drt.namespace("bench").component("w") \
            .endpoint("generate_tokens").client()
        processor = Processor(mdc, token_client, kvr)
        service = HttpService()
        service.manager.add_completions_model("bench",
                                              processor.completion)
        await service.start(host="127.0.0.1", port=0)

        shapes = (["multi_tenant", "rag", "agent"]
                  if args.shared_shape == "all" else [args.shared_shape])
        report = {"scenario": "shared_prefix", "users": args.users,
                  "waves": args.turns, "shapes": {}}
        agg_hits = agg_prompts = 0
        ttft_ratios = []
        share_ttfts: list = []  # share-leg TTFTs (the cache-sensitive arm)
        all_rows: list = []    # every leg's request rows (dynaslo goodput)
        async with aiohttp.ClientSession() as http:
            for shape in shapes:
                legs = {}
                # no-share control FIRST: its unique junk cannot be hit
                # by the shared leg, the shared leg's blocks can
                for share in (False, True):
                    tag = f"{shape}-{'sh' if share else 'no'}"
                    st0 = engine.stats()
                    r0 = kvr.stats()
                    rows = await _run_shared_leg(
                        args, shape, share, http, service.port,
                        publisher, kvr, engine.cap_tokens, tag)
                    st1 = engine.stats()
                    r1 = kvr.stats()
                    all_rows.extend(rows)
                    hits = (st1["prefix_hit_tokens_total"]
                            - st0["prefix_hit_tokens_total"])
                    prompts = (st1["prompt_tokens_total"]
                               - st0["prompt_tokens_total"])
                    ttfts = sorted(r["ttft"] for r in rows
                                   if r.get("ttft") is not None)
                    leg = {
                        "requests": len(rows),
                        "errors": sum(1 for r in rows if r.get("error")),
                        "ttft_p50_ms": (round(
                            ttfts[len(ttfts) // 2] * 1000, 1)
                            if ttfts else None),
                        "ttft_p95_ms": (round(
                            ttfts[min(int(len(ttfts) * 0.95),
                                      len(ttfts) - 1)] * 1000, 1)
                            if ttfts else None),
                        "prefix_hit_rate": round(hits / max(prompts, 1),
                                                 4),
                        "prefix_hit_tokens": hits,
                        "prompt_tokens": prompts,
                        "device_hit_blocks": (
                            st1["cache_device_hit_blocks_total"]
                            - st0["cache_device_hit_blocks_total"]),
                        "host_restored_blocks": (
                            st1["cache_host_restored_blocks_total"]
                            - st0["cache_host_restored_blocks_total"]),
                        "fresh_blocks": (
                            st1["cache_fresh_blocks_total"]
                            - st0["cache_fresh_blocks_total"]),
                        "restore_wait_s": round(
                            st1["cache_restore_wait_seconds_total"]
                            - st0["cache_restore_wait_seconds_total"], 4),
                        "router_predicted_blocks": (
                            r1["calibration"]["predicted_blocks_total"]
                            - r0["calibration"]["predicted_blocks_total"]),
                        "router_realized_blocks": (
                            r1["calibration"]["realized_blocks_total"]
                            - r0["calibration"]["realized_blocks_total"]),
                        "cost_split": await _shared_cost_split(
                            http, service.port, rows),
                    }
                    legs["share" if share else "noshare"] = leg
                    if share:
                        agg_hits += hits
                        agg_prompts += prompts
                        share_ttfts.extend(r["ttft"] for r in rows
                                           if r.get("ttft") is not None)
                entry = dict(legs)
                if (legs["share"]["ttft_p50_ms"]
                        and legs["noshare"]["ttft_p50_ms"]):
                    entry["ttft_delta_ms"] = round(
                        legs["noshare"]["ttft_p50_ms"]
                        - legs["share"]["ttft_p50_ms"], 1)
                    ttft_ratios.append(legs["noshare"]["ttft_p50_ms"]
                                       / max(legs["share"]["ttft_p50_ms"],
                                             1e-9))
                report["shapes"][shape] = entry
                print(json.dumps({shape: entry}), file=sys.stderr)
        st = engine.stats()
        report["prefix_hit_rate"] = round(agg_hits / max(agg_prompts, 1),
                                          4)
        report["hit_rate_windowed"] = round(
            st["gpu_prefix_cache_hit_rate"], 4)
        report["calibration"] = kvr.stats()["calibration"]
        report["post_warmup_compiles"] = st["post_warmup_compiles_total"]
        report["host_restores"] = st["host_restore_pages_total"]
        report["host_offloads"] = st["host_offload_pages_total"]
        report["ttft_noshare_over_share"] = (
            round(sum(ttft_ratios) / len(ttft_ratios), 3)
            if ttft_ratios else None)
        # dynaslo: goodput + per-role quantiles from the engine's merged
        # latency histograms (every wave's request rows judged)
        report["slo"] = _slo_block([st], all_rows)
        # dynaheat flat cache keys: the per-toggle A/B driver and
        # tools/cost_diff.py read these top-level (share-leg TTFT, the
        # lifecycle counters, and the arm's toggle settings)
        sorted_tt = sorted(share_ttfts)
        report["ttft_p50_ms"] = (round(
            sorted_tt[len(sorted_tt) // 2] * 1000, 1) if sorted_tt else None)
        report["ttft_p95_ms"] = (round(
            sorted_tt[min(int(len(sorted_tt) * 0.95),
                          len(sorted_tt) - 1)] * 1000, 1)
            if sorted_tt else None)
        report["restore_wait_ms"] = round(
            st["cache_restore_wait_seconds_total"] * 1000, 2)
        report["device_hit_blocks"] = st["cache_device_hit_blocks_total"]
        report["host_restored_blocks"] = st["cache_host_restored_blocks_total"]
        report["fresh_blocks"] = st["cache_fresh_blocks_total"]
        report["evict_offloaded_total"] = st["cache_evict_offloaded_total"]
        report["evict_dropped_total"] = st["cache_evict_dropped_total"]
        report["host_evictions_total"] = st["cache_host_evictions_total"]
        report["restore_batch_pages_mean"] = round(
            st["cache_restore_batch_pages_total"]
            / max(st["cache_restore_batches_total"], 1), 2)
        report["evict_policy"] = engine.ecfg.evict_policy
        report["restore_overlap"] = bool(engine.ecfg.restore_overlap)
        report["host_tier_int8"] = bool(engine.ecfg.host_tier_int8)
        report["router_load_balance_weight"] = \
            kvr.stats()["load_balance_weight"]
        print(json.dumps(report), file=sys.stderr)
        return report
    finally:
        if service is not None:
            await service.stop()
        if kvr is not None:
            await kvr.stop()
        if token_client is not None:
            await token_client.close()
        if publisher is not None:
            await publisher.stop()
        await engine.stop()
        await drt.shutdown()


# dynaheat per-toggle A/B: the SAME shared-prefix workload (same seed,
# same shapes, same pool pressure) re-run once per arm with a fresh
# engine, so every cache change is quoted against the lru/serial
# control it replaced rather than against a different traffic mix.
_CACHE_AB_ARMS = (
    # name            evict_policy  restore_overlap
    ("control",        "lru",       "off"),   # pre-dynaheat behavior
    ("cost_evict",     "cost",      "off"),
    ("overlap_restore", "lru",      "on"),
    ("cost_overlap",   "cost",      "on"),    # dynaheat defaults
)

_CACHE_AB_ARM_KEYS = (
    "prefix_hit_rate", "hit_rate_windowed", "ttft_p50_ms", "ttft_p95_ms",
    "restore_wait_ms", "restore_batch_pages_mean",
    "device_hit_blocks", "host_restored_blocks", "fresh_blocks",
    "evict_offloaded_total", "evict_dropped_total", "host_evictions_total",
    "post_warmup_compiles", "evict_policy", "restore_overlap",
    "host_tier_int8", "router_load_balance_weight",
)


def run_shared_cache_ab(args) -> dict:
    """Four-arm cache A/B (--cache-ab): lru/serial control, cost-aware
    eviction alone, overlapped restores alone, and both together. Value
    is the combined arm's realized prefix hit rate; vs_baseline is the
    control-over-combined TTFT-p95 ratio (>1 = dynaheat is faster)."""
    import copy

    if not args.host_pages:
        # the A/B is ABOUT the two-tier cache — without a host tier the
        # eviction policy only picks drop victims and restores never run
        args.host_pages = 16 * args.users
    arms = {}
    for name, policy, overlap in _CACHE_AB_ARMS:
        a = copy.copy(args)
        a.evict_policy = policy
        a.restore_overlap = overlap
        print(f"=== cache A/B arm {name}: evict={policy}, "
              f"restore_overlap={overlap} ===", file=sys.stderr)
        rep = asyncio.run(run_shared(a))
        arms[name] = {k: rep.get(k) for k in _CACHE_AB_ARM_KEYS}
    ctrl, best = arms["control"], arms["cost_overlap"]
    detail = {"scenario": "shared_cache_ab", "users": args.users,
              "waves": args.turns, "host_pages": args.host_pages,
              "host_tier_int8": best["host_tier_int8"],
              "arms": arms}
    for name, rep in arms.items():
        if name == "control":
            continue
        d = {}
        if ctrl["ttft_p95_ms"] and rep["ttft_p95_ms"]:
            d["ttft_p95_control_over_arm"] = round(
                ctrl["ttft_p95_ms"] / rep["ttft_p95_ms"], 3)
        d["hit_rate_delta"] = round(
            rep["prefix_hit_rate"] - ctrl["prefix_hit_rate"], 4)
        d["restore_wait_ms_delta"] = round(
            rep["restore_wait_ms"] - ctrl["restore_wait_ms"], 2)
        detail[f"{name}_vs_control"] = d
    vs = (round(ctrl["ttft_p95_ms"] / best["ttft_p95_ms"], 3)
          if ctrl["ttft_p95_ms"] and best["ttft_p95_ms"] else 1.0)
    return {"metric": metric_name(args),
            "value": best["prefix_hit_rate"],
            "unit": metric_unit(args), "vs_baseline": vs,
            "detail": detail}


# --------------------------------------------------- dynashard sharded A/B


async def _sharded_leg(args, tag, prompts, *, token_counts, http, port):
    """Drive the identical workload through one leg's HTTP frontend;
    returns {wall_s, output_tok_per_s, ttft_p50_ms, requests, errors}.
    Output tokens are counted ENGINE-side (decode_tokens_total delta +
    one first token per request) so both legs use the same ruler."""
    import json as _json

    before = [f() for f in token_counts]
    rows: list = []
    sem = asyncio.Semaphore(args.concurrency)

    async def one(i, prompt):
        async with sem:
            t0 = time.monotonic()
            first = None
            async with http.post(
                    f"http://127.0.0.1:{port}/v1/completions",
                    json={"model": "bench", "prompt": prompt,
                          "stream": True, "max_tokens": args.osl},
                    headers={"X-Request-Id": f"{tag}-{i:04d}"}) as resp:
                if resp.status != 200:
                    rows.append({"ttft": None, "error": True})
                    return
                async for raw in resp.content:
                    line = raw.strip()
                    if line == b"data: [DONE]":
                        break
                    if not line.startswith(b"data: "):
                        continue
                    chunk = _json.loads(line[len(b"data: "):])
                    if first is None and any(
                            (c.get("text") or "")
                            for c in chunk.get("choices", [])):
                        first = time.monotonic() - t0
            rows.append({"ttft": first, "error": False})

    t0 = time.monotonic()
    await asyncio.gather(*(one(i, p) for i, p in enumerate(prompts)))
    wall = time.monotonic() - t0
    after = [f() for f in token_counts]
    ok = [r for r in rows if not r["error"]]
    out_toks = sum(a - b for a, b in zip(after, before)) + len(ok)
    ttfts = sorted(r["ttft"] for r in ok if r["ttft"] is not None)
    return {
        "requests": len(rows),
        "errors": sum(1 for r in rows if r["error"]),
        "wall_s": round(wall, 3),
        "output_tok_per_s": round(out_toks / wall, 1) if wall else 0.0,
        "ttft_p50_ms": (round(ttfts[len(ttfts) // 2] * 1000, 1)
                        if ttfts else None),
    }


async def run_sharded(args):
    """dynashard tentpole A/B: the SAME workload served by (a) one
    unsharded engine and (b) --dp-replicas mesh-sharded engine replicas
    on partitioned submeshes — both behind the real aiohttp → HttpService
    → Processor → KvRouter → generate_tokens stack. Reports tok/s per
    leg, the mesh shape, per-replica device_time_fraction and compile
    counts (the compile fence must hold under sharding: 0 per replica)."""
    import aiohttp
    import jax
    import numpy as np

    from dynamo_tpu.engine.jax_engine import JaxEngine
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.processor import Processor
    from dynamo_tpu.llm.worker import serve_token_model
    from dynamo_tpu.parallel.serving import (devices_per_replica,
                                             parse_mesh_shape,
                                             ShardedReplicaSet)
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    axes = parse_mesh_shape(args.mesh or env_str_cfg("DYN_MESH_SHAPE")
                            or "model=2")
    replicas = max(args.dp_replicas, 1)
    need = devices_per_replica(axes) * replicas
    if len(jax.devices()) < need:
        raise RuntimeError(
            f"sharded A/B needs {need} devices "
            f"({replicas} x {axes}), have {len(jax.devices())} — on CPU "
            f"set DYN_FORCE_HOST_DEVICES (bench --cpu defaults it to 8)")
    cfg, ecfg, params, quant = engine_setup(args)

    rng = np.random.RandomState(args.seed)
    cap = min(ecfg.page_buckets[-1] * ecfg.page_size, 1 << 30)
    budget = max(cap - args.osl - 16, 64)
    prompts = [_word_text(rng, min(max(args.isl + int(v), 32), budget))
               for v in rng.randint(-args.isl // 4, args.isl // 4 + 1,
                                    size=args.requests)]
    mdc = ModelDeploymentCard(name="bench", tokenizer_kind="byte",
                              kv_block_size=ecfg.page_size,
                              model_type="completions")

    async def leg(tag, start_leg):
        drt = await DistributedRuntime.detached()
        service = kvr = token_client = None
        try:
            token_counts, compiles, extra, stop_leg = await start_leg(drt)
            kvr = KvRouter(drt, "bench", tag,
                           block_size=ecfg.page_size, seed=args.seed)
            await kvr.start(run_loop=False)
            await kvr.scrape_once()
            token_client = await drt.namespace("bench").component(tag) \
                .endpoint("generate_tokens").client()
            processor = Processor(mdc, token_client, kvr)
            service = HttpService()
            service.manager.add_completions_model("bench",
                                                  processor.completion)
            await service.start(host="127.0.0.1", port=0)
            async with aiohttp.ClientSession() as http:
                rep = await _sharded_leg(args, tag, prompts,
                                         token_counts=token_counts,
                                         http=http, port=service.port)
            rep["post_warmup_compiles"] = compiles()
            rep.update(extra())
            print(json.dumps({tag: rep}), file=sys.stderr)
            return rep
        finally:
            if service is not None:
                await service.stop()
            if kvr is not None:
                await kvr.stop()
            if token_client is not None:
                await token_client.close()
            try:
                await stop_leg()
            except UnboundLocalError:
                pass
            await drt.shutdown()

    async def start_unsharded(drt):
        engine = JaxEngine(cfg, ecfg, seed=args.seed, params=params,
                           quant=quant)
        print("warming up unsharded engine...", file=sys.stderr)
        await asyncio.to_thread(engine.warmup)
        _handle, publisher = await serve_token_model(
            drt, mdc, engine, namespace="bench", component="agg")

        async def stop():
            await publisher.stop()
            await engine.stop()

        return ([lambda: engine.decode_tokens_total],
                lambda: engine.fence.post_warmup_compiles,
                lambda: {"device_time_fraction":
                         round(engine.profiler.device_time_fraction(), 4),
                         "mesh_shape": "single"},
                stop)

    async def start_sharded(drt):
        rs = ShardedReplicaSet(
            cfg, ecfg, mesh_axes=axes, replicas=replicas,
            namespace="bench", component="sharded", mdc=mdc,
            dcp_address=drt.dcp.address, params=params, seed=args.seed,
            quant=quant)
        print(f"warming up {replicas} sharded replicas "
              f"(mesh {rs.mesh_shape})...", file=sys.stderr)
        await rs.start()

        def extra():
            return {
                "mesh_shape": rs.mesh_shape,
                "sharding": rs.describe(),
                "per_replica_device_time_fraction":
                    rs.device_time_fractions(),
                "per_replica_compiles": rs.post_warmup_compiles(),
                "per_replica_decode_tokens": {
                    r.name: r.engine.decode_tokens_total
                    for r in rs.replicas},
            }

        return ([lambda r=r: r.engine.decode_tokens_total
                 for r in rs.replicas],
                lambda: sum(rs.post_warmup_compiles().values()),
                extra, rs.stop)

    unsharded = await leg("agg", start_unsharded)
    sharded = await leg("sharded", start_sharded)
    report = {
        "scenario": "sharded_vs_unsharded",
        "mesh_shape": sharded.get("mesh_shape"),
        "dp_replicas": replicas,
        "unsharded": unsharded,
        "sharded": sharded,
        "sharded_over_unsharded_tok_per_s": round(
            sharded["output_tok_per_s"]
            / max(unsharded["output_tok_per_s"], 1e-9), 3),
        "post_warmup_compiles": (unsharded["post_warmup_compiles"]
                                 + sharded["post_warmup_compiles"]),
    }
    print(json.dumps(report), file=sys.stderr)
    return report


def _pctile(vals, q):
    """Deterministic nearest-rank percentile; None on empty (the one
    shared implementation in runtime/slo.py — dynaslo)."""
    from dynamo_tpu.runtime.slo import nearest_rank

    return nearest_rank(list(vals), q)


# default CPU-smoke objectives for the bench goodput block when no
# DYN_SLO_OBJECTIVES is set: generous enough that a healthy smoke run
# scores goodput 1.0 and any wedge/regression scores below it (chip runs
# set real targets via the env registry)
_BENCH_DEFAULT_SLO = "ttft<=30@0.95/600;e2e<=120@0.95/600"


def _slo_block(stats_list, request_rows=None):
    """dynaslo bench block: per-role latency quantiles from the workers'
    MERGED histograms (the same mergeable-histogram plane the metrics
    aggregator renders) + per-request goodput against the registered
    (or default CPU-smoke) objectives."""
    from dynamo_tpu.runtime import slo as _slo

    merged = _slo.merge_latency_wire(
        [s.get("latency_hist") or {} for s in stats_list])
    per_role = {
        role: {metric: {"p50_ms": round(h.quantile(0.5) * 1000, 3),
                        "p95_ms": round(h.quantile(0.95) * 1000, 3),
                        "p99_ms": round(h.quantile(0.99) * 1000, 3),
                        "count": h.count}
               for metric, h in sorted(per.items()) if h.count}
        for role, per in sorted(merged.items())}
    reg = _slo.SloRegistry.from_env()
    if not reg.objectives:
        reg = _slo.SloRegistry.parse(_BENCH_DEFAULT_SLO)
    gp = _slo.GoodputTracker(reg)
    for r in request_rows or []:
        if r.get("error") or r.get("shed"):
            gp.observe_failed()
            continue
        metrics = {k: r[k] for k in ("ttft", "itl", "e2e")
                   if r.get(k) is not None}
        gp.observe_request(metrics)
    return {
        "objectives": [o.to_dict() for o in reg.objectives],
        "goodput": gp.snapshot(),
        "per_role_quantiles": per_role,
    }


async def run_failover(args):
    """dynarevive robustness bench: two workers behind the real
    aiohttp → HttpService → Processor → KvRouter → generate_tokens
    stack. Phase 1 (churn): one worker is killed mid-burst — mid-stream
    failover must resume its streams on the sibling with zero client
    errors; reports goodput under churn and resume-stall p99 (the
    client-visible gap the failover inserts). Phase 2 (overload): 2x the
    surviving capacity is thrown at the frontend with SLO-aware
    admission control on; reports shed rate and admitted-TTFT p99 (the
    point of shedding: the requests we DO admit stay fast)."""
    import aiohttp
    import json as _json
    import random as _random

    import numpy as np

    from dynamo_tpu.engine.jax_engine import JaxEngine
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.processor import Processor
    from dynamo_tpu.llm.worker import serve_token_model
    from dynamo_tpu.runtime import profiling, revive
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    cfg, ecfg, params, quant = engine_setup(args)
    rng = np.random.RandomState(args.seed)
    cap = ecfg.page_buckets[-1] * ecfg.page_size
    # the resume prompt is prompt + emitted: keep isl + osl inside the
    # warmed grid so failover never trips the compile fence
    isl = max(min(args.isl, cap - 2 * args.osl - 16), 32)
    prompts = [_word_text(rng, isl) for _ in range(args.requests)]
    mdc = ModelDeploymentCard(name="bench", tokenizer_kind="byte",
                              kv_block_size=ecfg.page_size,
                              model_type="completions")

    drt = await DistributedRuntime.detached()
    drt2 = await DistributedRuntime.attach(drt.dcp.address)
    engines, handles, pubs = [], [], []
    service = kvr = token_client = admission = None
    try:
        for i, d in enumerate((drt, drt2)):
            # same seed → identical weights on both workers (the greedy
            # resume token-identity contract needs sibling equivalence)
            eng = JaxEngine(cfg, ecfg, seed=args.seed, params=params,
                            quant=quant, worker_label=f"w{i}")
            print(f"warming up worker {i}...", file=sys.stderr)
            # the compile fence is process-global: mask the already-armed
            # siblings while this worker warms up (the dynashard join
            # idiom) so per-worker compile counts stay meaningful
            live_fences = [e.fence for e in engines]
            for f in live_fences:
                f.disarm()
            try:
                await asyncio.to_thread(eng.warmup)
            finally:
                for f in live_fences:
                    f.arm()
            handle, pub = await serve_token_model(
                d, mdc, eng, namespace="bench", component="fo")
            engines.append(eng)
            handles.append(handle)
            pubs.append(pub)
        # production shape: the scrape loop runs, so the dead worker
        # drops out of the scheduler and optimistic slot accounting
        # resets as real occupancy comes back
        kvr = KvRouter(drt, "bench", "fo", block_size=ecfg.page_size,
                       scrape_interval=0.25, seed=args.seed)
        await kvr.start(run_loop=True)
        await kvr.scrape_once()
        token_client = await drt.namespace("bench").component("fo") \
            .endpoint("generate_tokens").client()
        processor = Processor(mdc, token_client, kvr)

        def signals():
            live = [e.stats() for e in engines if not e.draining]
            if not live:
                return revive.LoadSignals()
            return revive.LoadSignals(
                queue_depth=sum(s["num_requests_waiting"] for s in live),
                workers=len(live),
                loop_lag_p99_ms=max(s["loop_lag_p99_seconds"]
                                    for s in live) * 1000.0,
                kv_free_blocks=min(s["kv_free_blocks"] for s in live))

        admission = revive.AdmissionController(
            signals,
            cfg=revive.ShedConfig(
                queue_depth=max(ecfg.max_batch // 4, 2)),
            rng=_random.Random(args.seed))
        service = HttpService()  # churn phase: no shedding
        service.manager.add_completions_model("bench",
                                              processor.completion)
        await service.start(host="127.0.0.1", port=0)

        async def one(http, i, prompt, rows, tag, osl):
            rid = f"{tag}-{i:04d}"
            t0 = time.monotonic()
            first = last = None
            max_gap = 0.0
            chars = 0
            errored = False
            async with http.post(
                    f"http://127.0.0.1:{service.port}/v1/completions",
                    json={"model": "bench", "prompt": prompt,
                          "stream": True, "max_tokens": osl},
                    headers={"X-Request-Id": rid}) as resp:
                if resp.status == 503:
                    rows.append({"rid": rid, "shed": True, "error": False,
                                 "ttft": None, "max_gap": 0.0, "chars": 0})
                    return
                if resp.status != 200:
                    rows.append({"rid": rid, "shed": False, "error": True,
                                 "ttft": None, "max_gap": 0.0, "chars": 0})
                    return
                async for raw in resp.content:
                    line = raw.strip()
                    if line == b"data: [DONE]":
                        break
                    if line.startswith(b"event: error"):
                        errored = True
                        continue
                    if not line.startswith(b"data: "):
                        continue
                    chunk = _json.loads(line[len(b"data: "):])
                    piece = "".join(c.get("text") or ""
                                    for c in chunk.get("choices", []))
                    if piece:
                        now = time.monotonic()
                        if first is None:
                            first = now - t0
                        elif last is not None:
                            max_gap = max(max_gap, now - last)
                        last = now
                        chars += len(piece)  # byte tokenizer: chars==tokens
            rows.append({"rid": rid, "shed": False, "error": errored,
                         "ttft": first, "max_gap": max_gap,
                         "chars": chars,
                         "e2e": time.monotonic() - t0})

        # ---------------------------------------- phase 1: churn (kill)
        resumed_before = revive.journal().resumed_total
        rows1: list = []
        killed = []

        async def killer():
            # wait for the victim to be loaded and mid-decode, then die
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if handles[0].inflight > 0 and \
                        engines[0].decode_tokens_total >= args.osl:
                    await handles[0].die()
                    engines[0].draining = True  # capacity is gone for real
                    killed.append(time.monotonic())
                    return
                await asyncio.sleep(0.005)

        async with aiohttp.ClientSession() as http:
            t0 = time.monotonic()
            ktask = asyncio.ensure_future(killer())
            await asyncio.gather(*(one(http, i, p, rows1, "churn",
                                       args.osl)
                                   for i, p in enumerate(prompts)))
            wall1 = time.monotonic() - t0
            await ktask

            resumed_rows = []
            for r in rows1:
                cost = profiling.request_attribution(r["rid"]) or {}
                if cost.get("resumed_attempts"):
                    resumed_rows.append(r)
            ok1 = [r for r in rows1 if not r["error"] and not r["shed"]]
            churn = {
                "requests": len(rows1),
                "completed": len(ok1),
                "errors": sum(1 for r in rows1 if r["error"]),
                "worker_killed": bool(killed),
                "resumed": len(resumed_rows),
                "goodput_tok_per_s": round(
                    sum(r["chars"] for r in ok1) / wall1, 1)
                if wall1 else 0.0,
                "resume_stall_p99_ms": (round(_pctile(
                    [r["max_gap"] for r in resumed_rows], 99) * 1000, 1)
                    if resumed_rows else None),
                "ttft_p99_ms": (round(_pctile(
                    [r["ttft"] for r in ok1 if r["ttft"] is not None],
                    99) * 1000, 1) if ok1 else None),
            }
            print(_json.dumps({"churn": churn}), file=sys.stderr)

            # ------------------------------- phase 2: 2x overload, shed
            # sustained 2x the survivor's slot capacity in flight (not
            # one instantaneous burst): later arrivals see the queues the
            # earlier ones built, which is what the shed signals read
            service.set_admission(admission)
            admission.start(0.02)  # peak-hold sampler between arrivals
            n2 = 4 * ecfg.max_batch
            sem2 = asyncio.Semaphore(2 * ecfg.max_batch)
            prompts2 = [_word_text(rng, isl) for _ in range(n2)]
            rows2: list = []
            osl2 = max(args.osl // 2, 8)

            async def over(i, p):
                async with sem2:
                    await one(http, i, p, rows2, "over", osl2)

            t0 = time.monotonic()
            await asyncio.gather(*(over(i, p)
                                   for i, p in enumerate(prompts2)))
            wall2 = time.monotonic() - t0
            shed = [r for r in rows2 if r["shed"]]
            admitted = [r for r in rows2
                        if not r["shed"] and not r["error"]]
            overload = {
                "requests": n2,
                "overload_factor": 2.0,
                "shed": len(shed),
                "shed_rate": round(len(shed) / max(n2, 1), 3),
                "admitted": len(admitted),
                "errors": sum(1 for r in rows2 if r["error"]),
                "admitted_ttft_p99_ms": (round(_pctile(
                    [r["ttft"] for r in admitted
                     if r["ttft"] is not None], 99) * 1000, 1)
                    if admitted else None),
                "goodput_tok_per_s": round(
                    sum(r["chars"] for r in admitted) / wall2, 1)
                if wall2 else 0.0,
                "shed_by_signal": dict(sorted(
                    admission.shed_by_signal.items())),
            }
            print(_json.dumps({"overload": overload}), file=sys.stderr)

        report = {
            "scenario": "failover",
            "workers": 2,
            "isl": isl, "osl": args.osl,
            "churn": churn,
            "overload": overload,
            "revive_resumes": revive.journal().resumed_total
            - resumed_before,
            # the surviving replica must never compile mid-failover: the
            # resume prompt stays on the warmed grid
            "post_warmup_compiles": {
                f"w{i}": e.fence.post_warmup_compiles
                for i, e in enumerate(engines)},
            # dynaslo: goodput + per-role quantiles from the two
            # workers' MERGED latency histograms (both phases' requests
            # judged; shed counts against goodput, it was not served)
            "slo": _slo_block([e.stats() for e in engines],
                              rows1 + rows2),
        }
        print(_json.dumps(report), file=sys.stderr)
        return report
    finally:
        if admission is not None:
            await admission.stop()
        if service is not None:
            await service.stop()
        if kvr is not None:
            await kvr.stop()
        if token_client is not None:
            await token_client.close()
        for pub in pubs:
            await pub.stop()
        for handle in handles:
            await handle.stop()
        for eng in engines:
            await eng.stop()
        await drt2.shutdown()
        await drt.shutdown()


def env_str_cfg(name):
    from dynamo_tpu.runtime.config import env_str

    return env_str(name)


async def measure(engine, reqs, concurrency, trace=False):
    """Drive `reqs` through any AsyncEngine-shaped object at the given
    concurrency; returns the aggregate report (the reference batch-mode
    metrics, launch/dynamo-run input/batch.rs:42-105). ``trace=True``
    wraps every request in a dyntrace root span and appends a per-stage
    breakdown to the report."""
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime import tracing
    from dynamo_tpu.runtime.engine import Context

    from dynamo_tpu.runtime import profiling

    # dynaprof: lag-monitor the bench loop for the run's duration so
    # every report carries loop_lag_p99_ms (released before returning)
    profiling.acquire_loop_profiler()
    sem = asyncio.Semaphore(concurrency)
    results = []
    trace_rids = []
    # hard per-request watchdog: a wedged generator must surface as an
    # error row, never hang the whole bench (the driver runs this
    # unattended at end of round)
    from dynamo_tpu.runtime.config import env_float
    req_timeout = env_float("DYN_BENCH_REQ_TIMEOUT")

    async def one(req_idx, token_ids, osl):
        async with sem:
            ctx = Context()
            try:
                await asyncio.wait_for(_one_inner(ctx, token_ids, osl),
                                       req_timeout)
            except asyncio.TimeoutError:
                # cancel the engine-side sequence too: an abandoned
                # request would keep its batch slot + KV pages and decode
                # to max_tokens, starving the remaining waves
                ctx.stop_generating()
                print(f"request {req_idx} timed out after {req_timeout}s",
                      file=sys.stderr)
                results.append({
                    "tokens_in": len(token_ids), "tokens_out": 0,
                    "ttft": None, "elapsed": req_timeout, "itl": None,
                    "gaps": [], "error": True,
                })

    async def _one_inner(ctx, token_ids, osl):
        pre = PreprocessedRequest(
            token_ids=token_ids,
            sampling=SamplingOptions(),  # greedy
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
            eos_token_ids=[])
        if trace:
            trace_rids.append(ctx.id)
            with tracing.get_tracer().start_span(
                    "bench.request", parent=None, request_id=ctx.id,
                    attributes={"isl": len(token_ids), "osl": osl}):
                await _drive(pre, ctx, osl, len(token_ids))
        else:
            await _drive(pre, ctx, osl, len(token_ids))

    async def _drive(pre, ctx, osl, isl):
        t_start = time.monotonic()
        t_first = None
        chunk_stamps = []
        n_out = 0
        finish = None
        async for out in engine.generate(pre, ctx):
            now = time.monotonic()
            if out.token_ids:
                if t_first is None:
                    t_first = now
                chunk_stamps.append(now)
                n_out += len(out.token_ids)
            if out.finish_reason:
                finish = out.finish_reason
                break
        t_end = time.monotonic()
        # window-amortized ITL: the fused decode window emits K tokens
        # per host sync, so raw inter-arrival gaps are 0 within a
        # window and ~window-time at boundaries (the r1/r2 itl_p50=0
        # artifact). The honest per-request number is the mean
        # inter-token interval over the whole stream.
        itl = ((chunk_stamps[-1] - chunk_stamps[0]) / (n_out - 1)
               if n_out > 1 else None)
        results.append({
            "tokens_in": isl, "tokens_out": n_out,
            "ttft": (t_first - t_start) if t_first else None,
            "elapsed": t_end - t_start, "itl": itl,
            # raw inter-CHUNK arrival gaps: what a streaming client
            # actually experiences between deliveries (with decode_steps
            # K>1 these are ~K-token strides — report them alongside the
            # amortized figure, not instead of it; VERDICT r4 weak #6)
            "gaps": [b - a for a, b in zip(chunk_stamps, chunk_stamps[1:])],
            "error": finish == "error",
        })

    bench_t0 = time.monotonic()
    await asyncio.gather(*(one(i, t, o) for i, (t, o) in enumerate(reqs)))
    wall = time.monotonic() - bench_t0
    lag = profiling.loop_lag_snapshot()
    await profiling.release_loop_profiler()

    errors = sum(1 for r in results if r["error"])
    results = [r for r in results if not r["error"]]
    total_out = sum(r["tokens_out"] for r in results)
    total_in = sum(r["tokens_in"] for r in results)
    ttfts = sorted(r["ttft"] for r in results if r["ttft"] is not None)
    itls = sorted(r["itl"] for r in results if r["itl"] is not None)
    # pooled raw inter-chunk gaps across all requests (client-observed
    # stream cadence — the un-amortized truth the window-ITL smooths)
    gaps = sorted(g for r in results for g in r["gaps"])

    def pct(v, p):
        return v[min(int(len(v) * p / 100), len(v) - 1)] if v else None

    report = {
        "requests": len(results), "errors": errors,
        "wall_s": round(wall, 3),
        "req_per_s": round(len(results) / wall, 3),
        "output_tok_per_s": round(total_out / wall, 1),
        "total_tok_per_s": round((total_in + total_out) / wall, 1),
        "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 1) if ttfts else None,
        "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 1) if ttfts else None,
        "itl_p50_ms": round(pct(itls, 50) * 1000, 2) if itls else None,
        "itl_p99_ms": round(pct(itls, 99) * 1000, 2) if itls else None,
        "itl_raw_chunk_p50_ms": (round(pct(gaps, 50) * 1000, 2)
                                 if gaps else None),
        "itl_raw_chunk_p99_ms": (round(pct(gaps, 99) * 1000, 2)
                                 if gaps else None),
        # dynaprof: event-loop callback-overrun p99 during the run —
        # the scheduler-overhead companion to the latency percentiles
        "loop_lag_p99_ms": round(lag["p99_s"] * 1000, 2),
    }
    if trace:
        report["trace_stages"] = _trace_breakdown(trace_rids)
    return report


def _trace_breakdown(request_ids):
    """Per-request stage dump (stderr) + a mean/max rollup per stage name
    over the whole run, read straight from the dyntrace ring."""
    from dynamo_tpu.runtime import tracing

    tracer = tracing.get_tracer()
    per_stage = {}
    for rid in request_ids:
        tr = tracer.get_request_trace(rid)
        if tr is None:
            continue
        print(f"trace {rid}: " + " ".join(
            f"{name}={ms:.1f}ms" for name, ms in sorted(tr["stages"].items())),
            file=sys.stderr)
        for name, ms in tr["stages"].items():
            per_stage.setdefault(name, []).append(ms)
    return {name: {"n": len(v),
                   "mean_ms": round(sum(v) / len(v), 2),
                   "max_ms": round(max(v), 2)}
            for name, v in sorted(per_stage.items())}


async def run_bench(args):
    engine, cfg = build_engine(args)
    print("warming up (compiling bucket grid)...", file=sys.stderr)
    t0 = time.monotonic()
    engine.warmup()
    print(f"warmup done in {time.monotonic()-t0:.1f}s", file=sys.stderr)

    reqs = synth_requests(args, cfg.vocab_size, engine.cap_tokens)
    report = await measure(engine, reqs, args.concurrency,
                           trace=getattr(args, "trace", False))
    st = engine.stats()
    report["prefix_hit_rate"] = round(st["gpu_prefix_cache_hit_rate"], 4)
    # compile-regression gate for hot-path work (ROADMAP item 3): any
    # nonzero value means a serve-time XLA compile stalled the run
    report["post_warmup_compiles"] = st["post_warmup_compiles_total"]
    # dynaprof: sampled device/host split + per-bucket program costs
    # (empty/0.0 unless --prof-sample > 0)
    report["device_time_fraction"] = st["device_time_fraction"]
    report["bucket_cost"] = st["bucket_cost"]
    # dynaslo: per-role latency quantiles from the engine's mergeable
    # histograms (no per-request rows here — measure() owns the client
    # view; goodput rides the shared/failover scenarios)
    report["slo"] = _slo_block([st])
    if getattr(args, "trace", False):
        print(f"trace compile fence: {st['post_warmup_compiles_total']} "
              f"post-warmup XLA compile(s)", file=sys.stderr)
    if engine.ecfg.spec_decode:
        report["spec_steps"] = st["spec_decode_steps"]
        report["spec_acceptance_rate"] = round(
            st["spec_decode_acceptance_rate"], 4)
        report["spec_mean_accepted_len"] = round(
            st["spec_decode_mean_accepted_len"], 4)
    await engine.stop()
    print(json.dumps(report), file=sys.stderr)
    return report


async def run_hotpath(args):
    """dynaturbo hot-path record: a decode-heavy, small-batch,
    long-generation mix (ITL is decided by per-token host work, not
    FLOPs, in this regime) with profiling forced on, so ONE record
    carries the honest client metric (``itl_raw_chunk_p99_ms``), the
    per-bucket dispatch/device cost table, loop-lag p99 and the compile
    fence. Two invocations (±``--hotpath-legacy``) diff with
    ``python -m tools.cost_diff``."""
    # decode-heavy defaults wherever the caller left the global ones:
    # short prompts, long generations, small concurrency
    if args.isl == 512:
        args.isl = 96
    if args.osl == 128:
        args.osl = 192
    if args.requests == 64:
        args.requests = 16
    if args.concurrency == 32:
        args.concurrency = 4
    if not getattr(args, "prof_sample", 0):
        # the record is useless as hot-path evidence without the cost
        # table; sample every other iteration
        args.prof_sample = 2
    report = await run_bench(args)
    report["hotpath_legacy"] = bool(getattr(args, "hotpath_legacy",
                                            False))
    return report


async def run_disagg(args):
    """Disagg vs agg A/B on the same workload — the BASELINE.md north-star
    (reference docs/architecture.md:57-61 claims +30%/GPU at 1 node).

    On this testbed both engines time-share ONE chip and every KV page
    crosses the loopback relay, so the interesting output is the full
    metric set + the transfer-overhead breakdown, not a win: disagg's gain
    comes from putting prefill on separate hardware, which a single-chip
    A/B cannot express by construction.
    """
    from dynamo_tpu.engine.jax_engine import JaxEngine
    from dynamo_tpu.llm.disagg import DisaggRouter, PrefillWorker
    from dynamo_tpu.llm.disagg.decode import build_disagg_decode
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    engine, cfg = build_engine(args)  # aggregated baseline: full pool
    params = engine.params  # one HBM copy shared by all three engines
    print("warming up agg engine...", file=sys.stderr)
    engine.warmup()
    reqs = synth_requests(args, cfg.vocab_size, engine.cap_tokens)
    agg = await measure(engine, reqs, args.concurrency,
                        trace=getattr(args, "trace", False))
    agg_st = engine.stats()
    agg["post_warmup_compiles"] = agg_st["post_warmup_compiles_total"]
    agg["device_time_fraction"] = agg_st["device_time_fraction"]
    agg["bucket_cost"] = agg_st["bucket_cost"]
    await engine.stop()
    base_ecfg = engine.ecfg
    del engine

    # disaggregated: decode engine (2/3 pool) + prefill engine (1/3 pool)
    import dataclasses

    decode_ecfg = dataclasses.replace(base_ecfg,
                                      num_pages=base_ecfg.num_pages * 2 // 3)
    prefill_ecfg = dataclasses.replace(base_ecfg,
                                       num_pages=base_ecfg.num_pages // 3)
    decode_eng = JaxEngine(cfg, decode_ecfg, params=params)
    prefill_eng = JaxEngine(cfg, prefill_ecfg, params=params)
    print("warming up disagg engines...", file=sys.stderr)
    decode_eng.warmup()
    prefill_eng.warmup(decode=False)

    drt = await DistributedRuntime.detached()
    router = DisaggRouter(max_local_prefill_length=args.disagg_threshold)
    disagg = await build_disagg_decode(drt, decode_eng, namespace="bench",
                                       router=router, watch_config=False)
    pw = PrefillWorker(drt, prefill_eng, namespace="bench")
    pw.start()

    # one disagg leg per chunk size (0 = legacy bulk frame): same engines,
    # fresh prompts per leg (a repeated workload would prefix-hit the
    # decode pool and skip the transfer under test — --shared-prefix adds
    # a deliberate A/B leg that does exactly that, measuring the
    # transfer-vs-reuse interaction instead of dodging it)
    if args.kv_chunk_pages is not None:
        chunk_values = [int(x) for x in
                        str(args.kv_chunk_pages).split(",") if x != ""]
    else:
        chunk_values = [pw.chunk_pages]
    legs = []
    for li, cp in enumerate(chunk_values):
        pw.chunk_pages = cp
        import copy as _copy

        a = _copy.copy(args)
        a.seed = args.seed + 101 * li
        leg_reqs = (reqs if li == 0
                    else synth_requests(a, cfg.vocab_size,
                                        decode_eng.cap_tokens))
        before_st = disagg.stats()
        before_send = dict(pw.xfer.__dict__)
        print(f"--- disagg leg kv_chunk_pages={cp} ---", file=sys.stderr)
        dis = await measure(disagg, leg_reqs, args.concurrency,
                            trace=getattr(args, "trace", False))
        st = disagg.stats()
        send = {k: v - before_send[k] for k, v in pw.xfer.__dict__.items()}
        dis["kv_chunk_pages"] = cp
        dis["post_warmup_compiles"] = (
            decode_eng.fence.post_warmup_compiles
            + prefill_eng.fence.post_warmup_compiles)
        # dynaprof per-leg: decode-engine device/host split + program
        # cost table (the prefill engine's table rides under a suffix)
        dis["device_time_fraction"] = round(
            decode_eng.profiler.device_time_fraction(), 4)
        dis["bucket_cost"] = decode_eng.profiler.cost_table()
        dis["prefill_bucket_cost"] = prefill_eng.profiler.cost_table()
        dis["remote_prefills"] = (st["remote_prefills"]
                                  - before_st["remote_prefills"])
        dis["local_prefills"] = (st["local_prefills"]
                                 - before_st["local_prefills"])
        dis["remote_fallbacks"] = (st["remote_fallbacks"]
                                   - before_st["remote_fallbacks"])
        # per-request means over COMPLETED remote prefills (the wait/ingest
        # accumulators only count successes; timeouts → remote_fallbacks)
        ok_remote = max(dis["remote_prefills"] - dis["remote_fallbacks"], 1)
        wait_s = st["remote_wait_total_s"] - before_st["remote_wait_total_s"]
        inject_s = (st["kv_transfer_inject_seconds_total"]
                    - before_st["kv_transfer_inject_seconds_total"])
        dis["remote_wait_mean_ms"] = round(1000 * wait_s / ok_remote, 1)
        dis["transfer_mb"] = round(
            (st["kv_transfer_bytes_total"]
             - before_st["kv_transfer_bytes_total"]) / 1e6, 1)
        dis["transfer_pages"] = (st["kv_transfer_pages_total"]
                                 - before_st["kv_transfer_pages_total"])
        dis["transfer_ingest_ms_per_req"] = round(
            1000 * inject_s / ok_remote, 1)
        # per-stage pipeline breakdown: overlapped stages legitimately sum
        # past the sender's wall time — that inequality IS the evidence the
        # extract/compress/wire/inject pipeline overlaps (tentpole metric)
        stage_sum = (send["extract_seconds"] + send["compress_seconds"]
                     + send["wire_seconds"] + inject_s)
        dis["transfer_stages"] = {
            "extract_s": round(send["extract_seconds"], 4),
            "compress_s": round(send["compress_seconds"], 4),
            "wire_s": round(send["wire_seconds"], 4),
            "inject_s": round(inject_s, 4),
            "stage_sum_s": round(stage_sum, 4),
            "send_wall_s": round(send["wall_seconds"], 4),
            "chunks_sent": send["chunks_sent"],
            "overlap": bool(stage_sum > send["wall_seconds"]),
        }
        print(json.dumps(dis), file=sys.stderr)
        legs.append(dis)

    shared_ab = None
    if getattr(args, "shared_prefix", False):
        # transfer-vs-reuse A/B (dynacache): same length distribution,
        # but every prompt shares one page-aligned 2/3-ISL prefix. After
        # the first transfers commit the shared blocks, decode-side
        # reservations prefix-hit and the prefill worker skips shipping
        # those pages — measured as transfer pages per remote prefill
        # next to the decode engine's realized hit split.
        import copy as _copy

        import numpy as np

        pw.chunk_pages = chunk_values[0]
        ps = decode_eng.ecfg.page_size
        pl = max((int(args.isl * 2 // 3) // ps) * ps, ps)
        a = _copy.copy(args)
        a.seed = args.seed + 7777
        base = synth_requests(a, cfg.vocab_size, decode_eng.cap_tokens)
        motif_rng = np.random.RandomState(args.seed ^ 0xD1CE)
        motif = motif_rng.randint(1, min(cfg.vocab_size - 10, 255),
                                  size=pl).tolist()
        shared_reqs = []
        for toks, osl in base:
            if len(toks) <= pl + 8:
                toks = toks + motif[:pl + 8 - len(toks) + 1]
            shared_reqs.append((motif + list(toks[pl:]), osl))
        cs0 = decode_eng.pm.cache_stats()
        before_st = disagg.stats()
        print("--- disagg shared-prefix leg ---", file=sys.stderr)
        shared_leg = await measure(disagg, shared_reqs, args.concurrency)
        st = disagg.stats()
        cs1 = decode_eng.pm.cache_stats()
        hit_blocks = (cs1["device_hit_blocks_total"]
                      - cs0["device_hit_blocks_total"]
                      + cs1["host_restored_blocks_total"]
                      - cs0["host_restored_blocks_total"])
        alloc_blocks = hit_blocks + (cs1["fresh_blocks_total"]
                                     - cs0["fresh_blocks_total"])
        shared_leg["transfer_pages"] = (
            st["kv_transfer_pages_total"]
            - before_st["kv_transfer_pages_total"])
        shared_leg["remote_prefills"] = (st["remote_prefills"]
                                         - before_st["remote_prefills"])
        shared_leg["decode_hit_blocks"] = hit_blocks
        shared_leg["decode_hit_block_rate"] = round(
            hit_blocks / max(alloc_blocks, 1), 4)
        fresh_leg = legs[0]
        shared_ab = {
            "fresh": {k: fresh_leg[k] for k in
                      ("req_per_s", "ttft_p50_ms", "transfer_pages",
                       "remote_prefills")},
            "shared": {k: shared_leg[k] for k in
                       ("req_per_s", "ttft_p50_ms", "transfer_pages",
                        "remote_prefills", "decode_hit_blocks",
                        "decode_hit_block_rate")},
            "transfer_pages_per_remote_fresh": round(
                fresh_leg["transfer_pages"]
                / max(fresh_leg["remote_prefills"], 1), 2),
            "transfer_pages_per_remote_shared": round(
                shared_leg["transfer_pages"]
                / max(shared_leg["remote_prefills"], 1), 2),
        }
        print(json.dumps({"shared_prefix_ab": shared_ab}),
              file=sys.stderr)

    await pw.stop()
    await disagg.transfer.stop()
    await prefill_eng.stop()
    await decode_eng.stop()
    await drt.shutdown()

    best = max(legs, key=lambda d: d["req_per_s"])
    report = {"scenario": "disagg_vs_agg", "agg": agg, "disagg": best,
              "disagg_over_agg_req_per_s":
                  round(best["req_per_s"] / agg["req_per_s"], 3)}
    if shared_ab is not None:
        report["shared_prefix_ab"] = shared_ab
    if len(legs) > 1:
        report["disagg_legs"] = legs
    print(json.dumps(report), file=sys.stderr)
    return report


def _run_spec_ab(args) -> dict:
    """Speculative-decoding A/B: the same repetitive workload measured
    with spec_decode off then on (separately built + warmed engines).
    The headline value is the spec-ON tok/s; vs_baseline is the on/off
    ratio; the detail block carries both full reports plus the
    acceptance stats, all in the ONE driver-parsed JSON line."""
    import copy

    reports = {}
    for on in (False, True):
        a = copy.copy(args)
        a._spec_on = on
        print(f"--- spec A/B: speculation {'ON' if on else 'OFF'} ---",
              file=sys.stderr)
        reports["spec_on" if on else "spec_off"] = asyncio.run(run_bench(a))
    off_tps = reports["spec_off"]["output_tok_per_s"]
    value = reports["spec_on"]["output_tok_per_s"]
    out = {"metric": metric_name(args), "value": value,
           "unit": metric_unit(args),
           "vs_baseline": round(value / off_tps, 3) if off_tps else None,
           "detail": reports}
    if getattr(args, "_cpu_smoke", False):
        out["degraded"] = "cpu-smoke (no chip available)"
    return out


def _run_sweep(args) -> dict:
    """Batch-geometry sweep over (concurrency, max_batch, decode_steps):
    one engine per distinct (max_batch, decode_steps) — separately warmed
    and torn down so pools don't stack in HBM — measuring the headline
    workload at each point. Proves (or spends) the 'remaining headroom is
    batch geometry' claim from the round-3 notes with data instead of a
    roofline argument."""
    import copy

    points = []
    for spec in args.sweep.split(","):
        conc, mb, ds = (int(x) for x in spec.strip().split(":"))
        points.append((conc, mb, ds))
    rows = []
    for conc, mb, ds in points:
        a = copy.copy(args)
        a.concurrency, a.max_batch, a.decode_steps = conc, mb, ds
        # more requests than 2 concurrency waves so steady-state dominates
        a.requests = max(args.requests, 2 * conc)
        print(f"--- sweep point conc={conc} max_batch={mb} "
              f"decode_steps={ds} ---", file=sys.stderr)
        try:
            rep = asyncio.run(run_bench(a))
        except Exception as e:  # one bad point must not kill the sweep
            print(f"sweep point failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            continue
        rows.append({"concurrency": conc, "max_batch": mb,
                     "decode_steps": ds, **rep})
        print(json.dumps(rows[-1]), file=sys.stderr)
    if not rows:
        raise RuntimeError("every sweep point failed")
    hdr = (f"{'conc':>5} {'max_b':>5} {'K':>3} {'out tok/s':>10} "
           f"{'ttft_p50':>9} {'itl_p50':>8} {'err':>4}")
    print(hdr, file=sys.stderr)

    def cell(v, w):  # all-error points report their percentiles as None
        return f"{'-' if v is None else v:>{w}}"

    for r in rows:
        print(f"{r['concurrency']:>5} {r['max_batch']:>5} "
              f"{r['decode_steps']:>3} {cell(r['output_tok_per_s'], 10)} "
              f"{cell(r['ttft_p50_ms'], 9)} {cell(r['itl_p50_ms'], 8)} "
              f"{r['errors']:>4}", file=sys.stderr)
    best = max(rows, key=lambda r: r["output_tok_per_s"])
    return {"metric": metric_name(args),
            "value": best["output_tok_per_s"], "unit": metric_unit(args),
            "vs_baseline": 1.0,
            "detail": {"best": best, "sweep": rows}}


def main():
    args = parse_args()
    if getattr(args, "hotpath_legacy", False):
        # legacy arm env half: restore the per-iteration loop yield and
        # inline detokenization (must land before the engine loop and
        # the first Backend.generate read them)
        os.environ["DYN_LOOP_YIELD"] = "1"
        os.environ["DYN_ASYNC_DETOK"] = "0"
    watchdog = None
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        if args.scenario == "sharded":
            # the forced-device-count flag must land in XLA_FLAGS before
            # the jax backend initializes (silently ignored afterwards)
            from dynamo_tpu.parallel.serving import \
                apply_forced_host_devices
            from dynamo_tpu.runtime.config import env_set_default

            env_set_default("DYN_FORCE_HOST_DEVICES", "8")
            apply_forced_host_devices()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from dynamo_tpu.runtime.config import env_float
        ok, reason = probe_backend(env_float("DYN_BENCH_PROBE_TIMEOUT"))
        if not ok and args.spec:
            # --spec degrades to a CPU smoke A/B (tiny model, few
            # requests) instead of reporting chip-unavailable: the A/B
            # ratio + acceptance stats are still meaningful on CPU,
            # and the metric label says "cpu smoke" so the number is
            # never mistaken for a TPU headline
            print(f"no chip ({reason}); degrading --spec to a CPU smoke "
                  "run", file=sys.stderr)
            args._cpu_smoke = True
            args.model = "tiny"
            args.requests = min(args.requests, 8)
            args.concurrency = min(args.concurrency, 4)
            args.isl = min(args.isl, 96)
            args.osl = min(args.osl, 32)
            args.decode_steps = min(args.decode_steps, 4)
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        elif not ok:
            emit_unavailable(args, reason)
            return
        else:
            watchdog = arm_watchdog(
                args, env_float("DYN_BENCH_WALL_BUDGET"))
    try:
        record = _run_scenario(args)
    except BaseException as e:
        # a mid-run failure (relay drop after a good probe, engine error)
        # must still produce the ONE parseable record, not a bare
        # traceback — the round-3 rc=1/parsed=null gate failure mode
        import traceback
        traceback.print_exc()
        if watchdog is not None:
            watchdog.cancel()
        emit_unavailable(args, f"{type(e).__name__}: {e}"[:300])
        return
    if watchdog is not None:
        watchdog.cancel()
    if getattr(args, "trip_incident", False):
        record["blackbox"] = _trip_incident(args)
    if getattr(args, "report_out", None):
        # full machine-readable record for the perf trajectory; must
        # round-trip through json.load (tier-1 gated)
        with open(args.report_out, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"report written to {args.report_out}", file=sys.stderr)
    # the ONE line the driver records
    print(json.dumps(record))


def _trip_incident(args) -> dict:
    """dynablack --trip-incident: manual capture after the workload, so
    the chip session proves an armed recorder yields a renderable bundle
    without perturbing the benched path (the trip happens post-run)."""
    from dynamo_tpu.runtime import blackbox

    rec = blackbox.get_recorder()
    if not rec.enabled:
        return {"armed": False, "window_s": rec.window_s}
    bundle = rec.trip("manual", {"via": "bench"})
    if bundle is None:
        return {"armed": True, "captured": False,
                "cooldown_remaining_s": round(rec.cooldown_remaining_s(), 3)}
    block = {"armed": True, "captured": True,
             "incident_id": bundle["id"],
             "workers": sorted(bundle["workers"])}
    if getattr(args, "report_out", None):
        stem = args.report_out
        if stem.endswith(".json"):
            stem = stem[:-len(".json")]
        path = stem + ".incident.json"
        with open(path, "w") as f:
            f.write(blackbox.render_bundle_json(bundle))
            f.write("\n")
        print(f"incident bundle written to {path}", file=sys.stderr)
        block["bundle_path"] = path
    return block


def _run_scenario(args) -> dict:
    if getattr(args, "trace", False):
        # force-sample every benched request and size the ring to hold
        # the whole run's spans (~a dozen per request on the disagg path)
        from dynamo_tpu.runtime import tracing

        tracing.configure(sample=1.0,
                          ring=max(4096, args.requests * 64))
    if args.spec:
        return _run_spec_ab(args)
    if args.sweep:
        return _run_sweep(args)
    if args.scenario == "multiturn":
        report = asyncio.run(run_multiturn(args))
        return {"metric": metric_name(args),
                "value": report["ttft_later_turns_p50_ms"],
                "unit": metric_unit(args), "vs_baseline": 1.0,
                "detail": report}
    if args.scenario == "disagg":
        report = asyncio.run(run_disagg(args))
        return {"metric": metric_name(args),
                "value": report["disagg_over_agg_req_per_s"],
                "unit": metric_unit(args), "vs_baseline": 1.0,
                "detail": report}
    if args.scenario == "shared" and getattr(args, "cache_ab", False):
        return run_shared_cache_ab(args)
    if args.scenario == "shared":
        report = asyncio.run(run_shared(args))
        return {"metric": metric_name(args),
                "value": report["prefix_hit_rate"],
                "unit": metric_unit(args),
                "vs_baseline": report["ttft_noshare_over_share"] or 1.0,
                "detail": report}
    if args.scenario == "sharded":
        report = asyncio.run(run_sharded(args))
        return {"metric": metric_name(args),
                "value": report["sharded"]["output_tok_per_s"],
                "unit": metric_unit(args),
                "vs_baseline":
                    report["sharded_over_unsharded_tok_per_s"],
                "detail": report}
    if args.scenario == "failover":
        report = asyncio.run(run_failover(args))
        return {"metric": metric_name(args),
                "value": report["churn"]["goodput_tok_per_s"],
                "unit": metric_unit(args), "vs_baseline": 1.0,
                "detail": report}
    if args.scenario == "hotpath":
        report = asyncio.run(run_hotpath(args))
        return {"metric": metric_name(args),
                "value": report["itl_raw_chunk_p99_ms"],
                "unit": metric_unit(args), "vs_baseline": 1.0,
                "detail": report}
    report = asyncio.run(run_bench(args))
    # vs_baseline: reference publishes no absolute numbers —
    # BASELINE.json.published == {} — so round-over-round ratio
    # starts at 1.0
    prev = None
    if os.path.exists("BENCH_prev.json"):
        try:
            with open("BENCH_prev.json") as f:
                prev = json.load(f).get("value")
        except Exception:
            prev = None
    value = report["output_tok_per_s"]
    return {"metric": metric_name(args), "value": value,
            "unit": metric_unit(args),
            "vs_baseline": round(value / prev, 3) if prev else 1.0,
            "detail": report}


if __name__ == "__main__":
    main()
