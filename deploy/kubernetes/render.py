"""CLI shim: render a DynamoDeployment into manifests.

The implementation lives in dynamo_tpu.k8s.render (shared with the
reconcile controller); this file keeps `kubectl apply -f <(python
deploy/kubernetes/render.py dep.yaml)` working standalone."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from dynamo_tpu.k8s.render import main, render  # noqa: E402,F401

if __name__ == "__main__":
    sys.exit(main())
