"""KV page manager: allocation, prefix-cache reuse, eviction, events.

The host-side half of the KV cache (the device-side pool lives in
models/llama.py). Re-designs three reference components as one coherent
manager:

- reference ``lib/llm/src/kv/reuse.rs`` (AvailableBlocks: priority+FIFO
  reuse pool with sequence-hash match-and-reclaim) → ``PageManager``'s
  reusable pool + ``match_prefix``;
- reference ``lib/llm/src/tokens.rs`` (TokenBlock chained sequence hashes,
  xxh3) → ``chain_hashes`` (same chained-hash construction, seed 1337 over
  LE token bytes, indexer.rs:64,123-135);
- the vLLM-patch ``event_manager.py`` (KVCacheEventManager publishing
  stored/removed to the router) → ``drain_events``.

Pages are identified by pool index. A page is either free (never valid),
active (refcount > 0), or reusable (refcount 0, contents intact, reusable
by hash until evicted). Evictions pop the least-recently-freed reusable
page (LRU-FIFO like the reference's priority 0 tier).
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import xxhash

HASH_SEED = 1337  # match the reference's block hasher (kv_router/indexer.rs)


def hash_block(parent: int, tokens: Sequence[int]) -> int:
    """Chained block hash: xxh3_64(parent_hash_le || token_le_bytes)."""
    h = xxhash.xxh3_64(seed=HASH_SEED)
    h.update(int(parent).to_bytes(8, "little", signed=False))
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=False))
    return h.intdigest()


def chain_hashes(token_ids: Sequence[int], page_size: int,
                 parent: int = 0) -> List[int]:
    """Sequence hashes for each FULL block of token_ids."""
    out = []
    h = parent
    for i in range(len(token_ids) // page_size):
        h = hash_block(h, token_ids[i * page_size:(i + 1) * page_size])
        out.append(h)
    return out


@dataclass
class KvEvent:
    """Stored/Removed cache event (reference kv_router/protocols.rs
    KvCacheEvent)."""

    kind: str                      # "stored" | "removed"
    block_hashes: List[int]
    parent_hash: Optional[int] = None
    token_ids: Optional[List[int]] = None  # for stored: the tokens per block

    def to_dict(self) -> dict:
        return {"kind": self.kind, "block_hashes": self.block_hashes,
                "parent_hash": self.parent_hash}


@dataclass
class PageState:
    refcount: int = 0
    block_hash: Optional[int] = None  # set when committed (full + hashed)


class PageManager:
    """Host-side page pool bookkeeping with prefix reuse."""

    def __init__(self, num_pages: int, page_size: int):
        self.num_pages = num_pages
        self.page_size = page_size
        # page 0 is reserved as the padding target in device page tables
        self.pages: List[PageState] = [PageState() for _ in range(num_pages)]
        self.free: deque = deque(range(1, num_pages))
        self.reusable: "OrderedDict[int, None]" = OrderedDict()  # LRU order
        self.by_hash: Dict[int, int] = {}  # block_hash → page id
        self.events: List[KvEvent] = []
        self.pages[0].refcount = 1  # never allocated

    # ------------------------------------------------------------- queries

    @property
    def available(self) -> int:
        return len(self.free) + len(self.reusable)

    @property
    def active(self) -> int:
        return self.num_pages - 1 - self.available

    def usage(self) -> float:
        return self.active / max(self.num_pages - 1, 1)

    def match_prefix(self, token_ids: Sequence[int]) -> Tuple[List[int], List[int]]:
        """Longest cached prefix: returns (page_ids, their hashes). Does NOT
        take references — call ``allocate`` to claim."""
        pages, hashes = [], []
        for h in chain_hashes(token_ids, self.page_size):
            page = self.by_hash.get(h)
            if page is None:
                break
            pages.append(page)
            hashes.append(h)
        return pages, hashes

    # ---------------------------------------------------------- allocation

    def allocate_sequence(self, token_ids: Sequence[int],
                          extra_pages: int = 0) -> Optional[Tuple[List[int], int]]:
        """Claim pages for a prompt: reuse the longest cached prefix, then
        fresh pages to cover the prompt (+extra_pages headroom).

        Returns (page_ids, num_cached_tokens) or None if out of memory.
        The last (partial) block is never matched (reference
        manager.rs prepare_prefill_sequence semantics).
        """
        need_total = (len(token_ids) + self.page_size - 1) // self.page_size \
            + extra_pages
        cached_pages, _ = self.match_prefix(token_ids)
        # full-prompt hit: leave at least the final token to recompute so
        # prefill produces logits (cap reuse at len-1 tokens)
        max_reuse = max((len(token_ids) - 1) // self.page_size, 0)
        cached_pages = cached_pages[:max_reuse]
        need_fresh = need_total - len(cached_pages)
        if need_fresh > self.available:
            return None
        for p in cached_pages:
            self._ref(p)
        fresh = [self._pop_fresh() for _ in range(need_fresh)]
        return cached_pages + fresh, len(cached_pages) * self.page_size

    def allocate_page(self) -> Optional[int]:
        """One more page for a growing sequence (decode)."""
        if self.available == 0:
            return None
        return self._pop_fresh()

    def grow(self, pages: List[int], needed_tokens: int) -> bool:
        """Ensure the page list covers needed_tokens; appends fresh pages.
        Returns False if out of memory."""
        while len(pages) * self.page_size < needed_tokens:
            p = self.allocate_page()
            if p is None:
                return False
            pages.append(p)
        return True

    def commit(self, page: int, block_hash: int,
               token_ids: Optional[List[int]] = None,
               parent_hash: Optional[int] = None) -> None:
        """Mark a page's contents as a complete, hashed block (prefix-cache
        publish; emits the stored event for the KV router)."""
        st = self.pages[page]
        if st.block_hash == block_hash:
            return
        if block_hash in self.by_hash:
            # another page already holds this block; keep the existing one
            return
        st.block_hash = block_hash
        self.by_hash[block_hash] = page
        self.events.append(KvEvent("stored", [block_hash],
                                   parent_hash=parent_hash,
                                   token_ids=token_ids))

    def release_sequence(self, pages: List[int]) -> None:
        """Drop one reference on each page; refcount-0 pages become reusable
        (kept for prefix hits) or free (uncommitted)."""
        for p in pages:
            st = self.pages[p]
            st.refcount -= 1
            assert st.refcount >= 0, f"double free of page {p}"
            if st.refcount == 0:
                if st.block_hash is not None:
                    self.reusable[p] = None  # most-recently-freed last
                else:
                    self.free.append(p)

    # ------------------------------------------------------------- internal

    def _ref(self, page: int) -> None:
        st = self.pages[page]
        if st.refcount == 0 and page in self.reusable:
            del self.reusable[page]
        st.refcount += 1

    def _pop_fresh(self) -> int:
        if self.free:
            page = self.free.popleft()
        else:
            page, _ = self.reusable.popitem(last=False)  # evict LRU reusable
            st = self.pages[page]
            if st.block_hash is not None:
                del self.by_hash[st.block_hash]
                self.events.append(KvEvent("removed", [st.block_hash]))
                st.block_hash = None
        st = self.pages[page]
        assert st.refcount == 0
        st.refcount = 1
        return page

    def drain_events(self) -> List[KvEvent]:
        out, self.events = self.events, []
        return out
