"""KV page manager: allocation, prefix-cache reuse, eviction, events.

The host-side half of the KV cache (the device-side pool lives in
models/llama.py). Re-designs three reference components as one coherent
manager:

- reference ``lib/llm/src/kv/reuse.rs`` (AvailableBlocks: priority+FIFO
  reuse pool with sequence-hash match-and-reclaim) → ``PageManager``'s
  reusable pool + ``match_prefix``;
- reference ``lib/llm/src/tokens.rs`` (TokenBlock chained sequence hashes,
  xxh3) → ``chain_hashes`` (same chained-hash construction, seed 1337 over
  LE token bytes, indexer.rs:64,123-135);
- the vLLM-patch ``event_manager.py`` (KVCacheEventManager publishing
  stored/removed to the router) → ``drain_events``.

Pages are identified by pool index. A page is either free (never valid),
active (refcount > 0), or reusable (refcount 0, contents intact, reusable
by hash until evicted). Evictions pop the least-recently-freed reusable
page (LRU-FIFO like the reference's priority 0 tier).

**Host offload tier** (reference kv/ V2 StorageType::{System,Pinned} +
docs/kv_cache_manager.md, the "+40% TTFT" headline): with ``host_pages >
0``, a block evicted from the HBM pool moves to a host-DRAM pool instead
of being dropped — the manager queues a device→host copy
(``pending_offload``) and keeps the block matchable via its hash. A prefix
hit on a host block allocates a fresh HBM page and queues a host→device
restore (``pending_restore``); the engine drains both queues as batched
page copies before its next device step (jax_engine._drain_kv_tier).
"removed" router events fire only when a block leaves BOTH tiers.
"""

from __future__ import annotations

import heapq
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import xxhash

HASH_SEED = 1337  # match the reference's block hasher (kv_router/indexer.rs)

EVICT_POLICIES = ("lru", "cost")


def hash_block(parent: int, tokens: Sequence[int]) -> int:
    """Chained block hash: xxh3_64(parent_hash_le || token_le_bytes)."""
    h = xxhash.xxh3_64(seed=HASH_SEED)
    h.update(int(parent).to_bytes(8, "little", signed=False))
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=False))
    return h.intdigest()


def chain_hashes(token_ids: Sequence[int], page_size: int,
                 parent: int = 0) -> List[int]:
    """Sequence hashes for each FULL block of token_ids."""
    out = []
    h = parent
    for i in range(len(token_ids) // page_size):
        h = hash_block(h, token_ids[i * page_size:(i + 1) * page_size])
        out.append(h)
    return out


class ChainHashCache:
    """Incremental chained-hash state for ONE growing token sequence.

    The chained construction (each block hash folds in its parent's)
    makes hashes append-only: blocks already hashed stay valid as tokens
    append, so the per-admission and per-commit full-prefix re-hash
    (O(sequence) xxh3 work per call — on the decode hot path, once per
    page-boundary crossing) collapses to hashing only NEW full blocks.
    Callers must feed append-only extensions of the same sequence; a
    shrunken input resets the cache (defensive, not expected)."""

    __slots__ = ("page_size", "_hashes", "_ntok")

    def __init__(self, page_size: int):
        self.page_size = page_size
        self._hashes: List[int] = []
        self._ntok = 0

    def extend(self, token_ids: Sequence[int]) -> List[int]:
        """Hashes for every full block of ``token_ids`` (== what
        ``chain_hashes(token_ids, page_size)`` returns), hashing only the
        blocks not covered by earlier calls."""
        if len(token_ids) < self._ntok:
            self._hashes, self._ntok = [], 0
        nblocks = len(token_ids) // self.page_size
        h = self._hashes[-1] if self._hashes else 0
        for i in range(len(self._hashes), nblocks):
            h = hash_block(
                h, token_ids[i * self.page_size:(i + 1) * self.page_size])
            self._hashes.append(h)
        self._ntok = len(token_ids)
        return self._hashes[:nblocks]


@dataclass
class KvEvent:
    """Stored/Removed cache event (reference kv_router/protocols.rs
    KvCacheEvent)."""

    kind: str                      # "stored" | "removed"
    block_hashes: List[int]
    parent_hash: Optional[int] = None
    token_ids: Optional[List[int]] = None  # for stored: the tokens per block

    def to_dict(self) -> dict:
        return {"kind": self.kind, "block_hashes": self.block_hashes,
                "parent_hash": self.parent_hash}


@dataclass
class PageState:
    refcount: int = 0
    block_hash: Optional[int] = None  # set when committed (full + hashed)
    # dynacache: when this page's block entered the device tier (commit
    # or host-tier restore) — eviction age = now - committed_at
    committed_at: float = 0.0


@dataclass
class Alloc:
    """Result of ``allocate_sequence``. Iterates/indexes as the legacy
    (pages, cached_tokens) pair; ``restores`` lists (page, host_slot)
    host→device copies the engine must drain before computing on them."""

    pages: List[int]
    cached_tokens: int
    restores: List[Tuple[int, int]] = field(default_factory=list)
    # dynacache prefix split: how the allocated pages were sourced.
    # device_hit + host_restored + fresh == len(pages) (conservation —
    # pinned by tests/test_cache_obs.py)
    device_hit_blocks: int = 0
    host_restored_blocks: int = 0
    fresh_blocks: int = 0

    def __iter__(self):
        return iter((self.pages, self.cached_tokens))

    def __getitem__(self, i):
        return (self.pages, self.cached_tokens)[i]


class PageManager:
    """Host-side page pool bookkeeping with prefix reuse."""

    def __init__(self, num_pages: int, page_size: int, host_pages: int = 0,
                 evict_policy: str = "lru"):
        if evict_policy not in EVICT_POLICIES:
            raise ValueError(
                f"evict_policy must be one of {EVICT_POLICIES}, "
                f"got {evict_policy!r}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.evict_policy = evict_policy
        # every pool structure below is event-loop-affine: all methods
        # are sync (each call is one atomic block under the loop), and
        # cross-thread callers serialize on the engine's _pm_lock. The
        # annotations make dynarace reject any future async method that
        # lets an await interleave with pool invariants mid-update.
        # page 0 is reserved as the padding target in device page tables
        self.pages: List[PageState] = [PageState() for _ in range(num_pages)]  # guarded-by: loop
        self.free: deque = deque(range(1, num_pages))  # guarded-by: loop
        self.reusable: "OrderedDict[int, None]" = OrderedDict()  # guarded-by: loop
        self.by_hash: Dict[int, int] = {}  # guarded-by: loop
        self.events: List[KvEvent] = []  # guarded-by: loop
        self.pages[0].refcount = 1  # never allocated
        # host offload tier
        self.host_pages = host_pages
        self.host_free: deque = deque(range(host_pages))  # guarded-by: loop
        self.host_by_hash: Dict[int, int] = {}   # guarded-by: loop
        self.host_lru: "OrderedDict[int, int]" = OrderedDict()  # guarded-by: loop
        self.pending_offload: List[Tuple[int, int]] = []  # guarded-by: loop
        self.pending_restore: List[Tuple[int, int]] = []  # guarded-by: loop
        # host slots planned for restore inside an in-progress
        # allocate_sequence call: _pop_fresh→_host_slot evictions triggered
        # by the same call must not reassign them (they reach
        # pending_restore only when the call completes)
        self._pinned_slots: set = set()
        # slot→pin refcount, maintained at every pin transition (queued
        # copies enqueue/drain, _pinned_slots add/remove) so _host_slot's
        # busy check is O(1) instead of rebuilding a set of every queued
        # copy per claim
        self._slot_pins: Dict[int, int] = {}  # guarded-by: loop
        # ---- eviction policy (dynaheat) ----
        # `lru` keeps the original OrderedDict popitem/LRU-walk order as
        # the A/B control. `cost` runs GreedyDual over both tiers: lazy
        # min-heaps of (priority, seq, page_or_slot) with per-entry
        # generation stamps for O(log n) eviction; priority = clock + 1 +
        # hot-prefix hits, and the clock advances to each evicted entry's
        # priority so once-hot blocks age out instead of squatting.
        # heap rows are (priority, seq, page_or_slot, gen); a row is live
        # iff gen matches the current _dev_gen/_host_gen for its member
        self._dev_heap: List[Tuple[float, int, int, int]] = []  # guarded-by: loop
        self._dev_gen: Dict[int, int] = {}  # guarded-by: loop
        self._dev_clock = 0.0  # guarded-by: loop
        self._host_heap: List[Tuple[float, int, int, int]] = []  # guarded-by: loop
        self._host_gen: Dict[int, int] = {}  # guarded-by: loop
        self._host_clock = 0.0  # guarded-by: loop
        self._host_touch = 0  # host LRU clock (monotonic touch counter)
        self._evict_seq = 0  # heap FIFO tiebreaker (monotonic)
        # ---- dynacache telemetry (host-side counters; same loop/lock
        # discipline as the pool structures above) ----
        # allocation prefix split (blocks == pages)
        self.device_hit_blocks_total = 0  # guarded-by: loop
        self.host_restored_blocks_total = 0  # guarded-by: loop
        self.fresh_blocks_total = 0  # guarded-by: loop
        # HBM evictions by fate: offloaded-to-host vs dropped entirely,
        # plus block age (commit→eviction) and host-tier evictions
        self.evict_offloaded_total = 0  # guarded-by: loop
        self.evict_dropped_total = 0  # guarded-by: loop
        self.evict_age_seconds_total = 0.0  # guarded-by: loop
        self.host_evictions_total = 0  # guarded-by: loop
        # restore-queue drain latency: enqueue stamp per queued restore
        # page; drained totals accumulated in drain_tier_ops
        self._restore_enq: Dict[int, float] = {}  # guarded-by: loop
        self.restores_drained_total = 0  # guarded-by: loop
        self.restore_wait_seconds_total = 0.0  # guarded-by: loop
        # restore batching: drained-batch count + pages per batch (mean
        # batch size = pages/batches — the coalescing win the overlapped
        # drain is chasing)
        self.restore_batches_total = 0  # guarded-by: loop
        self.restore_batch_pages_total = 0  # guarded-by: loop
        # hot prefix chains: per-block-hash hit counter, bounded — hashes
        # past the cap are simply untracked (top-K reporting only needs
        # the hot head, and an unbounded dict would grow with the corpus)
        self._hit_counts: Dict[int, int] = {}  # guarded-by: loop
        self._hit_track_cap = 1024

    # ------------------------------------------------------------- queries

    @property
    def available(self) -> int:
        return len(self.free) + len(self.reusable)

    @property
    def active(self) -> int:
        return self.num_pages - 1 - self.available

    def usage(self) -> float:
        return self.active / max(self.num_pages - 1, 1)

    def match_prefix(self, token_ids: Sequence[int]) -> Tuple[List[int], List[int]]:
        """Longest cached prefix: returns (page_ids, their hashes). Does NOT
        take references — call ``allocate`` to claim."""
        pages, hashes = [], []
        for h in chain_hashes(token_ids, self.page_size):
            page = self.by_hash.get(h)
            if page is None:
                break
            pages.append(page)
            hashes.append(h)
        return pages, hashes

    # ---------------------------------------------------------- allocation

    def allocate_sequence(self, token_ids: Sequence[int],
                          extra_pages: int = 0,
                          chain: Optional[List[int]] = None
                          ) -> Optional[Alloc]:
        """Claim pages for a prompt: reuse the longest cached prefix
        (HBM pages directly; host-tier blocks via a fresh page + queued
        restore copy), then fresh pages to cover the prompt (+extra_pages
        headroom).

        Returns an :class:`Alloc` or None if out of memory. The last
        (partial) block is never matched (reference manager.rs
        prepare_prefill_sequence semantics). ``chain`` optionally supplies
        the precomputed full-block hashes of ``token_ids`` (a
        :class:`ChainHashCache` product) so admission skips the O(prompt)
        re-hash.
        """
        need_total = (len(token_ids) + self.page_size - 1) // self.page_size \
            + extra_pages
        # full-prompt hit: leave at least the final token to recompute so
        # prefill produces logits (cap reuse at len-1 tokens)
        max_reuse = max((len(token_ids) - 1) // self.page_size, 0)
        if chain is None:
            chain = chain_hashes(token_ids, self.page_size)
        chain = chain[:max_reuse]
        # walk the chain across both tiers; device hit → reuse page,
        # host hit → fresh page + restore; stop at the first full miss
        plan: List[Tuple[Optional[int], Optional[int], int]] = []
        for h in chain:
            page = self.by_hash.get(h)
            if page is not None:
                plan.append((page, None, h))
                continue
            slot = self.host_by_hash.get(h)
            if slot is not None:
                plan.append((None, slot, h))
                continue
            break
        n_restore = sum(1 for p, _, _ in plan if p is None)
        need_fresh = need_total - (len(plan) - n_restore)
        # device hits sitting in the reusable set count toward `available`
        # but become unpoppable once ref'd below — exclude them, or the
        # check passes and _pop_fresh runs dry mid-allocation
        reusable_hits = sum(1 for p, _, _ in plan
                            if p is not None and self.pages[p].refcount == 0)
        if need_fresh > self.available - reusable_hits:
            return None
        # ref every device hit BEFORE popping fresh pages: a pop can evict
        # refcount-0 reusable pages, including ones matched later in plan
        for page, _, _ in plan:
            if page is not None:
                self._ref(page)
        # pin every planned restore slot for the whole call: an earlier
        # plan entry's _pop_fresh can evict a device page into the host
        # tier, and _host_slot must not hand it a slot a later entry still
        # needs to read (silent KV corruption — ADVICE r1 high)
        pinned = {slot for page, slot, _ in plan if page is None}
        self._pinned_slots |= pinned
        for slot in pinned:
            self._pin_slot(slot)
        claimed: List[int] = []
        restores: List[Tuple[int, int]] = []
        try:
            for i, (page, slot, h) in enumerate(plan):
                if page is not None:
                    claimed.append(page)
                    continue
                # defensive re-check (pinning should make a vanished slot
                # impossible): treat it as a miss — drop this and every
                # later plan entry, recompute those blocks instead
                if self.host_by_hash.get(h) != slot:
                    for later, _, _ in plan[i:]:
                        if later is not None:
                            self.release_sequence([later])
                    plan = plan[:i]
                    break
                fresh = self._pop_fresh()
                # promote back to the device tier: matchable immediately
                # (the engine drains the copy before its next device step);
                # no "stored" event — the block never left this worker
                self.pages[fresh].block_hash = h
                self.pages[fresh].committed_at = time.monotonic()
                self.by_hash[h] = fresh
                self.host_lru.move_to_end(slot)
                self._host_push(slot, h)  # host hit — refresh its priority
                restores.append((fresh, slot))
                claimed.append(fresh)
            for _ in range(need_total - len(claimed)):
                claimed.append(self._pop_fresh())
        finally:
            self._pinned_slots -= pinned
            for slot in pinned:
                self._unpin_slot(slot)
        now = time.monotonic()
        for page, slot in restores:
            self._restore_enq[page] = now
            self._pin_slot(slot)
        self.pending_restore.extend(restores)
        # dynacache: prefix split + hot-chain hit counts for the blocks
        # actually reused (plan may have been truncated above)
        device_hit = sum(1 for p, _, _ in plan if p is not None)
        host_restored = len(restores)
        fresh_blocks = len(claimed) - device_hit - host_restored
        self.device_hit_blocks_total += device_hit
        self.host_restored_blocks_total += host_restored
        self.fresh_blocks_total += fresh_blocks
        for _, _, h in plan:
            if h in self._hit_counts:
                self._hit_counts[h] += 1
            elif len(self._hit_counts) < self._hit_track_cap:
                self._hit_counts[h] = 1
        return Alloc(claimed, len(plan) * self.page_size, restores,
                     device_hit_blocks=device_hit,
                     host_restored_blocks=host_restored,
                     fresh_blocks=fresh_blocks)

    def allocate_page(self) -> Optional[int]:
        """One more page for a growing sequence (decode)."""
        if self.available == 0:
            return None
        return self._pop_fresh()

    def grow(self, pages: List[int], needed_tokens: int) -> bool:
        """Ensure the page list covers needed_tokens; appends fresh pages.
        Returns False if out of memory."""
        while len(pages) * self.page_size < needed_tokens:
            p = self.allocate_page()
            if p is None:
                return False
            pages.append(p)
        return True

    def commit(self, page: int, block_hash: int,
               token_ids: Optional[List[int]] = None,
               parent_hash: Optional[int] = None) -> None:
        """Mark a page's contents as a complete, hashed block (prefix-cache
        publish; emits the stored event for the KV router)."""
        st = self.pages[page]
        if st.block_hash == block_hash:
            return
        if block_hash in self.by_hash:
            # another page already holds this block; keep the existing one
            return
        st.block_hash = block_hash
        st.committed_at = time.monotonic()
        self.by_hash[block_hash] = page
        self.events.append(KvEvent("stored", [block_hash],
                                   parent_hash=parent_hash,
                                   token_ids=token_ids))

    def commit_chain(self, pages: List[int], token_ids: Sequence[int],
                     extent: int, chain: Optional[List[int]] = None) -> int:
        """Commit every FULL block covered by ``token_ids[:extent]`` in
        one call — the multi-token publish path. Prefill completion,
        decode-window boundary crossings, and speculative accepts (which
        can advance a sequence K+1 tokens — several page boundaries — in
        ONE step) all funnel through here so the chained-hash bookkeeping
        lives in one place. Idempotent per block (:meth:`commit` dedups
        on hash); returns the number of full blocks covered. ``chain``
        optionally supplies precomputed full-block hashes covering at
        least ``extent`` so the publish skips the O(extent) re-hash."""
        nblocks = extent // self.page_size
        if chain is not None and len(chain) >= nblocks:
            hashes = chain[:nblocks]
        else:
            hashes = chain_hashes(token_ids[:nblocks * self.page_size],
                                  self.page_size)
        for i, h in enumerate(hashes):
            self.commit(pages[i], h,
                        parent_hash=hashes[i - 1] if i else None,
                        token_ids=list(token_ids[i * self.page_size:
                                                 (i + 1) * self.page_size]))
        return nblocks

    def release_sequence(self, pages: List[int]) -> None:
        """Drop one reference on each page; refcount-0 pages become reusable
        (kept for prefix hits) or free (uncommitted)."""
        for p in pages:
            st = self.pages[p]
            st.refcount -= 1
            assert st.refcount >= 0, f"double free of page {p}"
            if st.refcount == 0:
                if st.block_hash is not None:
                    self.reusable[p] = None  # most-recently-freed last
                    if self.evict_policy == "cost":
                        self._dev_push(p)
                else:
                    self.free.append(p)

    # ------------------------------------------------------------- internal

    def _pin_slot(self, slot: int) -> None:
        self._slot_pins[slot] = self._slot_pins.get(slot, 0) + 1

    def _unpin_slot(self, slot: int) -> None:
        n = self._slot_pins.get(slot, 0) - 1
        if n <= 0:
            self._slot_pins.pop(slot, None)
        else:
            self._slot_pins[slot] = n

    def _hits(self, block_hash: Optional[int]) -> int:
        return self._hit_counts.get(block_hash, 0) if block_hash is not None \
            else 0

    def _dev_push(self, page: int) -> None:
        """Enter ``page`` into the cost-policy device eviction heap (call
        when it becomes reusable). Priority is GreedyDual: clock + 1 +
        hot-prefix hits."""
        gen = self._dev_gen.get(page, 0) + 1
        # bounded-by: keys are page ids of the fixed-capacity device pool
        self._dev_gen[page] = gen
        self._evict_seq += 1
        pri = self._dev_clock + 1.0 + self._hits(self.pages[page].block_hash)
        heapq.heappush(self._dev_heap, (pri, self._evict_seq, page, gen))
        if len(self._dev_heap) > 4 * self.num_pages + 64:
            self._compact_heap("dev")

    def _dev_invalidate(self, page: int) -> None:
        """Lazy-invalidate any live heap row for ``page`` (it left the
        reusable pool by _ref or eviction)."""
        if page in self._dev_gen:
            self._dev_gen[page] += 1

    def _host_push(self, slot: int, block_hash: Optional[int]) -> None:
        """(Re)enter ``slot`` into the host eviction heap — called on
        every touch (insert, host hit, re-offload refresh). Under ``lru``
        the priority is a monotonic touch counter, which reproduces the
        OrderedDict LRU→MRU victim order exactly; under ``cost`` it is
        the GreedyDual score."""
        gen = self._host_gen.get(slot, 0) + 1
        # bounded-by: keys are slot ids of the fixed-capacity host pool
        self._host_gen[slot] = gen
        self._evict_seq += 1
        if self.evict_policy == "cost":
            pri = self._host_clock + 1.0 + self._hits(block_hash)
        else:
            self._host_touch += 1
            pri = float(self._host_touch)
        heapq.heappush(self._host_heap, (pri, self._evict_seq, slot, gen))
        if len(self._host_heap) > 4 * self.host_pages + 64:
            self._compact_heap("host")

    def _compact_heap(self, which: str) -> None:
        """Drop stale rows when lazy invalidation lets a heap outgrow its
        pool 4x (amortized O(pool) — pushes since the last compaction pay
        for it)."""
        if which == "dev":
            self._dev_heap = [r for r in self._dev_heap
                              if self._dev_gen.get(r[2]) == r[3]]
            heapq.heapify(self._dev_heap)
        else:
            self._host_heap = [r for r in self._host_heap
                               if self._host_gen.get(r[2]) == r[3]]
            heapq.heapify(self._host_heap)

    def _ref(self, page: int) -> None:
        st = self.pages[page]
        if st.refcount == 0 and page in self.reusable:
            del self.reusable[page]
            self._dev_invalidate(page)
        st.refcount += 1

    def _evict_reusable(self) -> int:
        """Pick the eviction victim from the reusable pool. ``lru`` pops
        the least-recently-freed entry (the original order — A/B control);
        ``cost`` pops the minimum GreedyDual row from the lazy heap,
        skipping stale rows, and advances the clock to the evicted
        priority so surviving hot blocks age relative to it."""
        if self.evict_policy == "cost":
            while self._dev_heap:
                pri, _, page, gen = heapq.heappop(self._dev_heap)
                if self._dev_gen.get(page) != gen or page not in self.reusable:
                    continue  # stale row (page was re-ref'd or re-pushed)
                del self.reusable[page]
                # bounded-by: keys are page ids of the fixed-capacity device pool
                self._dev_gen[page] = gen + 1
                self._dev_clock = max(self._dev_clock, pri)
                return page
            # defensive: heap dry but reusable non-empty (should not
            # happen — every reusable insert pushes a row)
        page, _ = self.reusable.popitem(last=False)
        self._dev_invalidate(page)
        return page

    def _pop_fresh(self) -> int:
        if self.free:
            page = self.free.popleft()
        else:
            page = self._evict_reusable()
            st = self.pages[page]
            if st.block_hash is not None:
                h = st.block_hash
                del self.by_hash[h]
                st.block_hash = None
                if st.committed_at:
                    self.evict_age_seconds_total += max(
                        time.monotonic() - st.committed_at, 0.0)
                slot = None
                if self.host_pages > 0:
                    if h in self.host_by_hash:
                        # block already resident in the host tier (this page
                        # was a restore) — no copy, just refresh LRU
                        slot = self.host_by_hash[h]
                        self.host_lru.move_to_end(slot)
                        self._host_push(slot, h)
                    else:
                        slot = self._host_slot()
                        if slot is not None:
                            self.host_by_hash[h] = slot
                            self.host_lru[slot] = h
                            self._host_push(slot, h)
                            self.pending_offload.append((page, slot))
                            self._pin_slot(slot)
                if slot is None:
                    self.evict_dropped_total += 1
                    self.events.append(KvEvent("removed", [h]))
                else:
                    self.evict_offloaded_total += 1
        # the page may carry a stale queued restore (its sequence released
        # before any device step drained it) — a late copy would clobber
        # the new owner's content
        if self.pending_restore:
            kept = []
            for p, s in self.pending_restore:
                if p == page:
                    self._unpin_slot(s)
                else:
                    kept.append((p, s))
            self.pending_restore = kept
            self._restore_enq.pop(page, None)
        st = self.pages[page]
        assert st.refcount == 0
        st.refcount = 1
        return page

    def _host_slot(self) -> Optional[int]:
        """Claim a host-tier slot, evicting the policy victim if full
        (``lru``: least-recently-touched; ``cost``: minimum GreedyDual
        score). Slots referenced by queued copies are pinned (a
        reassignment before the drain would corrupt the in-flight copy);
        the O(1) ``_slot_pins`` refcount replaces the old per-claim busy
        set + O(n) LRU walk. Pinned rows popped off the heap top are
        stashed and re-pushed after the claim, so a claim is O(log n +
        pinned). Returns None when the whole tier is pinned. A "removed"
        event fires only when the evicted block has no device copy either
        (it leaves the worker entirely)."""
        if self.host_free:
            return self.host_free.popleft()
        stashed: List[Tuple[float, int, int, int]] = []
        victim: Optional[int] = None
        while self._host_heap:
            row = heapq.heappop(self._host_heap)
            pri, _, slot, gen = row
            if self._host_gen.get(slot) != gen or slot not in self.host_lru:
                continue  # stale row (slot was re-touched or evicted)
            if self._slot_pins.get(slot, 0) > 0:
                stashed.append(row)  # still live — restore after the claim
                continue
            victim = slot
            if self.evict_policy == "cost":
                self._host_clock = max(self._host_clock, pri)
            break
        for row in stashed:
            heapq.heappush(self._host_heap, row)
        if victim is None:
            return None
        self._host_gen[victim] += 1
        old_h = self.host_lru.pop(victim)
        del self.host_by_hash[old_h]
        self.host_evictions_total += 1
        if old_h not in self.by_hash:
            self.events.append(KvEvent("removed", [old_h]))
        return victim

    def drain_tier_ops(self, restore_limit: Optional[int] = None
                       ) -> Tuple[List[Tuple[int, int]],
                                  List[Tuple[int, int]]]:
        """Pop queued (page, host_slot) tier copies: (offloads, restores).
        The engine must make all popped offload content visible in the
        host pool before executing any popped restore, and dispatch both
        before a device step that touches the pages involved.

        ``restore_limit`` caps restores popped per call (FIFO prefix) so
        a huge restore burst drains over several iterations instead of
        blocking one — sequences whose restores are still queued are
        gated out of prefill by the engine until their ops dispatch."""
        off, self.pending_offload = self.pending_offload, []
        if restore_limit is None or len(self.pending_restore) <= restore_limit:
            res, self.pending_restore = self.pending_restore, []
        else:
            res = self.pending_restore[:restore_limit]
            self.pending_restore = self.pending_restore[restore_limit:]
        for _, slot in off:
            self._unpin_slot(slot)
        if res:
            # restore drain latency: enqueue → this pop (the dispatch point)
            now = time.monotonic()
            for page, slot in res:
                self._unpin_slot(slot)
                ts = self._restore_enq.pop(page, None)
                if ts is not None:
                    self.restore_wait_seconds_total += max(now - ts, 0.0)
            self.restores_drained_total += len(res)
            self.restore_batches_total += 1
            self.restore_batch_pages_total += len(res)
        return off, res

    def host_usage(self) -> float:
        return len(self.host_by_hash) / self.host_pages if self.host_pages \
            else 0.0

    # ------------------------------------------------- dynacache telemetry

    def top_prefixes(self, k: int) -> List[dict]:
        """The K hottest cached block hashes by reuse count (bounded by
        the tracking cap), with residency so a dashboard can tell a hot
        chain that is still serving hits from one that was evicted."""
        hot = sorted(self._hit_counts.items(),
                     key=lambda kv: (-kv[1], kv[0]))[:max(k, 0)]
        return [{"block_hash": f"{h:016x}", "hits": n,
                 "tier": ("device" if h in self.by_hash
                          else "host" if h in self.host_by_hash
                          else "evicted")}
                for h, n in hot]

    def cache_stats(self) -> dict:
        """One flat dict of the dynacache counters (engine stats() embeds
        these under ``cache_*`` keys; /debug/cache renders them nested)."""
        return {
            "device_hit_blocks_total": self.device_hit_blocks_total,
            "host_restored_blocks_total": self.host_restored_blocks_total,
            "fresh_blocks_total": self.fresh_blocks_total,
            "evict_offloaded_total": self.evict_offloaded_total,
            "evict_dropped_total": self.evict_dropped_total,
            "evict_age_seconds_total": round(self.evict_age_seconds_total,
                                             4),
            "host_evictions_total": self.host_evictions_total,
            "restore_queue_depth": len(self.pending_restore),
            "restores_drained_total": self.restores_drained_total,
            "restore_wait_seconds_total": round(
                self.restore_wait_seconds_total, 4),
            "restore_batches_total": self.restore_batches_total,
            "restore_batch_pages_total": self.restore_batch_pages_total,
            "evict_policy": self.evict_policy,
        }

    def drain_events(self) -> List[KvEvent]:
        out, self.events = self.events, []
        return out
