"""The JAX serving engine: continuous batching over a paged KV cache.

This replaces the reference's engine integrations (patched vLLM/SGLang
subprocesses over ZMQ, lib/llm/src/engines/) with an in-process TPU-native
engine — the idiomatic choice on TPU where the engine IS the Python process
(SURVEY §5 "Distributed communication backend").

Design:

- one asyncio scheduler loop owns the device: it alternates chunked
  prefill steps and batched decode steps over static-shaped, bucketed
  programs (no data-dependent shapes under jit);
- per-request state is host-side (token lists, page tables from
  ``PageManager``); the device sees only padded arrays;
- device→host sync (sampled tokens) happens via ``run_in_executor`` so the
  event loop keeps serving other requests during a TPU step;
- sequences preempt (release pages, requeue) when the pool runs dry —
  prefix caching makes re-prefill cheap;
- the engine speaks the internal token-level protocol
  (``PreprocessedRequest`` in, ``EngineOutput`` chunks out) so it slots
  behind ``Backend`` exactly like the reference's ExecutionContext.
"""

from __future__ import annotations

import asyncio
import logging
import math
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from functools import partial
from typing import AsyncIterator, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..llm.protocols.common import (FINISH_CANCELLED, FINISH_EOS,
                                    FINISH_LENGTH, FINISH_TIMEOUT,
                                    EngineOutput, PreprocessedRequest)
from ..models.config import ModelConfig
from ..models.llama import DROP_SLOT, KVCacheSpec
from ..models.registry import get_model_module
from ..runtime import blackbox, guard, profiling, slo, tracing
from ..runtime.config import env_bool, env_flag, env_int, env_str
from ..runtime.engine import Context
from .jit_fence import CompileFence
from .kv_manager import ChainHashCache, PageManager
from .profiler import EngineProfiler, memory_snapshot
from .sampling import (SamplingBatch, logprob_aux, sample_tokens,
                       update_penalty_state, verify_greedy_draft)
from .spec_decode import propose_ngram_draft

log = logging.getLogger("dynamo_tpu.engine")


def _cancel_reason(ctx: Context) -> str:
    """Why a stopped sequence is ending: the request deadline expired
    (client-visible "timeout", HTTP 504) vs. the caller cancelled
    ("cancelled"). Either way the sequence is terminated on the cancel
    path and its pages free immediately."""
    return FINISH_TIMEOUT if ctx.expired else FINISH_CANCELLED


def _stamp_dispatch(fence: CompileFence, name: str, fn):
    """Wrap a jitted step fn so every dispatch notes its call form on
    the engine's compile fence. The note is raw refs (one attribute
    store); jit_fence renders it into a dtype[shape] call-form key only
    when a post-warmup compile actually trips the fence."""
    def call(*args, **kwargs):
        fence.note_dispatch(name, args, kwargs)
        return fn(*args, **kwargs)
    return call


@dataclass
class EngineConfig:
    page_size: int = 64
    num_pages: int = 512
    max_batch: int = 64
    prefill_chunk: int = 512
    max_top_k: int = 64
    # host-DRAM offload tier: blocks evicted from HBM spill here and
    # restore on prefix hits (reference kv/ V2 multi-tier storage +
    # docs/kv_cache_manager.md "+40% TTFT"); 0 disables the tier
    host_pages: int = 0
    # tiered-KV restore chunking: at most this many host→HBM page
    # restores dispatch per scheduler iteration, so one request with a
    # huge host-tier prefix hit cannot block every other request's step
    # behind a bulk synchronous copy (VERDICT r2 weak #7: 30.9 s TTFT
    # with the tier on a relay-attached chip). Gated sequences wait in
    # `prefilling` while their restores drain across iterations; 0 =
    # unlimited (the old single-shot behavior)
    tier_restore_chunk: int = 32
    # top-N alternatives returned per token when a request asks for
    # logprobs; matches OpenAI's top_logprobs cap of 20 so no valid
    # request is silently truncated. ONE static value so all logprob
    # requests share a compiled window variant (the per-row requested
    # count is sliced host-side)
    max_top_logprobs: int = 20
    # pre-compile the logprobs decode-window variants (logprobs_topn is
    # a STATIC argname: serving flips it from 0 to max_top_logprobs on
    # the first request that asks for logprobs, and each value is its
    # own program per bucket). On by default — logprobs is a stock
    # OpenAI-API field any client can send, so unlike penalties the
    # unwarmed form is routinely reachable (DL026 warmup-form-drift
    # finding, previously a runtime compile-fence trip class)
    warmup_logprobs: bool = True
    # pre-compile the penalized decode-window variants too (doubles the
    # decode programs in warmup). Off by default: most deployments never
    # send sampling penalties, and a first penalty request merely pays
    # one compile per bucket
    warmup_penalties: bool = False
    # int8-compress the host tier (engine/kv_compress.py): pages are
    # quantized ON DEVICE before D2H and dequantized ON DEVICE after
    # H2D, so the slow host link moves ~half the bytes and the host
    # pool holds ~2x the pages per GB. LOSSY (restored pages round-trip
    # through int8). None (default) = ON whenever the tier is enabled,
    # unless DYN_HOST_TIER_FP16 asks for the lossless fallback;
    # explicit True/False wins over both
    host_tier_int8: Optional[bool] = None
    # dynaheat eviction policy for BOTH cache tiers: "cost" (GreedyDual
    # over the dynacache hot-prefix hit table — hot shared prefixes
    # outlive cold one-shot churn) or "lru" (the original least-recently-
    # freed order, kept as the A/B control). None reads DYN_EVICT_POLICY.
    evict_policy: Optional[str] = None
    # dynaheat overlapped restores: a drained restore batch's H2D +
    # dequantize dispatches on one drain and its page inject lands on
    # the NEXT, overlapping the intervening device step. False = the
    # serial same-drain inject (A/B control). None reads
    # DYN_RESTORE_OVERLAP.
    restore_overlap: Optional[bool] = None
    max_prefill_batch: int = 8  # prompts packed per prefill dispatch
    # fused decode window: run K decode+sample steps inside ONE jitted
    # program (sampling stays on device; tokens cross to the host once per
    # window). The serving loop is dispatch-latency-bound — per-step host
    # round-trips dwarf the ~ms device compute — so K amortizes dispatch
    # K-fold. EOS/stop/budget masking runs ON DEVICE (rows freeze), so K
    # can grow without dead compute past a stop.
    decode_steps: int = 4
    # pipelined dispatch: window N+1 (and the next prefill batch) are
    # enqueued BEFORE window N's tokens are read back, so the host
    # round-trip overlaps device compute. The device-side carry
    # (tok/pos/done/steps/remaining) makes this exact, not speculative.
    pipeline_decode: bool = True
    # prefill-priority: iterations with prompts waiting to prefill skip
    # the decode-window dispatch, so prompt batches drain at full cadence
    # (measured: interleaving a K-step window between every prefill batch
    # doubles TTFT and costs throughput by delaying batch build-up)
    prefill_priority: bool = True
    # token-budgeted chunked-prefill mixing (the vLLM-style middle ground
    # between the two all-or-nothing policies above): when set, every
    # iteration dispatches BOTH a decode window and a prefill batch, but
    # the prefill batch is trimmed to at most this many prompt tokens, so
    # a burst of long prompts cannot starve running decodes (ITL p99
    # bounded by window + budget-prefill time instead of the full burst
    # drain). None keeps pure prefill-priority. Overrides prefill_priority
    # when set.
    prefill_token_budget: Optional[int] = None
    # self-speculative decoding: a host-side prompt-lookup drafter
    # (engine/spec_decode.py) proposes up to spec_tokens candidates per
    # greedy row from its own prompt+generated history; ONE batched
    # [B, spec_tokens+1] verify forward checks them and the longest
    # greedy-matching prefix (plus the bonus token) is accepted — 1..K+1
    # tokens per dispatch. OFF by default so the compiled-program set
    # (and the pipelined window path) is untouched; when on, the decode
    # arm runs synchronously (the win is tokens-per-dispatch, not
    # dispatch overlap). Non-greedy / penalty / logit_bias / logprobs
    # rows transparently bypass speculation.
    spec_decode: bool = False
    spec_tokens: int = 4      # K: max draft tokens verified per step
    spec_ngram_max: int = 4   # longest suffix n-gram the drafter matches
    spec_ngram_min: int = 1   # shortest n-gram worth matching
    # dynaprof sampling cadence: profile every Nth scheduler iteration
    # with a timed dispatch (device/host split + per-bucket cost table;
    # engine/profiler.py). The sampled iteration pays one deliberate
    # device sync. None reads DYN_PROF_SAMPLE; 0 disables (default).
    prof_sample: Optional[int] = None
    # on-device stop table width (eos_token_ids + stop_token_ids rows,
    # padded with -1); requests with more ids fall back to the (lagging
    # but correct) host-side check
    max_eos_ids: int = 8
    # long-context: prompts whose prefill extent exceeds this take the
    # sequence-parallel ring-attention prefill (parallel/ring_attention.py)
    # instead of the chunked path — requires a mesh with a "seq" axis > 1.
    # None disables. The long path compiles one program per padded-length
    # bucket (pow2, seq-divisible); page_buckets must still cover the
    # decode-side table width for these prompts.
    long_prefill_threshold: Optional[int] = None
    # bucketing (static shapes under jit); keep these sets SMALL — every
    # (bucket combination) is one XLA compile, and warmup() pre-compiles
    # the full grid so serving never compiles mid-flight
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    prefill_buckets: Tuple[int, ...] = (16, 64, 512)
    page_buckets: Tuple[int, ...] = (8, 64)
    watermark_pages: int = 4  # keep-free headroom before admitting
    # ── decode hot-path toggles ──────────────────────────────────────
    # each gates exactly ONE hot-path change so its cost-table delta can
    # be measured in isolation (tools/cost_diff.py; docs/hot_path.md)
    #
    # prefill-priority iterations where the prefill sweep dispatched
    # NOTHING (every candidate restore-gated / cancelled / cache-covered)
    # still dispatch a decode window instead of idling the device for a
    # whole iteration. TTFT semantics unchanged: iterations that actually
    # dispatch a prefill batch still skip the window.
    overlap_idle_prefill: bool = True
    # read the window's on-device per-row emitted counts and emit each
    # row's tokens as ONE chunk: one EngineOutput + one event-loop wakeup
    # per row-window instead of per token, and one bulk page commit. Rows
    # whose stop-id set exceeds max_eos_ids keep the per-token host path
    # (the device stop table can't represent them).
    coalesce_window_emissions: bool = True
    # reuse the uploaded sampler-param/page-table device arrays across
    # decode-window dispatches while the batch composition is unchanged,
    # skipping the per-dispatch host→device re-upload. NOTE: freezes the
    # per-dispatch reseed of UNSEEDED sampled rows for the cached span
    # (seeded rows and greedy rows are bit-identical either way).
    cache_sampler_params: bool = True
    # run _admit inside _step right after the decode-window dispatch, so
    # its host work (bucketing, page reservation, prefix-cache hashing)
    # overlaps the window's device compute instead of serializing ahead
    # of the dispatch on the event-loop thread
    admit_in_step: bool = True

    def __post_init__(self) -> None:
        if self.prefill_chunk % self.page_size != 0:
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk}) must be a multiple "
                f"of page_size ({self.page_size}): chunk starts must stay "
                f"page-aligned for the page-granular KV commit")
        if self.spec_decode and self.spec_tokens < 1:
            raise ValueError(
                f"spec_tokens ({self.spec_tokens}) must be >= 1 when "
                f"spec_decode is enabled")

    @staticmethod
    def _pick(buckets: Tuple[int, ...], n: int) -> int:
        for b in buckets:
            if n <= b:
                return b
        b = buckets[-1]
        while b < n:
            b *= 2
        return b

    def bucket_batch(self, n: int) -> int:
        return min(self._pick(self.batch_buckets, n), self.max_batch)

    def prefill_bucket_batch(self, n: int) -> int:
        """Prefill batches only use the two warmed buckets
        (bucket_batch(1) and bucket_batch(max_prefill_batch)) so a
        mid-serving prompt mix never triggers a fresh XLA compile."""
        small = self.bucket_batch(1)
        return small if n <= small else self.bucket_batch(
            self.max_prefill_batch)

    def bucket_len(self, n: int) -> int:
        return min(self._pick(self.prefill_buckets, n), self.prefill_chunk)

    def bucket_pages(self, n: int) -> int:
        return self._pick(self.page_buckets, n)

    def warmed_grid(self) -> dict:
        """The EXACT images of the bucket helpers over every admissible
        serving input — the shape set warmup() must compile so no jitted
        engine entry point ever compiles mid-serving. Computed by
        enumeration rather than from the bucket tuples directly because
        ``_pick`` doubles past its last bucket: with exotic configs
        (``prefill_chunk`` above the largest prefill bucket,
        ``max_batch`` outside ``batch_buckets``) the reachable shapes are
        a strict superset of the declared buckets. The compile-fence
        grid-coverage test pins warmup() to this set."""
        cap_pages = min(self.page_buckets[-1], max(self.num_pages - 1, 1))
        return {
            "prefill_lens": sorted({
                self.bucket_len(n)
                for n in range(1, self.prefill_chunk + 1)}),
            "decode_batches": sorted({
                self.bucket_batch(n)
                for n in range(1, self.max_batch + 1)}),
            "prefill_batches": sorted({
                self.prefill_bucket_batch(n)
                for n in range(1, max(self.max_prefill_batch,
                                      self.max_batch) + 1)}),
            "page_buckets": sorted({
                self.bucket_pages(n) for n in range(1, cap_pages + 1)}),
        }


@dataclass(eq=False)  # identity semantics: `in`/`==` must never deep-compare
class Sequence:
    req: PreprocessedRequest
    context: Context
    out: asyncio.Queue
    tokens: List[int]            # prompt + generated (host truth)
    num_prompt: int
    pages: List[int] = field(default_factory=list)
    computed: int = 0            # positions already in the KV cache
    generated: int = 0
    finished: Optional[str] = None
    finish_emitted: bool = False
    last_token: int = 0          # next decode input
    arrival: float = field(default_factory=time.monotonic)
    # disaggregation: keep pages alive after finish so the prefill worker
    # can extract them (caller must release_pages() afterwards)
    hold_pages: bool = False
    # dynaprof cost attribution (host-side counters, no device work):
    # queue wait stamped at admission; occupancy-weighted device-step
    # share (each dispatch distributes exactly 1.0 across its batch, so
    # fleet-wide shares sum to the dispatch count); peak page footprint
    queue_wait_s: float = 0.0
    prefix_hit: int = 0
    dispatch_share: float = 0.0
    dispatches: int = 0
    max_pages: int = 0
    # dynacache prefix split: how this request's prompt pages were
    # sourced at first admission (device reuse vs host-tier restore vs
    # fresh compute) + how long its queued restores waited to dispatch
    device_hit_blocks: int = 0
    host_restored_blocks: int = 0
    restore_t0: Optional[float] = None
    restore_wait_s: float = 0.0
    # dynaslo: last token-bearing emission (None until the first token
    # leaves the engine) — TTFT on the first emission, per-token ITL on
    # every later gap, e2e at finish (all host clock reads, no syncs)
    last_emit_t: Optional[float] = None
    # incremental chained-hash state over `tokens` (kv_manager
    # ChainHashCache, engine-lazily created): admission's prefix match
    # and every page-boundary publish extend it instead of re-hashing
    # the whole sequence
    hash_cache: Optional[ChainHashCache] = None
    # dynahot DL022: the request's eos/stop id lists are immutable per
    # sequence, so the per-token append path reads one cached frozenset
    # membership instead of rebuilding `x or []` defaults every token
    _stop_set: Optional[frozenset] = None
    _dev_stop_count: int = -1

    @property
    def stop_set(self) -> frozenset:
        s = self._stop_set
        if s is None:
            stop = self.req.stop
            eos = () if stop.ignore_eos else (self.req.eos_token_ids or ())
            s = frozenset(eos) | frozenset(stop.stop_token_ids or ())
            self._stop_set = s
        return s

    @property
    def dev_stop_count(self) -> int:
        """Rows the full stop-id set would occupy in the device stop
        table (list lengths, duplicates counted, matching the decode
        window's eos-table seeding)."""
        n = self._dev_stop_count
        if n < 0:
            stop = self.req.stop
            n = 0 if stop.ignore_eos else len(self.req.eos_token_ids or ())
            n += len(stop.stop_token_ids or ())
            self._dev_stop_count = n
        return n

    def max_new(self) -> int:
        mt = self.req.stop.max_tokens
        return mt if mt is not None else 1 << 30

    @property
    def prefill_extent(self) -> int:
        """Tokens whose KV must exist before decode can run. Fresh request:
        the whole prompt (its last logits seed sampling). Resumed after
        preemption: everything except the final token, which is the next
        decode input (its KV is written by that decode step)."""
        return self.num_prompt if self.generated == 0 else len(self.tokens) - 1


@dataclass
class _PendingWindow:
    """A dispatched-but-unread decode window. ``toks`` and ``carry`` are
    device arrays (futures under JAX async dispatch); reading ``toks``
    back is deferred until after the NEXT window is enqueued."""

    batch: List[Sequence]
    toks: jax.Array                 # [B, K] sampled tokens
    emitted: jax.Array              # [B] on-device valid-token counts
    carry: tuple                    # (tok, pos, done, steps, remaining)
    index: Dict[int, int] = field(default_factory=dict)  # id(seq) → row
    aux: Optional[tuple] = None     # (lp [B,K], tv [B,K,N], ti [B,K,N])
    processed: bool = False


@dataclass
class _PendingPrefill:
    """A dispatched-but-unread prefill batch: ``sampled`` is the on-device
    first-token draw for rows that completed their prompt this chunk
    (None when no row finished)."""

    finishing: List[Tuple[int, Sequence]]
    sampled: Optional[jax.Array]
    aux: Optional[tuple] = None  # (lp [B], top_vals [B,N], top_ids [B,N])
    processed: bool = False


class JaxEngine:
    """AsyncEngine over the JAX model (token-level core engine)."""

    def __init__(self, model_cfg: ModelConfig, engine_cfg: Optional[EngineConfig]
                 = None, params=None, seed: int = 0, dtype=None, mesh=None,
                 quant: Optional[str] = None,
                 worker_label: Optional[str] = None):
        self.cfg = model_cfg
        self.ecfg = engine_cfg or EngineConfig()
        # dynashard replica identity: a STABLE per-replica label (e.g.
        # "r0") threaded through stats() → ForwardPassMetrics → the
        # aggregator's `replica` gauge label, the per-request cost block
        # and dyntrace spans — instance ids (lease hex) are unique but
        # not stable across restarts, so dashboards key on this instead
        self.worker_label = worker_label or ""
        self.mesh_devices = int(mesh.size) if mesh is not None else 1
        self.mesh_axes = ({k: int(v) for k, v in mesh.shape.items()
                           if int(v) > 1} if mesh is not None else {})
        self.mesh_shape = (",".join(f"{k}={v}" for k, v in
                                    self.mesh_axes.items())
                           or "single")
        model = get_model_module(model_cfg)
        if params is None:
            if quant == "int8":
                # init + quantize on host CPU so the bf16 tree never
                # exists in HBM (how 8B-shaped weights start on a 16 GB
                # chip); see models/quant.py
                from ..models.quant import host_init_quantized
                params = host_init_quantized(model, model_cfg, seed)
            else:
                params = model.init_params(model_cfg,
                                           jax.random.PRNGKey(seed))
        elif quant == "int8":
            from ..models.quant import quantize_params
            params = quantize_params(params)
        self.params = params
        spec = KVCacheSpec(self.ecfg.num_pages, self.ecfg.page_size)
        self.kv_k, self.kv_v = model.init_kv_cache(model_cfg, spec, dtype)
        self.mesh = mesh
        if mesh is not None and mesh.size > 1:
            from ..parallel.mesh import shard_kv_cache, shard_params
            self.params = shard_params(self.params, model_cfg, mesh)
            self.kv_k, self.kv_v = shard_kv_cache(self.kv_k, self.kv_v,
                                                  model_cfg, mesh)
        # all three attention paths (prefill, K=1 decode, fused decode
        # window) keep the Pallas kernel under a mesh via shard_map over
        # the head axis (ops/paged_attention.py *_sharded wrappers)
        self.prefill_fn, self.decode_fn = model.make_step_fns(
            model_cfg, mesh=mesh)
        if mesh is not None and mesh.size > 1:
            d = mesh.shape.get("data", 1)
            bad = [b for b in self.ecfg.batch_buckets if b % d]
            if d > 1 and bad:
                raise ValueError(
                    f"batch_buckets {bad} not divisible by mesh data axis "
                    f"({d}): shard_map decode windows need whole rows per "
                    f"data shard")
        if hasattr(model, "make_decode_window_fn"):
            # model-provided fused window (read-only pool + window buffer:
            # one pool copy in HBM; see llama.make_decode_window_fn)
            self.decode_multi_fn = model.make_decode_window_fn(
                model_cfg, True, self.ecfg.max_top_k, mesh=mesh)
        else:
            self.decode_multi_fn = _make_decode_multi(
                model, model_cfg, self.ecfg.max_top_k, mesh=mesh)
        # self-speculative decode: the [B, K+1] verify forward (only
        # built — and only warmed — when the flag is on, so the default
        # compiled-program set is untouched). Model families without a
        # verify fn (MLA's latent cache) silently keep the standard path.
        self.verify_fn = None
        if self.ecfg.spec_decode:
            if hasattr(model, "make_verify_fn"):
                self.verify_fn = model.make_verify_fn(model_cfg, mesh=mesh)
            else:
                log.warning("spec_decode enabled but %s has no "
                            "make_verify_fn; speculation disabled",
                            model.__name__)
        self.spec_steps = 0
        self.spec_draft_tokens_total = 0
        self.spec_accepted_tokens_total = 0
        # sequence-parallel long-prefill (ring attention over the mesh's
        # "seq" axis) — the serving wire-up of parallel/ring_attention.py
        # (r2 built it but nothing reached it; VERDICT r2 missing #5)
        self.long_prefill_fn = None
        self.long_prefills_total = 0
        if (self.ecfg.long_prefill_threshold is not None
                and mesh is not None and mesh.shape.get("seq", 1) > 1):
            # Gemma-2's sliding window / softcap thread through the ring
            # as position predicates (parallel/ring_attention.py) — all
            # three model families take this path (VERDICT r4 task 7)
            from ..parallel.ring_attention import (make_long_prefill_fn,
                                                   make_mla_long_prefill_fn)
            # MLA takes the latent-only ring exchange (only the shared
            # compressed stream rotates on ICI); GQA rotates per-head K/V
            builder = (make_mla_long_prefill_fn if model_cfg.is_mla
                       else make_long_prefill_fn)
            self.long_prefill_fn = builder(model_cfg, mesh)
            self._seq_par = mesh.shape["seq"]
        # resolve the dynaheat None-means-env config knobs ONCE, here,
        # so every later read sees a concrete value (the ecfg object is
        # per-engine; bench/tests that pass explicit values are
        # untouched)
        if self.ecfg.host_tier_int8 is None:
            self.ecfg.host_tier_int8 = (
                self.ecfg.host_pages > 0
                and not env_bool("DYN_HOST_TIER_FP16"))
        if self.ecfg.evict_policy is None:
            self.ecfg.evict_policy = env_str("DYN_EVICT_POLICY") or "cost"
        if self.ecfg.restore_overlap is None:
            self.ecfg.restore_overlap = env_bool("DYN_RESTORE_OVERLAP", True)
        # async frames must take _pm_lock (declared below) before
        # touching the page pool; sync frames on the engine step path
        # are serialized by the single-worker executor
        self.pm = PageManager(self.ecfg.num_pages,  # guarded-by: self._pm_lock
                              self.ecfg.page_size,
                              host_pages=self.ecfg.host_pages,
                              evict_policy=self.ecfg.evict_policy)
        # host-DRAM offload pools (same per-page layout as the HBM pool)
        self.host_k = self.host_v = None
        self.host_k_s = self.host_v_s = None
        if self.ecfg.host_pages > 0:
            # derive page geometry from the ACTUAL device pools: the two
            # pools differ per family (MLA: latent [.., 1, ps, r] vs rope
            # [.., 1, ps, dr]) — rebuilding from GQA config fields here
            # would allocate wrong-shaped host pools for MLA and crash
            # the first offload landing
            hk = (model_cfg.num_layers, self.ecfg.host_pages,
                  *self.kv_k.shape[2:])
            hv = (model_cfg.num_layers, self.ecfg.host_pages,
                  *self.kv_v.shape[2:])
            if self.ecfg.host_tier_int8:
                # compressed tier: int8 rows + f32 per-row scales — the
                # D2H/H2D link moves ~half the bytes and the same host
                # RAM holds ~2x the pages (engine/kv_compress.py)
                self.host_k = np.zeros(hk, np.int8)
                self.host_v = np.zeros(hv, np.int8)
                self.host_k_s = np.zeros(hk[:-1] + (1,), np.float32)
                self.host_v_s = np.zeros(hv[:-1] + (1,), np.float32)
            else:
                # the pool's .dtype is already a numpy dtype (ml_dtypes
                # registers bf16) — resolving it through a device
                # round-trip (np.asarray(jnp.zeros(...))) was dynajit
                # DL017's first true positive
                hdtype = np.dtype(self.kv_k.dtype)
                self.host_k = np.zeros(hk, hdtype)
                self.host_v = np.zeros(hv, hdtype)
        self.offload_pages_total = 0
        self.restore_pages_total = 0
        # guards PageManager between the event-loop thread (_admit) and
        # executor-thread disagg jobs (reserve/release/submit); engine steps
        # are already serialized with those jobs by the single-worker executor
        self._pm_lock = threading.Lock()
        self.waiting: List[Sequence] = []
        self.prefilling: List[Sequence] = []
        self.running: List[Sequence] = []
        # pipelined dispatch state: windows/prefills enqueued on device but
        # not yet read back, plus finished sequences whose pages must stay
        # allocated until every in-flight window containing them completes
        # (a premature free could hand a page to a new sequence while the
        # old window still writes it)
        self._inflight: List[_PendingWindow] = []
        self._pending: Optional[_PendingWindow] = None
        self._pending_prefill: Optional[_PendingPrefill] = None
        self._deferred_free: List[Sequence] = []
        # cache_sampler_params: (key, SamplingBatch, device arrays) of the
        # last decode-window dispatch. The key holds the batch list itself
        # (Sequence is identity-eq), so a stale hit after id() reuse is
        # impossible — the cached refs keep those Sequences alive until
        # the next composition change replaces the entry.
        self._samp_cache: Optional[tuple] = None
        # tiered-KV overlap state: offload gathers dispatched but not yet
        # copied to the host pool (device arrays + target slots), and HBM
        # pages whose host→HBM restore is still queued (their sequences
        # are gated out of prefill until the copy dispatches)
        self._offload_inflight: List[Tuple] = []
        self._unrestored_pages: set = set()
        # restore_overlap staging: ONE drained restore batch whose H2D +
        # dequantize dispatched on the previous drain (overlapping the
        # intervening device step) and whose page inject lands on the
        # next. Rows: (page, block_hash) per restored page + the device
        # arrays; pages stay in _unrestored_pages until injected.
        self._restore_staged: Optional[Tuple] = None
        # per-sequence max context implied by the warmed bucket grid: a
        # request may never need more pages than the largest page bucket,
        # or serving would compile mid-flight (VERDICT r2 weak #6)
        self.cap_pages = min(self.ecfg.page_buckets[-1],
                             max(self.ecfg.num_pages - 1, 1))
        self.cap_tokens = self.cap_pages * self.ecfg.page_size
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._aio_loop: Optional[asyncio.AbstractEventLoop] = None
        # thread id of the loop's thread, captured in start(): _emit's
        # on/off-loop routing is one integer compare (no exception probe)
        self._aio_loop_tid: Optional[int] = None
        self._stopped = False
        # dynarevive graceful drain: a draining engine refuses new work
        # (typed NoCapacity) while in-flight sequences run to completion
        self.draining = False
        self._exec = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="jax-step")
        # observability (ForwardPassMetrics analog, kv_router/protocols.rs)
        self.steps = 0
        # step timeline: bounded ring of scheduler events (queue-wait,
        # batch occupancy, tokens/step, spec accepts) surfaced through
        # /v1/traces on the HTTP frontend (dyntrace)
        self.step_timeline = tracing.StepTimeline(
            env_int("DYN_STEP_TIMELINE") or 0)
        tracing.register_timeline(f"jax-engine-{id(self):x}",
                                  self.step_timeline)
        # runtime compile fence (engine/jit_fence.py): armed by warmup(),
        # counts every post-warmup XLA compile; DYN_JIT_FENCE=warn|raise
        # escalates. The counter rides stats() → ForwardPassMetrics →
        # dyn_engine_post_warmup_compiles_total.
        self.fence = CompileFence(f"jax-engine-{id(self):x}",
                                  timeline=self.step_timeline)
        # stamp every fenced jit dispatch with its call form so a fence
        # trip can name the offending form (jit name + operand
        # dtype[shape] + static kwargs). note_dispatch stores raw refs
        # only; rendering happens on the trip path, never per dispatch.
        for _attr in ("prefill_fn", "decode_fn", "decode_multi_fn",
                      "verify_fn", "long_prefill_fn"):
            _fn = getattr(self, _attr, None)
            if _fn is not None:
                setattr(self, _attr,
                        _stamp_dispatch(self.fence, _attr, _fn))
        # dynaprof: sampled device/host dispatch timing + per-bucket cost
        # (engine/profiler.py; sample=0 keeps the hot path sync-free)
        self.profiler = EngineProfiler(f"jax-engine-{id(self):x}",
                                       timeline=self.step_timeline,
                                       sample=self.ecfg.prof_sample)
        # per-page KV bytes (both pools) for attribution/occupancy
        # accounting — .nbytes is shape metadata, not a device sync
        self._page_bytes = int(
            (self.kv_k.nbytes + self.kv_v.nbytes)
            // max(self.ecfg.num_pages, 1))
        # dispatches that distributed a step share (the attribution
        # conservation invariant: sum of per-request shares == this)
        self.batch_dispatches_total = 0
        self.queue_wait_seconds_total = 0.0
        self.prefill_tokens_total = 0
        # iterations where a decode window dispatched WHILE prompts were
        # still prefilling — the observable for budgeted mixing
        self.mixed_dispatches = 0
        self.decode_tokens_total = 0
        self.prefix_hit_tokens_total = 0
        self.prompt_tokens_total = 0
        # dynacache: windowed hit rate over the last DYN_CACHE_WINDOW
        # admissions — the lifetime ratio above goes flat after enough
        # traffic, so the aggregator gauge reads this recent-traffic view
        # instead (ISSUE 11 satellite; totals stay exported alongside)
        self._hit_window: deque = deque(
            maxlen=max(env_int("DYN_CACHE_WINDOW") or 256, 1))
        # dynaslo: per-role mergeable latency histograms (TTFT, ITL,
        # queue wait, e2e) — host-side counter arithmetic only, shipped
        # via stats() → ForwardPassMetrics.latency_hist and merged by
        # the metrics aggregator into fleet-wide quantiles. The role
        # defaults to "unified"; disagg wrappers relabel via set_role().
        self.latency = slo.LatencyRecorder("unified")
        profiling.register_cache(f"jax-engine-{id(self):x}", self)
        # dynablack: incident bundles fold this engine's stats() (cost
        # table, cache, memory) at capture time — weakly held, cold path
        blackbox.get_recorder().register_stats_source(
            self.worker_label or f"jax-engine-{id(self):x}", self)

    @property
    def role(self) -> str:
        return self.latency.role

    def set_role(self, role: str) -> None:
        """Label this engine's serving role (prefill|decode|unified) for
        the stats plane and latency histograms (dynaslo). Call before
        serving; earlier observations keep their original role."""
        self.latency.role = role

    # ---------------------------------------------------------- lifecycle

    def warmup(self, progress: bool = False, decode: bool = True) -> int:
        """Pre-compile the full bucket grid (prefill T×P, decode B×P,
        sampling per B) so no compile ever happens mid-serving — a
        mid-flight compile stalls every in-flight request for the compile
        latency. Returns the number of programs compiled.
        ``decode=False`` skips the decode-window grid — for prefill-only
        workers (disagg), whose engine never runs a decode step."""
        ecfg = self.ecfg
        # the EXACT reachable shape images (not the declared bucket
        # tuples): _pick doubles past its last bucket, so exotic configs
        # reach shapes the tuples alone would miss — compiling them
        # mid-serving (the compile fence below counts such misses)
        grid = ecfg.warmed_grid()
        page_buckets = grid["page_buckets"] or [8]
        t0 = time.monotonic()
        n = 0
        # under a mesh: the committed (NamedSharding) decode-window carry
        # per batch bucket, captured below to warm the pipelined call
        # forms (see the committed-carry note in the decode loop)
        carries: Dict[int, tuple] = {}
        prefill_bs = grid["prefill_batches"]
        for P in page_buckets:
            for T in grid["prefill_lens"]:
                for PB in prefill_bs:
                    # warm exactly the serving variant: page-granular
                    # commit for ps-aligned buckets, row scatter otherwise
                    pslots = (jnp.full((PB, T // ecfg.page_size),
                                       ecfg.num_pages, jnp.int32)
                              if T % ecfg.page_size == 0 else None)
                    logits, self.kv_k, self.kv_v = self.prefill_fn(
                        self.params, jnp.zeros((PB, T), jnp.int32),
                        jnp.zeros((PB, T), jnp.int32) - 1,
                        self.kv_k, self.kv_v, jnp.zeros((PB, P), jnp.int32),
                        jnp.full((PB, T), DROP_SLOT, jnp.int32),
                        jnp.zeros((PB,), jnp.int32), pslots)
                    # penalties=None EXPLICITLY: the jit cache keys on the
                    # call's (args, kwargs) treedef, so an explicit-None
                    # kwarg and an omitted default are DIFFERENT entries —
                    # _sample_device always passes penalties=, and warming
                    # the omitted form left every serving bucket one
                    # compile short (found by the compile fence)
                    toks = sample_tokens(
                        logits, jnp.zeros(PB),
                        jnp.zeros(PB, jnp.int32), jnp.ones(PB),
                        jnp.zeros(PB, jnp.uint32),
                        jnp.zeros(PB, jnp.int32),
                        max_top_k=ecfg.max_top_k, penalties=None)
                    if ecfg.warmup_logprobs and ecfg.max_top_logprobs > 0:
                        # _sample_device runs logprob_aux EAGERLY after
                        # every prefill/decode dispatch that asked for
                        # logprobs, so its op-by-op executables compile
                        # per logits bucket on the first such request —
                        # a fence trip the jitted-window variants above
                        # don't cover (DL026, same finding class)
                        logprob_aux(logits, toks, ecfg.max_top_logprobs)
                    n += 1
            for B in (grid["decode_batches"] if decode else []):
                tableB = jnp.zeros((B, P), jnp.int32)
                if ecfg.decode_steps > 1:
                    # warm the penalty-free variant always; the penalized
                    # window programs too when warmup_penalties (default:
                    # a first penalty request pays one compile per bucket
                    # mid-serving — documented tradeoff, most deployments
                    # never send penalties and should not double warmup)
                    pen_variants = [None]
                    if ecfg.warmup_penalties:
                        V = self.cfg.vocab_size
                        pen_variants.append((
                            jnp.zeros((B, V), jnp.int32),
                            jnp.zeros((B, V), jnp.int8),
                            jnp.ones(B), jnp.zeros(B), jnp.zeros(B)))
                    # logprobs_topn is a STATIC argname: serving flips it
                    # to max_top_logprobs for any window with a logprobs
                    # request, so each value is its own program per
                    # bucket — warm both or the first logprobs request
                    # compiles mid-serving (DL026 warmup-form-drift)
                    topn_variants = [0]
                    if ecfg.warmup_logprobs and ecfg.max_top_logprobs > 0:
                        topn_variants.append(ecfg.max_top_logprobs)
                    for pv in pen_variants:
                        for topn in topn_variants:
                            # kwargs explicitly, matching the serving
                            # call form in _dispatch_decode_window — the
                            # jit cache distinguishes explicit static
                            # kwargs from omitted defaults (compile-fence
                            # finding, same class as the penalties=None
                            # note above)
                            out = self.decode_multi_fn(
                                self.params, jnp.zeros(B, jnp.int32),
                                jnp.zeros(B, jnp.int32) - 1,
                                jnp.zeros(B, bool), jnp.zeros(B, jnp.int32),
                                jnp.ones(B, jnp.int32), self.kv_k,
                                self.kv_v, tableB, jnp.zeros(B),
                                jnp.zeros(B, jnp.int32),
                                jnp.ones(B), jnp.zeros(B, jnp.uint32),
                                jnp.full((B, ecfg.max_eos_ids), -1,
                                         jnp.int32),
                                pv, k_steps=ecfg.decode_steps,
                                logprobs_topn=topn)
                            if topn:
                                (toks, _emitted, _aux, _carry, self.kv_k,
                                 self.kv_v) = out
                                n += 1
                            else:
                                (toks, _emitted, _carry, self.kv_k,
                                 self.kv_v) = out
                            if pv is None and self.mesh is not None:
                                # committed-carry variant: under a mesh
                                # the pipelined window's (tok, pos, done,
                                # steps, remaining) arrive COMMITTED
                                # (NamedSharding outputs of the previous
                                # window / _merge_carry) while the
                                # host-array call above is uncommitted —
                                # DIFFERENT jit cache entries, so without
                                # this the first chained window would
                                # compile mid-serving (found by the
                                # compile fence on the first sharded
                                # engine). Feed the window its own carry
                                # to warm that variant; save it for the
                                # merge-combo loop below.
                                if topn == 0:
                                    carries[B] = _carry
                                out = self.decode_multi_fn(
                                    self.params, *_carry, self.kv_k,
                                    self.kv_v, tableB, jnp.zeros(B),
                                    jnp.zeros(B, jnp.int32), jnp.ones(B),
                                    jnp.zeros(B, jnp.uint32),
                                    jnp.full((B, ecfg.max_eos_ids), -1,
                                             jnp.int32),
                                    pv, k_steps=ecfg.decode_steps,
                                    logprobs_topn=topn)
                                if topn:
                                    (toks, _emitted, _aux, _carry,
                                     self.kv_k, self.kv_v) = out
                                else:
                                    (toks, _emitted, _carry, self.kv_k,
                                     self.kv_v) = out
                                n += 1
                else:
                    logits, self.kv_k, self.kv_v = self.decode_fn(
                        self.params, jnp.zeros(B, jnp.int32),
                        jnp.zeros(B, jnp.int32) - 1, self.kv_k, self.kv_v,
                        tableB, jnp.full((B,), DROP_SLOT, jnp.int32))
                    toks = sample_tokens(
                        logits, jnp.zeros(B),
                        jnp.zeros(B, jnp.int32),
                        jnp.ones(B), jnp.zeros(B, jnp.uint32),
                        jnp.zeros(B, jnp.int32),
                        max_top_k=ecfg.max_top_k, penalties=None)
                    if ecfg.warmup_logprobs and ecfg.max_top_logprobs > 0:
                        logprob_aux(logits, toks, ecfg.max_top_logprobs)
                if self.verify_fn is not None:
                    # speculative verify grid: one [B, K+1] program per
                    # (B, P) bucket + the accept-mask program per B
                    Kv = ecfg.spec_tokens + 1
                    logits, self.kv_k, self.kv_v = self.verify_fn(
                        self.params, jnp.zeros((B, Kv), jnp.int32),
                        jnp.zeros((B, Kv), jnp.int32) - 1, self.kv_k,
                        self.kv_v, tableB,
                        jnp.full((B, Kv), DROP_SLOT, jnp.int32))
                    verify_greedy_draft(logits,
                                        jnp.zeros((B, Kv - 1), jnp.int32),
                                        jnp.zeros(B, jnp.int32),
                                        max_top_k=ecfg.max_top_k)
                    n += 1
                n += 1
                if progress:
                    print(f"warmup: {n} programs, {time.monotonic()-t0:.0f}s",
                          flush=True)
        # long-context ring-prefill buckets: every padded length a served
        # long prompt can hit, so the first long request never compiles
        # mid-serving (same invariant as the chunked grid)
        if self.long_prefill_fn is not None:
            from ..parallel.ring_attention import scatter_prefill_kv
            t = self._long_bucket(self.ecfg.long_prefill_threshold + 1)
            while True:
                logits, k_all, v_all = self.long_prefill_fn(
                    self.params, jnp.zeros((1, t), jnp.int32),
                    jnp.zeros((1, t), jnp.int32) - 1)
                self.kv_k, self.kv_v = scatter_prefill_kv(
                    self.kv_k, self.kv_v, k_all, v_all,
                    jnp.full((1, t), DROP_SLOT, jnp.int32))
                toks = sample_tokens(
                    logits, jnp.zeros(1), jnp.zeros(1, jnp.int32),
                    jnp.ones(1), jnp.zeros(1, jnp.uint32),
                    jnp.zeros(1, jnp.int32),
                    max_top_k=ecfg.max_top_k, penalties=None)
                if ecfg.warmup_logprobs and ecfg.max_top_logprobs > 0:
                    logprob_aux(logits, toks, ecfg.max_top_logprobs)
                n += 1
                if t >= self.cap_tokens:
                    break
                t *= 2
        # carry-merge combos (tiny programs): window N+1's inputs stitch
        # the previous window's device carry with host rows for newly
        # admitted sequences — one compile per (B_prev, B_new) pair
        if decode and ecfg.decode_steps > 1 and ecfg.pipeline_decode:
            bset = grid["decode_batches"]
            for Bp in bset:
                # under a mesh the in-flight window's carry is COMMITTED
                # (NamedSharding) — warm the merge with the real warmed
                # carry so serving's exact sharding mix (committed carry
                # + uncommitted host rows) hits the cache (unsharded
                # engines keep the host-zeros form: committed and
                # uncommitted coincide on one device)
                carry = carries.get(Bp) if self.mesh is not None else None
                if carry is None:
                    carry = (jnp.zeros(Bp, jnp.int32),
                             jnp.zeros(Bp, jnp.int32),
                             jnp.zeros(Bp, bool), jnp.zeros(Bp, jnp.int32),
                             jnp.ones(Bp, jnp.int32))
                for Bn in bset:
                    _merge_carry(*carry, jnp.zeros(Bn, jnp.int32),
                                 jnp.zeros(Bn, bool),
                                 jnp.zeros(Bn, jnp.int32),
                                 jnp.zeros(Bn, jnp.int32) - 1,
                                 jnp.zeros(Bn, jnp.int32),
                                 jnp.ones(Bn, jnp.int32))
                    n += 1
        # host-tier copy programs: offload gathers / restore scatters run
        # MID-SERVING on pow2-padded page batches (engine._drain_kv_tier)
        # — warm every reachable pow2 size so the first eviction/restore
        # under load never compiles (the dynajit warmup-coverage check
        # pins these entries to this loop)
        if self.host_k is not None:
            size = 1
            while True:
                idx = jnp.zeros(size, jnp.int32)
                # the serving drain builds its index operands as
                # jnp.asarray(<python list>, jnp.int32) — a DIFFERENT
                # lowering (convert_element_type) from zeros/full above,
                # one tiny program per distinct padded length. Warm that
                # call form too, or the first drain of each pow2 size
                # compiles mid-serving (compile-fence finding on the
                # cache A/B arms).
                jax.block_until_ready(jnp.asarray([0] * size, jnp.int32))
                # both pools: their page shapes differ per model family
                # (MLA latent vs rope), so each is its own program set
                for pool_attr in ("kv_k", "kv_v"):
                    g = _gather_pages(getattr(self, pool_attr), idx)
                    if self.ecfg.host_tier_int8:
                        from .kv_compress import (dequantize_pages,
                                                  quantize_pages)

                        q, s = quantize_pages(g)
                        if self.mesh is not None:
                            # serving restores dequantize UNCOMMITTED
                            # host arrays; under a mesh the committed
                            # quantize outputs here are a different jit
                            # cache entry — round-trip through the host
                            # so warmup matches the serving call form
                            q = jnp.asarray(np.asarray(q))  # dynalint: disable=implicit-host-transfer
                            s = jnp.asarray(np.asarray(s))  # dynalint: disable=implicit-host-transfer
                        rows = dequantize_pages(q, s)
                    else:
                        rows = g
                        if self.mesh is not None:
                            # same committed-vs-uncommitted note: serving
                            # restores inject np views of the host pool.
                            # Warmup-time sync, not a hot-path leak.
                            rows = jnp.asarray(np.asarray(rows))  # dynalint: disable=implicit-host-transfer
                    setattr(self, pool_attr, _inject_pages(
                        getattr(self, pool_attr),
                        jnp.full((size,), ecfg.num_pages, jnp.int32),
                        rows))
                    n += 1
                if size >= self.ecfg.num_pages:
                    break
                size *= 2
        jax.block_until_ready(self.kv_k)
        # arm the runtime compile fence: from here on, ANY XLA compile is
        # a serving stall — counted always, warn/raise per DYN_JIT_FENCE
        self.fence.arm()
        log.info("warmup compiled %d programs in %.1fs", n,
                 time.monotonic() - t0)
        return n

    def start(self) -> None:
        if self._loop_task is None:
            self._aio_loop = asyncio.get_running_loop()
            self._aio_loop_tid = threading.get_ident()
            # dynaprof: the serving loop gets a lag monitor + stall
            # watchdog for as long as an engine runs on it (refcounted;
            # stop() releases)
            profiling.acquire_loop_profiler()
            self._loop_task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        self._stopped = True
        self._wake.set()
        if self._loop_task:
            await self._loop_task
            await profiling.release_loop_profiler()
        self._exec.shutdown(wait=False)

    async def drain(self, timeout_s: float = 10.0) -> bool:
        """dynarevive graceful drain: refuse new work (``generate``
        raises typed NoCapacity) and run every in-flight sequence to its
        natural finish, bounded by ``timeout_s``. On timeout, leftovers
        are cancelled on the normal cancel path (pages free, clients get
        a "cancelled" finish). Returns True when everything finished
        inside the budget. The engine keeps running — call ``stop()``
        afterwards to end the scheduler loop."""
        self.draining = True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(timeout_s, 0.0)

        def busy() -> bool:
            return bool(self.waiting or self.prefilling or self.running
                        or self._inflight or self._pending_prefill)

        while busy() and loop.time() < deadline:
            await asyncio.sleep(0.02)
        drained = not busy()
        if not drained:
            log.warning("engine drain timed out with work in flight "
                        "(waiting=%d prefilling=%d running=%d); "
                        "cancelling leftovers", len(self.waiting),
                        len(self.prefilling), len(self.running))
            for seq in self.waiting + self.prefilling + self.running:
                seq.context.kill()
            self._wake.set()
        return drained

    # ------------------------------------------------------ AsyncEngine API

    async def generate(self, request: PreprocessedRequest,
                       context: Context) -> AsyncIterator[EngineOutput]:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.from_dict(request)
        if self.draining:
            # typed refusal (HTTP 503 + Retry-After upstream): a
            # draining engine admits nothing new while in-flight
            # sequences finish
            raise guard.NoCapacity("engine draining")
        self.start()
        if self.worker_label or self.mesh_devices > 1:
            # dynashard: stamp which replica/submesh serves this request
            # on the enclosing span (serve.generate_tokens on a worker,
            # http.request when served in-process)
            span = tracing.current_span()
            if span is not None:
                span.set_attribute("replica", self.worker_label)
                span.set_attribute("mesh_shape", self.mesh_shape)
        seq = Sequence(req=request, context=context, out=asyncio.Queue(),
                       tokens=list(request.token_ids),
                       num_prompt=len(request.token_ids))
        if seq.num_prompt == 0:
            yield EngineOutput(finish_reason="error", text="empty prompt")
            return
        self.waiting.append(seq)
        self._wake.set()
        while True:
            out: EngineOutput = await seq.out.get()
            yield out
            if out.finish_reason is not None:
                return

    # ------------------------------------------------------------- metrics

    def stats(self) -> dict:
        """ForwardPassMetrics analog for the KV router
        (reference kv_router/protocols.rs:18-30). Keys here that match
        ForwardPassMetrics field names ride the stats plane into the
        metrics aggregator's dyn_worker_*/dyn_engine_* gauges."""
        lag = profiling.loop_lag_snapshot()
        return {
            # dynashard replica identity: the stable per-replica label +
            # submesh geometry ride the stats plane so the aggregator can
            # label gauges per replica (instance ids alone are unstable
            # lease hex) and dashboards can split by mesh size
            "worker_label": self.worker_label,
            "mesh_shape": self.mesh_shape,
            "mesh_devices": self.mesh_devices,
            # dynaslo: serving role + per-role mergeable latency
            # histograms (TTFT/ITL/queue-wait/e2e) — the aggregator
            # merges these across workers into fleet-wide quantiles
            "role": self.role,
            "latency_hist": self.latency.to_wire(),
            # dynaprof: loop health + sampled device/host split +
            # per-bucket program costs + page-pool occupancy
            "loop_lag_p50_seconds": lag["p50_s"],
            "loop_lag_p99_seconds": lag["p99_s"],
            "device_time_fraction":
                round(self.profiler.device_time_fraction(), 4),
            "profiled_steps_total": self.profiler.profiled_steps,
            "bucket_cost": self.profiler.cost_table(),
            "batch_dispatches_total": self.batch_dispatches_total,
            "kv_free_blocks": len(self.pm.free),
            "kv_cached_blocks": len(self.pm.reusable),
            "host_free_blocks": len(self.pm.host_free),
            "memory": memory_snapshot(self.pm, self._page_bytes),
            "request_active_slots": len(self.running) + len(self.prefilling),
            "request_total_slots": self.ecfg.max_batch,
            "kv_active_blocks": self.pm.active,
            "kv_total_blocks": self.ecfg.num_pages - 1,
            "num_requests_waiting": len(self.waiting),
            "queue_wait_seconds_total": round(self.queue_wait_seconds_total,
                                              4),
            "gpu_cache_usage_perc": self.pm.usage(),
            # dynacache: the headline rate is WINDOWED (last
            # DYN_CACHE_WINDOW admissions) so the aggregator gauge tracks
            # recent traffic instead of flattening into the lifetime mean;
            # the cumulative counters ride alongside for totals/rates
            "gpu_prefix_cache_hit_rate": self._windowed_hit_rate(),
            "gpu_prefix_cache_hit_rate_lifetime":
                (self.prefix_hit_tokens_total /
                 max(self.prompt_tokens_total, 1)),
            "prefix_hit_tokens_total": self.prefix_hit_tokens_total,
            "prompt_tokens_total": self.prompt_tokens_total,
            **{f"cache_{k}": v
               for k, v in self.pm.cache_stats().items()},
            "host_cache_usage_perc": self.pm.host_usage(),
            "host_offload_pages_total": self.offload_pages_total,
            "host_restore_pages_total": self.restore_pages_total,
            "long_prefills_total": self.long_prefills_total,
            # compile fence: XLA compiles observed after warmup() armed
            # the fence (0 = the zero-compile serving invariant holds)
            "post_warmup_compiles_total": self.fence.post_warmup_compiles,
            # speculative decode observability: acceptance rate is
            # accepted/drafted (drafter quality); mean accepted length is
            # accepted drafts per verify step (tokens-per-dispatch gain —
            # each step also emits its bonus token on top)
            "spec_decode_steps": self.spec_steps,
            "spec_decode_draft_tokens_total": self.spec_draft_tokens_total,
            "spec_decode_accepted_tokens_total":
                self.spec_accepted_tokens_total,
            "spec_decode_acceptance_rate":
                (self.spec_accepted_tokens_total /
                 max(self.spec_draft_tokens_total, 1)),
            "spec_decode_mean_accepted_len":
                (self.spec_accepted_tokens_total /
                 max(self.spec_steps, 1)),
        }

    def _windowed_hit_rate(self) -> float:
        """Prefix-hit tokens / prompt tokens over the admission window
        (0.0 while empty). One pass over a bounded deque — cheap enough
        for every stats scrape."""
        hit = total = 0
        for h, p in self._hit_window:
            hit += h
            total += p
        return hit / total if total else 0.0

    def cache_snapshot(self) -> dict:
        """dynacache /debug/cache view: pool + host-tier occupancy, the
        allocation/eviction/restore counters, windowed vs lifetime hit
        rate, and the bounded top-K hot prefix chains."""
        topk = max(env_int("DYN_CACHE_TOPK") or 20, 0)
        with self._pm_lock:
            pm = self.pm
            snap = {
                "pool": {
                    "total_blocks": self.ecfg.num_pages - 1,
                    "active_blocks": pm.active,
                    "cached_blocks": len(pm.reusable),
                    "free_blocks": len(pm.free),
                    "usage": round(pm.usage(), 4),
                },
                "host_tier": {
                    "total_blocks": pm.host_pages,
                    "used_blocks": len(pm.host_by_hash),
                    "free_blocks": len(pm.host_free),
                    "usage": round(pm.host_usage(), 4),
                },
                "hit_rate_windowed": round(self._windowed_hit_rate(), 4),
                "hit_rate_lifetime": round(
                    self.prefix_hit_tokens_total
                    / max(self.prompt_tokens_total, 1), 4),
                "prefix_hit_tokens_total": self.prefix_hit_tokens_total,
                "prompt_tokens_total": self.prompt_tokens_total,
                **pm.cache_stats(),
                "top_prefixes": pm.top_prefixes(topk),
            }
        return snap

    # ------------------------------------------------------- scheduler loop

    async def _loop(self) -> None:
        loop = asyncio.get_running_loop()
        # `await run_in_executor` suspends this coroutine at least once
        # per iteration (the step future is never done at await time), so
        # the event loop already drains its ready queue every step. The
        # historical unconditional `asyncio.sleep(0)` on top of that only
        # bought a second scheduling round-trip per iteration — measured
        # loop-lag p99 before/after in docs/hot_path.md. DYN_LOOP_YIELD=1
        # restores it for A/B.
        extra_yield = env_flag("DYN_LOOP_YIELD")
        while not self._stopped:
            if not (self.waiting or self.prefilling or self.running
                    or self._inflight or self._pending_prefill):
                self._wake.clear()
                await self._wake.wait()
                continue
            if guard.chaos() is not None:
                # worker-scoped chaos (dynarevive): a delay rule on
                # `engine.stall` freezes the scheduler loop for its ms —
                # the kill-mid-decode / stalled-worker scenarios in the
                # same seeded grammar as the transport faults. The
                # `guard.chaos() is not None` gate keeps the hot path
                # free of the coroutine when no chaos is configured.
                await guard.chaos_point("engine.stall")
            try:
                if not self.ecfg.admit_in_step:
                    # legacy placement: admission host work serializes
                    # ahead of the step on the event-loop thread
                    self._admit()
                await loop.run_in_executor(self._exec, self._step)
                self._reap()
            except Exception:  # noqa: BLE001 — engine loop must survive
                log.exception("engine step failed")
                await loop.run_in_executor(self._exec, self._abort_all)
            if extra_yield:
                await asyncio.sleep(0)
        # shutdown: drain in-flight windows so no client hangs on a queue
        if self._inflight or self._pending_prefill:
            try:
                await loop.run_in_executor(self._exec, self._flush_pipeline)
            except Exception:  # noqa: BLE001
                log.exception("pipeline flush on stop failed")

    def _step(self) -> None:
        """One scheduler iteration (executor thread). Pipelined mode
        enqueues the next decode window AND the next prefill chunk before
        reading back the previous window/prefill, so the host round-trip
        (the dominant cost on dispatch-latency-bound setups) overlaps
        device compute. Unpipelined modes keep the reference-equivalent
        prefill-priority ordering."""
        self.profiler.tick()  # dynaprof: one compare at sample=0
        self._drain_kv_tier()
        if self.verify_fn is not None:
            if self.ecfg.admit_in_step:
                self._admit_in_step()
            self._step_spec()
            return
        if self.ecfg.decode_steps <= 1:
            # single-step decode: fully synchronous; budgeted mixing
            # interleaves a decode step behind the trimmed prefill batch
            if self.ecfg.admit_in_step:
                self._admit_in_step()
            budget = self.ecfg.prefill_token_budget
            if self.prefilling:
                pf = self._dispatch_prefill(budget)
                if pf is not None:
                    self._process_prefill(pf)
            if self.running and (budget is not None
                                 or not self.prefilling):
                if budget is not None and self.prefilling:
                    self.mixed_dispatches += 1
                self._decode_step_single()
            return
        if not self.ecfg.pipeline_decode:
            if self.ecfg.admit_in_step:
                self._admit_in_step()
            budget = self.ecfg.prefill_token_budget
            if self.prefilling:
                pf = self._dispatch_prefill(budget)
                if pf is not None:
                    self._process_prefill(pf)
            if self.running and (budget is not None
                                 or not self.prefilling):
                if budget is not None and self.prefilling:
                    self.mixed_dispatches += 1
                pend = self._dispatch_decode_window()
                if pend is not None:
                    self._process_window(pend)
            self._drain_deferred()
            return
        prev = self._pending
        prev_pf = self._pending_prefill
        budget = self.ecfg.prefill_token_budget
        if (budget is None and self.ecfg.prefill_priority
                and self.prefilling):
            # prefill-priority: prompt batches drain at full cadence. But
            # when the sweep dispatches NOTHING (every candidate
            # restore-gated, cancelled, or cache-covered) the device
            # would idle a whole iteration — fill the bubble with a
            # decode window (overlap_idle_prefill). TTFT is untouched:
            # iterations that actually ship a prefill still skip it.
            self._pending_prefill = self._dispatch_prefill(budget)
            if (self._pending_prefill is None
                    and self.ecfg.overlap_idle_prefill):
                self._pending = self._dispatch_decode_window()
            else:
                self._pending = None
        else:
            # budgeted mixing (or prefill_priority off): decode windows
            # keep their cadence even while prompts are prefilling
            self._pending = self._dispatch_decode_window()
            self._pending_prefill = self._dispatch_prefill(budget)
            if (self._pending is not None
                    and self._pending_prefill is not None):
                self.mixed_dispatches += 1
        if self.ecfg.admit_in_step:
            # admission lands AFTER the dispatches: its host work
            # (bucketing, page reservation, prefix hashing) overlaps the
            # in-flight window's device compute instead of serializing
            # ahead of the dispatch on the event-loop thread. Admitted
            # sequences enter prefilling for the next iteration's sweep.
            self._admit_in_step()
        if prev is not None:
            self._process_window(prev)
        if prev_pf is not None:
            self._process_prefill(prev_pf)
        self._drain_deferred()
        # idle drain: with no live work left, read back the remaining
        # windows now so final tokens/finishes emit and pages free
        if (not (self.running or self.prefilling or self.waiting)
                and (self._inflight or self._pending_prefill)):
            self._flush_pipeline()

    def _flush_pipeline(self) -> None:
        """Synchronize: read back every in-flight window/prefill so host
        state is current and all page releases are safe. Called before
        preemption (pool pressure), on shutdown, and by disagg jobs that
        need exclusive page ownership."""
        for w in list(self._inflight):
            self._process_window(w)
        self._pending = None
        if self._pending_prefill is not None:
            self._process_prefill(self._pending_prefill)
            self._pending_prefill = None
        self._drain_deferred()

    def _abort_all(self) -> None:
        """Error path: drop pipeline state, release everything, fail all
        in-flight requests (the loop itself must survive). Covers the
        sequences parked OUTSIDE prefilling/running: deferred frees and a
        pending prefill's finishing rows — dropping either would hang its
        client on a queue that never sees a finish_reason."""
        try:
            jax.block_until_ready(self.kv_k)
        except Exception:  # noqa: BLE001
            pass
        # land inflight offload gathers: their host slots are already
        # hash-mapped, so abandoning them would leave stale host content
        # a future restore could read
        try:
            self._land_inflight_offloads(self._offload_inflight)
        except Exception:  # noqa: BLE001
            pass
        self._offload_inflight.clear()
        parked = list(self._deferred_free)
        if self._pending_prefill is not None:
            parked += [s for _, s in self._pending_prefill.finishing]
        self._inflight.clear()
        self._pending = None
        self._pending_prefill = None
        self._deferred_free.clear()
        for seq in parked + self.prefilling + self.running:
            self._release(seq)
            self._finish(seq, "error")
        self.prefilling.clear()
        self.running.clear()

    # ----------------------------------------------------------- admission

    def _admit(self) -> None:
        while self.waiting and (len(self.running) + len(self.prefilling)
                                < self.ecfg.max_batch):
            seq = self.waiting[0]
            if seq.context.stopped:
                self.waiting.pop(0)
                self._finish(seq, _cancel_reason(seq.context))
                continue
            if seq.num_prompt >= self.cap_tokens:
                # admission is clamped to the warmed bucket grid: a prompt
                # needing more pages than the largest page bucket would
                # force a fresh XLA compile mid-serving (VERDICT r2 weak
                # #6) — reject instead (long prompts route to the
                # sequence-parallel ring-prefill path when configured)
                self.waiting.pop(0)
                self._emit(seq, EngineOutput(
                    token_ids=[],
                    text=f"prompt length {seq.num_prompt} exceeds engine "
                         f"context capacity {self.cap_tokens}"))
                self._finish(seq, "error")
                continue
            chain = self._chain(seq)
            with self._pm_lock:
                alloc = self.pm.allocate_sequence(seq.tokens, chain=chain)
                if (alloc is None
                        or self.pm.available < self.ecfg.watermark_pages):
                    if alloc is not None:
                        self.pm.release_sequence(alloc[0])
                    break  # out of pages; wait for frees
                if alloc.restores:
                    # gate this sequence out of prefill until its
                    # host→HBM restores have dispatched (chunked drain)
                    self._unrestored_pages.update(
                        p for p, _ in alloc.restores)
            self.waiting.pop(0)
            pages, cached_tokens = alloc
            seq.pages = pages
            seq.computed = min(cached_tokens, seq.prefill_extent)
            if alloc.restores:
                # restore_wait stops when the sequence clears the
                # _unrestored_pages gate in _dispatch_prefill
                seq.restore_t0 = time.monotonic()
            if seq.generated == 0:  # don't double-count resumed sequences
                wait = time.monotonic() - seq.arrival
                self.queue_wait_seconds_total += wait
                seq.queue_wait_s = wait
                self.latency.observe("queue_wait", wait)
                seq.prefix_hit = seq.computed
                seq.device_hit_blocks = alloc.device_hit_blocks
                seq.host_restored_blocks = alloc.host_restored_blocks
                self.step_timeline.add(
                    "admit", queue_wait_ms=round(wait * 1000.0, 3),
                    request_id=seq.context.id,
                    occupancy=len(self.running) + len(self.prefilling) + 1,
                    waiting=len(self.waiting))
                self.prefix_hit_tokens_total += seq.computed
                self.prompt_tokens_total += seq.num_prompt
                self._hit_window.append((seq.computed, seq.num_prompt))
            # proto: request.lifecycle admitted->prefill
            self.prefilling.append(seq)

    def _admit_in_step(self) -> None:
        """Admission on the executor thread (admit_in_step), bracketed as
        its own cost-table row so the host segment it moves off the
        event-loop thread stays visible under --prof-sample. The guard
        keeps the common no-waiters iteration at one compare."""
        if not self.waiting:
            return
        at0 = self.profiler.begin()
        self._admit()
        self.profiler.end(at0, "admit", ("host",))

    # ------------------------------------------------------- KV tier drain

    def _land_inflight_offloads(self, entries) -> None:
        """Copy parked offload gathers into the host pool (the D2H
        readback that overlapped the intervening device steps). Under
        host_tier_int8 each entry carries (q, s) pairs — quantized on
        device before the D2H, so these np.asarray reads move int8."""
        for k_dev, v_dev, oslots, n in entries:
            if self.ecfg.host_tier_int8:
                (kq, ks), (vq, vs) = k_dev, v_dev
                self.host_k[:, oslots] = np.asarray(kq)[:, :n]
                self.host_k_s[:, oslots] = np.asarray(ks)[:, :n]
                self.host_v[:, oslots] = np.asarray(vq)[:, :n]
                self.host_v_s[:, oslots] = np.asarray(vs)[:, :n]
            else:
                self.host_k[:, oslots] = np.asarray(k_dev)[:, :n]
                self.host_v[:, oslots] = np.asarray(v_dev)[:, :n]

    def _drain_kv_tier(self, full: bool = False) -> None:
        """Run queued HBM↔host page copies (executor thread, before any
        device step so offloads read pre-step content and restores land
        before their pages are attended to). Batched, pow2-padded gathers
        keep the compile count logarithmic in batch size.

        Overlap strategy (relay-attached chips pay ~0.5 s per host
        round-trip): offload gathers dispatch WITHOUT a synchronous
        readback — the device arrays park in ``_offload_inflight`` and
        are copied to the host pool on a LATER drain, overlapping the
        intervening device step. Restores are chunked
        (``tier_restore_chunk`` per iteration) so a bulk restore cannot
        stall every other request; their sequences stay gated via
        ``_unrestored_pages`` until the copy dispatches.

        With ``restore_overlap`` the drained batch is PIPELINED: its
        host-slot gather + H2D + dequantize dispatch now, but the page
        inject lands at the START of the next drain — the transfer gets
        the whole intervening device step to complete instead of
        stalling it. Staged pages stay in ``_unrestored_pages`` until
        injected; rows whose page was recycled in between are remapped
        to the out-of-range pad target at inject time (the scatter
        drops them), so a late inject can never clobber a reallocated
        page.

        ``full=True`` drains EVERYTHING now — required by the paths that
        hand pages to a consumer with no later drain between (disagg
        reserve/extract/inject)."""
        if self.host_k is None:
            return
        chunk = None if full else (self.ecfg.tier_restore_chunk or None)
        # land the previous drain's staged restore batch FIRST: its H2D
        # overlapped the intervening step, so this inject is cheap
        if self._restore_staged is not None:
            self._inject_staged()
        with self._pm_lock:
            off, res = self.pm.drain_tier_ops(restore_limit=chunk)
            # block hash per drained page, captured under the lock — the
            # inject-time validity check compares against by_hash
            res_hashes = [self.pm.pages[p].block_hash for p, _ in res]
            # the gate set mirrors the still-queued restores exactly —
            # this also un-gates pages whose stale restore _pop_fresh
            # cancelled on reallocation (their new owner must not wait
            # for a copy that will never run). Newly staged pages are
            # added back below.
            self._unrestored_pages = {p for p, _ in
                                      self.pm.pending_restore}
        if off:
            pages = [p for p, _ in off]
            slots = [s for _, s in off]
            idx = jnp.asarray(_pad_pow2(pages, 0), jnp.int32)
            # dispatch only — no np.asarray round-trip here
            k_dev = _gather_pages(self.kv_k, idx)
            v_dev = _gather_pages(self.kv_v, idx)
            if self.ecfg.host_tier_int8:
                from .kv_compress import quantize_pages

                k_dev = quantize_pages(k_dev)  # (q, s) device pair
                v_dev = quantize_pages(v_dev)
            self._offload_inflight.append((k_dev, v_dev, slots, len(off)))
            self.offload_pages_total += len(off)
        # harvest offload gathers whose D2H overlapped earlier steps. With
        # restores about to run, EVERY inflight offload must land first (a
        # restore may read a slot whose content is still in flight);
        # otherwise keep the newest gather in flight to overlap the next
        # step
        land_all = bool(res) or full
        if self._offload_inflight and (land_all
                                       or len(self._offload_inflight) > 1):
            keep = [] if land_all else self._offload_inflight[-1:]
            harvest = (self._offload_inflight if land_all
                       else self._offload_inflight[:-1])
            self._offload_inflight = keep
            self._land_inflight_offloads(harvest)
        if res:
            rt0 = time.perf_counter()
            pages = [p for p, _ in res]
            slots = [s for _, s in res]
            # pad the host gather with slot 0 (content discarded)
            hsl = _pad_pow2(slots, 0)
            if self.ecfg.host_tier_int8:
                # H2D moves int8 + scales; dequant runs on device
                from .kv_compress import dequantize_pages

                k_rows = dequantize_pages(
                    jnp.asarray(self.host_k[:, hsl]),
                    jnp.asarray(self.host_k_s[:, hsl]))
                v_rows = dequantize_pages(
                    jnp.asarray(self.host_v[:, hsl]),
                    jnp.asarray(self.host_v_s[:, hsl]))
            else:
                k_rows = jnp.asarray(self.host_k[:, hsl])
                v_rows = jnp.asarray(self.host_v[:, hsl])
            overlap = bool(self.ecfg.restore_overlap) and not full
            if overlap:
                # pipeline: park the in-flight rows; the inject lands at
                # the start of the NEXT drain. Pages stay gated.
                self._restore_staged = (pages, res_hashes, k_rows, v_rows)
                self._unrestored_pages.update(pages)
            else:
                # serial (A/B control / full drain): inject in the same
                # drain. Pad targets out-of-range → dropped by the
                # scatter.
                idx = _pad_pow2(pages, self.ecfg.num_pages)
                iidx = jnp.asarray(idx, jnp.int32)
                self.kv_k = _inject_pages(self.kv_k, iidx, k_rows)
                self.kv_v = _inject_pages(self.kv_v, iidx, v_rows)
            self.restore_pages_total += len(res)
            # dynacache: restore drain visibility — a step-timeline event
            # and a dyntrace span per drained batch (dispatch time only;
            # no sync added — the copies land with the next device step).
            # Both are no-ops when their ring/sampling is off.
            rdt = time.perf_counter() - rt0
            self.step_timeline.add(
                "cache.restore", pages=len(res),
                queued=len(self._unrestored_pages),
                staged=int(overlap),
                dispatch_ms=round(rdt * 1000.0, 3))
            tracing.get_tracer().record_span(
                "cache.restore", rdt, parent=None,
                attributes={"pages": len(res), "staged": overlap,
                            "queued": len(self._unrestored_pages)})

    def _inject_staged(self) -> None:
        """Land the staged restore batch (restore_overlap second half).
        Rows whose page was recycled since staging (sequence released
        and the page re-popped — its hash no longer maps to it) are
        remapped to the out-of-range pad target so the scatter drops
        them; their content now belongs to someone else."""
        pages, hashes, k_rows, v_rows = self._restore_staged
        self._restore_staged = None
        with self._pm_lock:
            tgt = [p if self.pm.by_hash.get(h) == p else self.ecfg.num_pages
                   for p, h in zip(pages, hashes)]
        iidx = jnp.asarray(_pad_pow2(tgt, self.ecfg.num_pages), jnp.int32)
        self.kv_k = _inject_pages(self.kv_k, iidx, k_rows)
        self.kv_v = _inject_pages(self.kv_v, iidx, v_rows)
        self._unrestored_pages.difference_update(pages)

    # ------------------------------------------------------------- prefill

    def _dispatch_prefill(self, token_budget: Optional[int] = None
                          ) -> Optional[_PendingPrefill]:
        """Enqueue one chunked-prefill step over a BATCH of prefilling
        sequences (each contributes its next chunk) WITHOUT reading back.
        Batching prompts into one dispatch matters as much as the decode
        window when dispatch latency dominates: N prompts cost one round
        trip, not N — and under pipelining that round trip overlaps the
        in-flight decode window."""
        candidates: List[Sequence] = []
        for seq in list(self.prefilling):
            if seq.context.stopped:
                self.prefilling.remove(seq)
                self._terminate(seq, _cancel_reason(seq.context))
                continue
            if self._unrestored_pages and not self._unrestored_pages.isdisjoint(
                    seq.pages):
                # host-tier restores for this sequence are still queued
                # (chunked drain): computing on its pages now would read
                # stale KV. It waits; the drain clears a chunk per
                # iteration
                continue
            if seq.restore_t0 is not None:
                # dynacache: the sequence's host-tier restores have all
                # dispatched — admission→here is its restore wait
                seq.restore_wait_s = time.monotonic() - seq.restore_t0
                seq.restore_t0 = None
            if seq.prefill_extent - seq.computed <= 0:
                # resumed sequence fully covered by the prefix cache
                self.prefilling.remove(seq)
                seq.last_token = seq.tokens[-1]
                # proto: request.lifecycle prefill->decode
                self.running.append(seq)
                continue
            if (self.long_prefill_fn is not None
                    and seq.prefill_extent - seq.computed
                    > self.ecfg.long_prefill_threshold):
                # sequence-parallel ring prefill: one dispatch for the
                # whole prompt, sharded over the mesh's seq axis
                self.prefilling.remove(seq)
                self._long_prefill(seq)
                continue
            candidates.append(seq)
        if not candidates:
            return None
        # bucket-homogeneous batching: the dispatch pads every row to the
        # LARGEST member's (T, P) bucket, so one long prompt in a batch of
        # short ones multiplies the whole batch's padded attention flops.
        # Keep FIFO fairness for the head, then prefer its bucket-mates.
        head = candidates[0]

        def tbucket(s):
            return self.ecfg.bucket_len(
                min(s.prefill_extent - s.computed, self.ecfg.prefill_chunk))

        hb = tbucket(head)
        # fill with the head's bucket-mates, then with SMALLER-bucket
        # prompts only (they ride along without raising T; a larger-bucket
        # member would promote every row's padded attention to its bucket)
        mates = [s for s in candidates[1:] if tbucket(s) == hb]
        picked = {id(head)} | {id(s) for s in mates}
        batch = [head] + mates
        batch += [s for s in candidates[1:]
                  if id(s) not in picked and tbucket(s) < hb]
        batch = batch[: self.ecfg.max_prefill_batch]
        if token_budget is not None:
            # budgeted mixing: trim the batch to ~token_budget prompt
            # tokens. The head always ships (its chunk may alone exceed a
            # small budget — per-iteration prefill is then bounded by
            # max(prefill_chunk, budget), keeping chunk starts page-aligned
            # rather than slicing mid-chunk)
            kept, total = [], 0
            for s in batch:
                c = min(s.prefill_extent - s.computed,
                        self.ecfg.prefill_chunk)
                if kept and total + c > token_budget:
                    break
                kept.append(s)
                total += c
            batch = kept

        chunks = [min(s.prefill_extent - s.computed, self.ecfg.prefill_chunk)
                  for s in batch]
        B = self.ecfg.prefill_bucket_batch(len(batch))
        T = self.ecfg.bucket_len(max(chunks))
        P = self.ecfg.bucket_pages(max(len(s.pages) for s in batch))

        tokens = np.zeros((B, T), np.int32)
        positions = np.full((B, T), -1, np.int32)
        table = np.zeros((B, P), np.int32)
        last_idx = np.zeros(B, np.int32)
        ps = self.ecfg.page_size
        # page-granular KV commit when the bucket is page-aligned AND every
        # chunk start is (prefix hits are whole pages and chunk sizes are
        # ps-multiples, so misalignment means an exotic config slipped past
        # __post_init__ — fall back to the row scatter rather than crash)
        use_paged = (T % ps == 0
                     and all(s.computed % ps == 0 for s in batch))
        slots = np.full((B, T), DROP_SLOT, np.int32)
        pslots = np.full((B, max(T // ps, 1)), self.ecfg.num_pages, np.int32)
        for i, (seq, chunk) in enumerate(zip(batch, chunks)):
            start = seq.computed
            tokens[i, :chunk] = seq.tokens[start:start + chunk]
            positions[i, :chunk] = np.arange(start, start + chunk)
            pages = np.asarray(seq.pages, np.int64)
            table[i, :len(seq.pages)] = seq.pages
            last_idx[i] = chunk - 1
            # flat slots are always built: model modules without a paged
            # commit path (MLA's latent cache) ignore page_slots and use
            # these; llama ignores them when page_slots is present
            pos = np.arange(start, start + chunk)
            slots[i, :chunk] = pages[pos // ps] * ps + pos % ps
            if use_paged:
                first = start // ps
                npg = (chunk + ps - 1) // ps
                pslots[i, :npg] = pages[first:first + npg]

        pt0 = self.profiler.begin()
        logits, self.kv_k, self.kv_v = self.prefill_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(positions),
            self.kv_k, self.kv_v, jnp.asarray(table), jnp.asarray(slots),
            jnp.asarray(last_idx),
            jnp.asarray(pslots) if use_paged else None)
        self.profiler.end(pt0, "prefill", (B, T, P),
                          tokens=int(sum(chunks)), sync_ref=logits)
        self._account_dispatch(batch)
        self.steps += 1
        self.step_timeline.add(
            "prefill", batch=len(batch), tokens=int(sum(chunks)),
            occupancy=len(self.running) + len(self.prefilling),
            waiting=len(self.waiting))

        finishing: List[Tuple[int, Sequence]] = []
        for i, (seq, chunk) in enumerate(zip(batch, chunks)):
            seq.computed += chunk
            self.prefill_tokens_total += chunk
            if seq.computed >= seq.prefill_extent:
                self.prefilling.remove(seq)
                finishing.append((i, seq))
        if not finishing:
            # a chunk dispatch with nothing to read back still returns a
            # (finishing-empty) marker: _step must distinguish
            # "dispatched, mid-prompt" from "dispatched nothing" so
            # prefill-priority only skips the decode window on iterations
            # that actually shipped prefill work
            return _PendingPrefill(finishing=[], sampled=None)
        # one on-device sampling pass over the full bucket (avoids a fresh
        # compile per finishing-count); skipped entirely when every
        # finishing row is a preemption-resume (next token already sampled)
        if any(s.generated == 0 for _, s in finishing):
            sampled, aux = self._sample_device(batch, logits)
        else:
            sampled, aux = None, None
        return _PendingPrefill(finishing=finishing, sampled=sampled,
                               aux=aux)

    def _long_prefill(self, seq: Sequence) -> None:
        """Whole-prompt sequence-parallel prefill via ring attention: run
        the seq-sharded stack over the padded prompt, scatter the per-layer
        K/V into the paged pool, sample the first token. Synchronous (one
        dispatch covers thousands of tokens, so the pipelining that hides
        per-window round-trips buys little here)."""
        from ..parallel.ring_attention import scatter_prefill_kv

        extent = seq.prefill_extent
        ps = self.ecfg.page_size
        T = self._long_bucket(extent)
        tokens = np.zeros((1, T), np.int32)
        positions = np.full((1, T), -1, np.int32)
        tokens[0, :extent] = seq.tokens[:extent]
        positions[0, :extent] = np.arange(extent)
        pt0 = self.profiler.begin()
        logits, k_all, v_all = self.long_prefill_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(positions))
        self.profiler.end(pt0, "long_prefill", (T,),
                          tokens=extent - seq.computed, sync_ref=logits)
        self._account_dispatch([seq])
        pages = np.asarray(seq.pages, np.int64)
        pos = np.arange(T)
        # positions below seq.computed are prefix-cache hits living in
        # pages SHARED with other sequences — the ring pass recomputes
        # them (whole-prompt program; the math needs their K/V in flight)
        # but must NOT write them back: FP accumulation-order differences
        # vs the committed content would mutate pages another decoding
        # sequence is attending to
        writable = (pos >= seq.computed) & (pos < extent)
        slots = np.where(writable,
                         pages[np.minimum(pos // ps, len(pages) - 1)] * ps
                         + pos % ps, DROP_SLOT)[None, :]
        self.kv_k, self.kv_v = scatter_prefill_kv(
            self.kv_k, self.kv_v, k_all, v_all,
            jnp.asarray(slots, jnp.int32))
        self.prefill_tokens_total += extent - seq.computed
        seq.computed = extent
        self.long_prefills_total += 1
        self.steps += 1
        self._commit_full_pages(seq)
        if seq.generated == 0:
            toks_d, aux_d = self._sample_device([seq], logits)
            aux = (tuple(np.asarray(a) for a in aux_d)
                   if aux_d is not None else None)
            self._append_token(seq, int(np.asarray(toks_d)[0]),
                               lp=self._lp_entry(seq, aux, 0))
            if seq.finished is None:
                # proto: request.lifecycle prefill->decode
                self.running.append(seq)
        else:
            # resumed after preemption: next token already sampled
            seq.last_token = seq.tokens[-1]
            # proto: request.lifecycle prefill->decode
            self.running.append(seq)

    def _long_bucket(self, extent: int) -> int:
        """Padded length for the ring prefill: pow2 multiples of
        lcm(seq_axis, page_size) — divisible by the seq axis for
        shard_map, page-aligned, logarithmically many compiles."""
        base = math.lcm(self._seq_par, self.ecfg.page_size)
        T = base
        while T < extent:
            T *= 2
        return T

    def _process_prefill(self, pf: _PendingPrefill) -> None:
        """Read back a dispatched prefill's first-token draws and admit
        the finished prompts into decode."""
        if pf.processed:
            return
        pf.processed = True
        toks = np.asarray(pf.sampled) if pf.sampled is not None else None
        aux = (tuple(np.asarray(a) for a in pf.aux)
               if pf.aux is not None else None)
        for i, seq in pf.finishing:
            self._commit_full_pages(seq)
            if seq.generated == 0:
                self._append_token(seq, int(toks[i]),
                                   lp=self._lp_entry(seq, aux, i))
                if seq.finished is None:
                    # proto: request.lifecycle prefill->decode
                    self.running.append(seq)
            else:
                # resumed after preemption: last token already sampled
                seq.last_token = seq.tokens[-1]
                # proto: request.lifecycle prefill->decode
                self.running.append(seq)

    # -------------------------------------------------------------- decode

    def _grow_or_preempt(self, batch: List[Sequence], lookahead: int) -> None:
        """Grow every batch member's pages ``lookahead`` tokens ahead
        (clamped to the grid capacity); on pool exhaustion, flush the
        pipeline (so releases are safe and deferred frees land) and
        preempt newest-arrival sequences until the batch fits."""
        for seq in list(batch):
            if seq not in batch:
                continue
            if seq.finished is not None or seq.context.stopped:
                # a flush below may have finished earlier batch members
                batch.remove(seq)
                continue
            target = min(len(seq.tokens) + lookahead, self.cap_tokens)
            if self.pm.grow(seq.pages, target):
                continue
            self._flush_pipeline()  # host state current; frees landed
            if seq.finished is not None or seq.context.stopped:
                batch.remove(seq)  # the flush finished/cancelled it
                continue
            target = min(len(seq.tokens) + lookahead, self.cap_tokens)
            while not self.pm.grow(seq.pages, target):
                live = [s for s in self.running if s.finished is None]
                if not live:
                    batch.remove(seq)
                    break
                victim = max(live, key=lambda s: s.arrival)
                log.warning("KV pool exhausted; preempting %s",
                            victim.context.id)
                if victim in batch:
                    batch.remove(victim)
                self.running.remove(victim)
                self._release(victim)
                victim.computed = 0  # keep tokens/generated: resume not redo
                # proto: request.lifecycle decode->admitted
                self.waiting.insert(0, victim)
                if victim is seq:
                    break
        # drain tier ops queued by grow-evictions NOW, before this step's
        # forward dispatch: the evicted page's new owner writes it in the
        # program we're about to enqueue, and a drain on the NEXT step
        # would gather content the device has already overwritten —
        # poisoning the host tier with spliced pages
        self._drain_kv_tier()

    def _decode_step_single(self, batch: Optional[List[Sequence]] = None
                            ) -> None:
        """K=1 decode: one forward + sample per dispatch, synchronous.
        ``batch`` restricts the step to a subset of running rows (the
        spec-decode fallback arm); None takes every running row."""
        if batch is None:
            batch = [s for s in self.running if s.finished is None]
        batch = batch[: self.ecfg.max_batch]
        for seq in list(batch):
            if seq.context.stopped:
                batch.remove(seq)
                self.running.remove(seq)
                self._release(seq)
                self._finish(seq, _cancel_reason(seq.context))
        self._grow_or_preempt(batch, 1)
        if not batch:
            return
        B = self.ecfg.bucket_batch(len(batch))
        P = self.ecfg.bucket_pages(max(len(s.pages) for s in batch))
        tokens = np.zeros(B, np.int32)
        positions = np.full(B, -1, np.int32)
        table = np.zeros((B, P), np.int32)
        slots = np.full(B, DROP_SLOT, np.int32)
        for i, seq in enumerate(batch):
            pos = len(seq.tokens) - 1  # position of last_token
            tokens[i] = seq.last_token
            positions[i] = pos
            table[i, :len(seq.pages)] = seq.pages
            page = seq.pages[pos // self.ecfg.page_size]
            slots[i] = (page * self.ecfg.page_size
                        + pos % self.ecfg.page_size)
        pt0 = self.profiler.begin()
        logits, self.kv_k, self.kv_v = self.decode_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(positions),
            self.kv_k, self.kv_v, jnp.asarray(table), jnp.asarray(slots))
        toks_d, aux_d = self._sample_device(batch, logits)
        self.profiler.end(pt0, "decode", (B, P), tokens=len(batch),
                          sync_ref=toks_d)
        self._account_dispatch(batch)
        sampled = np.asarray(toks_d)[:len(batch)]
        aux = (tuple(np.asarray(a) for a in aux_d)
               if aux_d is not None else None)
        self.steps += 1
        self.decode_tokens_total += len(batch)
        for i, (seq, tok) in enumerate(zip(batch, sampled)):
            self._append_token(seq, int(tok),
                               lp=self._lp_entry(seq, aux, i))
        self.step_timeline.add(
            "decode", batch=len(batch), tokens=len(batch),
            occupancy=len(self.running) + len(self.prefilling),
            waiting=len(self.waiting))

    # -------------------------------------------------- speculative decode

    def _step_spec(self) -> None:
        """Scheduler iteration with self-speculative decoding enabled.

        Synchronous (no cross-iteration pipelining): the speculative win
        is up to K+1 tokens per dispatch, not dispatch overlap — and the
        drafter reads host token lists every step, so they must be
        exact. Prefill keeps its existing policies (priority or budgeted
        mixing). Rows whose drafter finds a candidate continuation take
        the batched verify step; everything else — no draft found,
        non-greedy sampling, penalties, logit_bias, logprobs — falls
        back to the standard fused-window/single-token dispatch."""
        budget = self.ecfg.prefill_token_budget
        if self.prefilling:
            pf = self._dispatch_prefill(budget)
            if pf is not None:
                self._process_prefill(pf)
        if self.prefilling and budget is None and self.ecfg.prefill_priority:
            return
        for seq in list(self.running):
            if seq.context.stopped:
                self._terminate(seq, _cancel_reason(seq.context))
        batch = [s for s in self.running if s.finished is None]
        batch = batch[: self.ecfg.max_batch]
        if not batch:
            return
        if self.prefilling:
            self.mixed_dispatches += 1
        spec_rows: List[Sequence] = []
        drafts: Dict[int, List[int]] = {}
        rest: List[Sequence] = []
        for seq in batch:
            d = self._draft_for(seq)
            if d:
                spec_rows.append(seq)
                drafts[id(seq)] = d
            else:
                rest.append(seq)
        if spec_rows:
            self._decode_step_spec(spec_rows, drafts)
        # the spec step's pool-pressure preemption can evict rows parked
        # in `rest` (they lose their pages and requeue) — never dispatch
        # a row the scheduler no longer runs
        rest = [s for s in rest if s in self.running]
        if rest:
            if self.ecfg.decode_steps > 1:
                pend = self._dispatch_decode_window(batch=rest)
                if pend is not None:
                    self._process_window(pend)
            else:
                self._decode_step_single(batch=rest)
        self._drain_deferred()

    def _draft_for(self, seq: Sequence) -> List[int]:
        """Prompt-lookup draft for one row, or [] when the row bypasses
        speculation. Bypass covers exactly the semantics a greedy
        multi-token verify cannot reproduce: sampled rows (temperature),
        count-state penalties and logit_bias (their logits depend on
        tokens accepted earlier in the SAME step), and logprobs requests
        (the verify path returns no per-token aux)."""
        s = seq.req.sampling
        if (not s.greedy or _wants_count_state(s)
                or getattr(s, "logit_bias", None)
                or seq.req.output.logprobs is not None):
            return []
        # clamp the draft so even a full accept (K drafts + bonus) stays
        # inside the row's token budget and the warmed grid capacity
        k = min(self.ecfg.spec_tokens,
                seq.max_new() - seq.generated - 1,
                self.cap_tokens - len(seq.tokens) - 1)
        if k <= 0:
            return []
        return propose_ngram_draft(seq.tokens, k, self.ecfg.spec_ngram_max,
                                   self.ecfg.spec_ngram_min)

    def _decode_step_spec(self, batch: List[Sequence],
                          drafts: Dict[int, List[int]]) -> None:
        """One batched multi-token verify: each row's input is [pending
        decode token, draft...], every input's KV scatters into its page
        slot during the forward, and the vectorized greedy accept-mask
        keeps the longest matching draft prefix plus the bonus token.

        Rejected drafts leave junk KV past each row's accepted extent.
        That is safe by the engine's standing invariants: causal masking
        hides positions beyond any query's own position, a slot is
        rewritten when its position's REAL token becomes the decode
        input (before anything attends to it), and page commits only
        ever publish positions strictly behind the newest token."""
        K = self.ecfg.spec_tokens
        # page coverage for every potential write this step (positions
        # through len(tokens)-1+K) plus the next pending token's slot
        self._grow_or_preempt(batch, K + 1)
        batch = [s for s in batch
                 if s.finished is None and not s.context.stopped]
        if not batch:
            return
        B = self.ecfg.bucket_batch(len(batch))
        P = self.ecfg.bucket_pages(max(len(s.pages) for s in batch))
        T = K + 1
        ps = self.ecfg.page_size
        tokens = np.zeros((B, T), np.int32)
        positions = np.full((B, T), -1, np.int32)
        table = np.zeros((B, P), np.int32)
        slots = np.full((B, T), DROP_SLOT, np.int32)
        draft_arr = np.zeros((B, K), np.int32)
        draft_len = np.zeros(B, np.int32)
        for i, seq in enumerate(batch):
            d = drafts[id(seq)][:K]
            n = len(d)
            pos0 = len(seq.tokens) - 1  # position of the pending token
            tokens[i, :n + 1] = [seq.last_token] + d
            pr = np.arange(pos0, pos0 + n + 1)
            positions[i, :n + 1] = pr
            table[i, :len(seq.pages)] = seq.pages
            pages = np.asarray(seq.pages, np.int64)
            slots[i, :n + 1] = pages[pr // ps] * ps + pr % ps
            draft_arr[i, :n] = d
            draft_len[i] = n
        pt0 = self.profiler.begin()
        logits, self.kv_k, self.kv_v = self.verify_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(positions),
            self.kv_k, self.kv_v, jnp.asarray(table), jnp.asarray(slots))
        out_d, acc_d = verify_greedy_draft(
            logits, jnp.asarray(draft_arr), jnp.asarray(draft_len),
            max_top_k=self.ecfg.max_top_k)
        self.profiler.end(pt0, "spec_verify", (B, P),
                          tokens=int(draft_len.sum()) + len(batch),
                          sync_ref=out_d)
        self._account_dispatch(batch)
        out = np.asarray(out_d)  # host sync — the spec arm is synchronous
        acc = np.asarray(acc_d)
        self.steps += 1
        self.spec_steps += 1
        step_accepted = step_drafted = 0
        for i, seq in enumerate(batch):
            accepted = int(acc[i])
            self.spec_draft_tokens_total += int(draft_len[i])
            self.spec_accepted_tokens_total += accepted
            step_drafted += int(draft_len[i])
            step_accepted += accepted
            for j in range(accepted + 1):
                if seq.finished is not None or seq.context.stopped:
                    break  # tokens past an accepted stop are discarded
                self._append_token(seq, int(out[i, j]))
                self.decode_tokens_total += 1
        self.step_timeline.add(
            "spec_verify", batch=len(batch), drafted=step_drafted,
            accepted=step_accepted,
            occupancy=len(self.running) + len(self.prefilling),
            waiting=len(self.waiting))

    def _dispatch_decode_window(self, batch: Optional[List[Sequence]] = None
                                ) -> Optional[_PendingWindow]:
        """Enqueue the next fused K-step decode window WITHOUT reading
        back. Rows carried over from the in-flight window take their
        (token, position, done, step, budget) state from the on-device
        carry — the host's lagging view never enters the feedback loop —
        while newly admitted rows are seeded from host state. ``batch``
        restricts the window to a subset of running rows (the spec-decode
        fallback arm, which has already swept cancellations)."""
        K = self.ecfg.decode_steps
        if batch is None:
            for seq in list(self.running):
                if seq.context.stopped:
                    self._terminate(seq, _cancel_reason(seq.context))
            batch = [s for s in self.running if s.finished is None]
        else:
            batch = [s for s in batch if s.finished is None
                     and not s.context.stopped]
        # submit_prefilled can push running past max_batch; overflow rows
        # simply wait a round (arrays below are sized ≤ max_batch)
        batch = batch[: self.ecfg.max_batch]
        if not batch:
            return None
        # grow pages to cover this window AND the in-flight one (device
        # positions can lead host state by up to K tokens)
        self._grow_or_preempt(batch, 2 * K)
        # the flush inside _grow_or_preempt may have finished rows
        batch = [s for s in batch
                 if s.finished is None and not s.context.stopped]
        if not batch:
            return None

        prev = self._pending  # None if _grow_or_preempt flushed
        # sampling penalties need ACCURATE host token lists (counts are
        # rebuilt from seq.tokens each dispatch): land the in-flight
        # window first, trading the pipelining overlap away only for
        # batches that actually use penalties
        if prev is not None and any(_wants_count_state(s.req.sampling)
                                    for s in batch):
            self._process_window(prev)
            prev = None
            # the readback may have finished rows (EOS/length) and freed
            # their pages — dispatching them would scatter into page 0
            batch = [s for s in batch
                     if s.finished is None and not s.context.stopped]
            if not batch:
                return None
        B = self.ecfg.bucket_batch(len(batch))
        P = self.ecfg.bucket_pages(max(len(s.pages) for s in batch))
        E = self.ecfg.max_eos_ids
        # cache_sampler_params: while the batch composition (rows, page
        # counts, bucket shape) is unchanged, the page table, stop table
        # and sampler params are bit-identical — reuse last dispatch's
        # device arrays instead of rebuilding + re-uploading them. The key
        # holds the Sequence objects themselves (identity compare), so no
        # stale hit is possible. NOTE: a hit also freezes the build-time
        # random seeds of UNSEEDED sampled rows for the cached span.
        key = ((B, P, list(batch), [len(s.pages) for s in batch])
               if self.ecfg.cache_sampler_params else None)
        cached = self._samp_cache
        if key is not None and cached is not None and cached[0] == key:
            sb, (d_table, d_temp, d_topk, d_topp, d_seeds,
                 d_eos) = cached[1], cached[2]
        else:
            table = np.zeros((B, P), np.int32)
            eos = np.full((B, E), -1, np.int32)
            for i, seq in enumerate(batch):
                table[i, :len(seq.pages)] = seq.pages
                ids: List[int] = []
                if not seq.req.stop.ignore_eos:
                    ids.extend(seq.req.eos_token_ids or [])
                ids.extend(seq.req.stop.stop_token_ids or [])
                if ids:
                    eos[i, :min(len(ids), E)] = ids[:E]
            sb = SamplingBatch.build([s.req.sampling for s in batch], B)
            d_table, d_eos = jnp.asarray(table), jnp.asarray(eos)
            d_temp = jnp.asarray(sb.temperature)
            d_topk = jnp.asarray(sb.top_k)
            d_topp = jnp.asarray(sb.top_p)
            d_seeds = jnp.asarray(sb.seeds)
            if key is not None:
                self._samp_cache = (key, sb, (d_table, d_temp, d_topk,
                                              d_topp, d_seeds, d_eos))
        from_carry = np.zeros(B, bool)
        src = np.zeros(B, np.int32)
        ntok = np.zeros(B, np.int32)
        npos = np.full(B, -1, np.int32)
        nsteps = np.zeros(B, np.int32)
        nrem = np.ones(B, np.int32)
        for i, seq in enumerate(batch):
            if prev is not None and id(seq) in prev.index:
                from_carry[i] = True
                src[i] = prev.index[id(seq)]
            else:
                ntok[i] = seq.last_token
                npos[i] = len(seq.tokens) - 1
                nsteps[i] = seq.generated
                nrem[i] = max(min(seq.max_new() - seq.generated,
                                  self.cap_tokens - len(seq.tokens)), 1)
        if prev is not None:
            tok, pos, done, steps, rem = _merge_carry(
                *prev.carry, jnp.asarray(src), jnp.asarray(from_carry),
                jnp.asarray(ntok), jnp.asarray(npos), jnp.asarray(nsteps),
                jnp.asarray(nrem))
        else:
            tok, pos = jnp.asarray(ntok), jnp.asarray(npos)
            done = jnp.zeros(B, bool)
            steps, rem = jnp.asarray(nsteps), jnp.asarray(nrem)
        pen = self._penalty_args(batch, sb, B)
        topn = (self.ecfg.max_top_logprobs
                if self._wants_logprobs(batch) else 0)
        pt0 = self.profiler.begin()
        out = self.decode_multi_fn(
            self.params, tok, pos, done, steps, rem, self.kv_k, self.kv_v,
            d_table, d_temp, d_topk, d_topp, d_seeds, d_eos, pen,
            k_steps=K, logprobs_topn=topn)
        if topn:
            toks, emitted, aux, carry, self.kv_k, self.kv_v = out
        else:
            toks, emitted, carry, self.kv_k, self.kv_v = out
            aux = None
        # sampled window timing serializes THIS window's pipeline (the
        # drain waits out the in-flight overlap) — the documented
        # sampling overhead; absent entirely at sample=0
        self.profiler.end(pt0, "decode_window", (B, P, K),
                          tokens=len(batch) * K, sync_ref=toks)
        self._account_dispatch(batch)
        self.steps += 1
        pend = _PendingWindow(batch=list(batch), toks=toks,
                              emitted=emitted, carry=carry, aux=aux,
                              index={id(s): i for i, s in enumerate(batch)})
        self._inflight.append(pend)
        return pend

    def _process_window(self, pend: _PendingWindow) -> None:
        """Read back a dispatched window's tokens (the only host sync in
        the decode loop — overlapped with the NEXT window's compute) and
        apply host-side bookkeeping: emission, stop conditions, prefix
        commits. Host stop checks mirror the device masking, so they agree
        except for >max_eos_ids stop lists (host wins, device lags)."""
        if pend.processed:
            return
        pend.processed = True
        toks = np.asarray(pend.toks)
        aux = (tuple(np.asarray(a) for a in pend.aux)
               if pend.aux is not None else None)
        coalesce = self.ecfg.coalesce_window_emissions
        if coalesce:
            # outputs of the same program as toks — ready the moment toks
            # is, so these reads add no extra device sync. carry is never
            # donated (warmup's merge-combo loop reuses one), so reading
            # done here is safe even with the next window in flight.
            counts = np.asarray(pend.emitted)
            done = np.asarray(pend.carry[2])
        if pend in self._inflight:
            self._inflight.remove(pend)
        if self._pending is pend:
            self._pending = None
        K = toks.shape[1]
        emitted = 0
        # host-segment bracket: pure bookkeeping time (emission, stop
        # mirror, page publish) — the readback wait above is already
        # visible as decode_window device_us
        ht0 = self.profiler.begin()
        for i, seq in enumerate(pend.batch):
            if seq.finished is not None:
                continue
            if coalesce and not seq.context.stopped \
                    and self._device_stops_complete(seq):
                emitted += self._append_row(
                    seq, toks[i], int(counts[i]), bool(done[i]), aux, i)
                continue
            for j in range(K):
                if seq.finished is not None or seq.context.stopped:
                    break  # tokens past EOS/stop are discarded
                self._append_token(seq, int(toks[i, j]),
                                   lp=self._lp_entry(seq, aux, i, j))
                self.decode_tokens_total += 1
                emitted += 1
        self.profiler.end(ht0, "process_window", (len(pend.batch), K),
                          tokens=emitted)
        self.step_timeline.add(
            "decode_window", batch=len(pend.batch), tokens=emitted,
            occupancy=len(self.running) + len(self.prefilling),
            waiting=len(self.waiting))

    def _device_stops_complete(self, seq: Sequence) -> bool:
        """True when the row's full stop-id set fit the on-device stop
        table, so the window's done flag / emitted count are authoritative
        and the host can bulk-append without per-token stop checks."""
        return seq.dev_stop_count <= self.ecfg.max_eos_ids

    def _append_row(self, seq: Sequence, row: np.ndarray, n: int,
                    dev_done: bool, aux, i: int) -> int:
        """Bulk-append one window row using the device's valid-token
        count: ONE EngineOutput (one cross-thread wakeup) for the whole
        window instead of one per token, one page-publish sweep, and the
        finish decision read off the device's done flag. Token identity
        with the per-token path is pinned by test."""
        n = min(n, row.shape[0])
        if n <= 0:
            if dev_done and seq.finished is None:
                # row entered the window already frozen but never got its
                # host-side finish (defensive: unreachable under FIFO
                # window processing) — terminate so it can't re-dispatch
                self._terminate(seq, FINISH_LENGTH)
            return 0
        ids = [int(t) for t in row[:n]]
        prev_filled = len(seq.tokens)
        seq.tokens.extend(ids)
        seq.last_token = ids[-1]
        seq.generated += n
        self.decode_tokens_total += n
        lps = tops = None
        if aux is not None and seq.req.output.logprobs is not None:
            entries = [self._lp_entry(seq, aux, i, j) for j in range(n)]
            lps = [e[0] for e in entries]
            tops = [e[1] for e in entries]
        self._emit(seq, EngineOutput(
            token_ids=ids, prompt_tokens=seq.num_prompt,
            logprobs=lps, top_logprobs=tops))
        # prefix-cache publish when the row crossed a page boundary (same
        # len-1 publishable-extent rule as _append_token; commit_chain
        # dedups blocks already published)
        filled = len(seq.tokens)
        ps = self.ecfg.page_size
        if (filled - 1) // ps > max(prev_filled - 1, 0) // ps:
            self.pm.commit_chain(seq.pages, seq.tokens, filled - 1,
                                 chain=self._chain(seq))
        if dev_done:
            last = ids[-1]
            hit = last in seq.stop_set
            self._terminate(seq, FINISH_EOS if hit else FINISH_LENGTH)
        elif (seq.generated >= seq.max_new()
              or len(seq.tokens) >= self.cap_tokens):
            # host caps the device couldn't see at seed time (defensive
            # mirror of _append_token's length cut)
            self._terminate(seq, FINISH_LENGTH)
        return n

    # -------------------------------------------- deferred page reclamation

    def _release_or_defer(self, seq: Sequence) -> None:
        """Release a sequence's pages unless an in-flight window still
        writes them (freeing early could hand a page to a new owner while
        the old window's scatter lands — corrupting prefix-cache pages).
        The pending finish emission rides with the release."""
        if any(id(seq) in w.index for w in self._inflight):
            if seq not in self._deferred_free:
                self._deferred_free.append(seq)
        else:
            self._release(seq)
            self._emit_finish(seq)

    def _drain_deferred(self) -> None:
        still: List[Sequence] = []
        for seq in self._deferred_free:
            if any(id(seq) in w.index for w in self._inflight):
                still.append(seq)
            else:
                self._release(seq)
                self._emit_finish(seq)
        self._deferred_free = still

    # ------------------------------------------------------------- helpers

    def _penalty_state(self, seqs: List[Sequence], pad_to: int):
        """(counts [B,V] int32 of GENERATED tokens, presence [B,V] int8
        over the full context) rebuilt from the host token lists — the
        stateless-per-dispatch form (slots migrate between sequences, so
        device-resident histograms would need per-dispatch resharding
        anyway). Only ever built for batches that use penalties."""
        V = self.cfg.vocab_size
        counts = np.zeros((pad_to, V), np.int32)
        presence = np.zeros((pad_to, V), np.int8)
        for i, s in enumerate(seqs):
            # host-list → host-array construction, not a device sync
            gen = np.asarray(s.tokens[s.num_prompt:], np.int64)  # dynalint: disable=jax-host-sync-in-hot-path
            if gen.size:
                counts[i] = np.bincount(gen, minlength=V)[:V]
            ctx = np.asarray(s.tokens, np.int64)  # dynalint: disable=jax-host-sync-in-hot-path
            presence[i, ctx[ctx < V]] = 1
        return (jnp.asarray(counts), jnp.asarray(presence))

    def _penalty_args(self, seqs: List[Sequence], sb: SamplingBatch,
                      pad_to: int):
        """The (counts, presence, rep, freq, pres[, bias]) tuple the
        samplers take, or None for penalty/bias-free batches (the only
        warmed path). The bias element is appended only when some row
        sets logit_bias — its own treedef, so bias-free penalty batches
        reuse the 5-tuple program."""
        biased = [getattr(s.req.sampling, "logit_bias", None)
                  for s in seqs]
        if not sb.has_penalties and not any(biased):
            return None
        if sb.has_penalties:
            state = self._penalty_state(seqs, pad_to)
        else:
            # bias-only: counts/presence are mathematically unused
            # (rep=1, freq=pres=0 broadcast them away) — [B, 1]
            # placeholders instead of 2x [B, V] arrays per dispatch
            state = (jnp.zeros((pad_to, 1), jnp.int32),
                     jnp.zeros((pad_to, 1), jnp.int8))
        out = state + (jnp.asarray(sb.rep), jnp.asarray(sb.freq),
                       jnp.asarray(sb.pres))
        if any(biased):
            V = self.cfg.vocab_size
            rows = [self._bias_row(s) if b else None
                    for s, b in zip(seqs, biased)]
            bias = np.zeros((pad_to, V), np.float32)
            for i, r in enumerate(rows):
                if r is not None:
                    bias[i] = r
            out = out + (jnp.asarray(bias),)
        return out

    def _bias_row(self, seq: Sequence) -> np.ndarray:
        """Per-sequence dense logit_bias row, built once and cached on
        the Sequence (the dict is immutable per request; only the batch
        assembly runs per dispatch)."""
        row = getattr(seq, "_bias_row", None)
        if row is None:
            V = self.cfg.vocab_size
            row = np.zeros(V, np.float32)
            bias_map = seq.req.sampling.logit_bias
            if bias_map:
                for t, v in bias_map.items():
                    if 0 <= int(t) < V:
                        row[int(t)] = v
            seq._bias_row = row
        return row

    def _sample_device(self, seqs: List[Sequence], logits) -> jax.Array:
        """On-device token draw, no readback. logits: [B_padded, V]
        (bucketed); pads sampling params to match so every distinct batch
        bucket compiles exactly once."""
        pad_to = logits.shape[0]
        sb = SamplingBatch.build([s.req.sampling for s in seqs], pad_to)
        steps = np.zeros(pad_to, np.int32)
        steps[:len(seqs)] = [s.generated for s in seqs]
        pen = self._penalty_args(seqs, sb, pad_to)
        toks = sample_tokens(logits, jnp.asarray(sb.temperature),
                             jnp.asarray(sb.top_k), jnp.asarray(sb.top_p),
                             jnp.asarray(sb.seeds), jnp.asarray(steps),
                             max_top_k=self.ecfg.max_top_k, penalties=pen)
        aux = None
        if self._wants_logprobs(seqs):
            aux = logprob_aux(jnp.asarray(logits), toks,
                              self.ecfg.max_top_logprobs)
        return toks, aux

    def _wants_logprobs(self, seqs: List[Sequence]) -> bool:
        return any(s.req.output.logprobs is not None for s in seqs)

    def _lp_entry(self, seq: Sequence, aux, i: int, j: Optional[int] = None):
        """(logprob, {token_id: logprob, ...}) for row i (step j in a
        window) — None unless this sequence asked for logprobs."""
        if aux is None or seq.req.output.logprobs is None:
            return None
        lp, tv, ti = aux
        if j is None:
            chosen, vals, ids = lp[i], tv[i], ti[i]
        else:
            chosen, vals, ids = lp[i, j], tv[i, j], ti[i, j]
        topn = min(int(seq.req.output.logprobs), len(ids))
        top = {int(t): float(v) for t, v in zip(ids[:topn], vals[:topn])}
        return float(chosen), top

    def _append_token(self, seq: Sequence, tok: int, lp=None) -> None:
        """Record a generated token: emit, check termination, commit pages."""
        seq.tokens.append(tok)
        seq.last_token = tok
        seq.generated += 1
        eos = tok in seq.stop_set
        self._emit(seq, EngineOutput(
            token_ids=[tok], prompt_tokens=seq.num_prompt,
            logprobs=[lp[0]] if lp is not None else None,
            top_logprobs=[lp[1]] if lp is not None else None))
        # prefix-cache publish: commit a page only once every slot in it
        # holds WRITTEN KV. The newest token's KV is written when it next
        # serves as a decode input — which never happens for a terminal
        # token under on-device stop freezing — so the publishable extent
        # is len(tokens) - 1 positions, one token past the page boundary.
        # Committing at filled % ps == 0 (the pre-pipelining rule) would
        # publish a page whose last slot is junk and poison later hits.
        filled = len(seq.tokens)
        ps = self.ecfg.page_size
        if (filled - 1) >= ps and (filled - 1) % ps == 0:
            # multi-token publish (commit() dedups the already-published
            # blocks): speculative accepts can append several tokens
            # between boundary checks, so commit everything the extent
            # covers, not just the newest block
            self.pm.commit_chain(seq.pages, seq.tokens, filled - 1,
                                 chain=self._chain(seq))
        if eos:
            self._terminate(seq, FINISH_EOS)
        elif (seq.generated >= seq.max_new()
              or len(seq.tokens) >= self.cap_tokens):
            # the capacity cut mirrors the device-side `remaining` clamp:
            # the device froze this row at the grid boundary, so stop
            # appending its (repeated) trailing tokens
            self._terminate(seq, FINISH_LENGTH)

    def _terminate(self, seq: Sequence, reason: str) -> None:
        """Terminal-state a sequence. The finished flag is set NOW (no
        more tokens append); the finish_reason EMISSION rides with the
        page release, which defers until any in-flight window containing
        the row completes — so by the time a client sees finish, the
        engine's capacity accounting already reflects the freed pages."""
        if seq in self.running:
            self.running.remove(seq)
        if seq.finished is None:
            # proto: request.lifecycle prefill|decode->finished|timeout|cancelled
            seq.finished = reason
        self._release_or_defer(seq)

    def _chain(self, seq: Sequence) -> List[int]:
        """Full-block hashes of seq.tokens via the per-sequence
        incremental cache (created on first use)."""
        if seq.hash_cache is None:
            seq.hash_cache = ChainHashCache(self.ecfg.page_size)
        return seq.hash_cache.extend(seq.tokens)

    def _commit_full_pages(self, seq: Sequence) -> None:
        self.pm.commit_chain(seq.pages, seq.tokens, seq.prefill_extent,
                             chain=self._chain(seq))

    def _release(self, seq: Sequence) -> None:
        if seq.hold_pages:
            return  # disagg prefill-only: caller extracts, then releases
        if seq.pages:
            self.pm.release_sequence(seq.pages)
            seq.pages = []

    def _finish(self, seq: Sequence, reason: str) -> None:
        if seq.finished is None:
            # proto: request.lifecycle admitted->finished|timeout|cancelled
            seq.finished = reason
        self._emit_finish(seq)

    def _account_dispatch(self, batch: List[Sequence]) -> None:
        """dynaprof attribution: each dispatch distributes exactly 1.0
        step share across its batch (occupancy weighting), so the sum of
        per-request shares equals batch_dispatches_total — the
        conservation invariant tests/test_profiling.py pins. Host-side
        counter updates only."""
        share = 1.0 / len(batch)
        for seq in batch:
            seq.dispatch_share += share
            seq.dispatches += 1
            if len(seq.pages) > seq.max_pages:
                seq.max_pages = len(seq.pages)
        self.batch_dispatches_total += 1

    def _attribution(self, seq: Sequence) -> dict:
        """Per-request cost block: where this request's share of the
        engine's time and memory went. ``device_ms_est`` scales the
        occupancy-weighted step share by the sampled mean device time
        per dispatch (None until something has been sampled)."""
        est = self.profiler.mean_device_ms_per_step()
        ps = self.ecfg.page_size
        prompt_blocks = (seq.num_prompt + ps - 1) // ps
        return {
            "queue_wait_ms": round(seq.queue_wait_s * 1000.0, 3),
            "device_step_share": round(seq.dispatch_share, 6),
            "dispatches": seq.dispatches,
            "prompt_tokens": seq.num_prompt,
            "prefix_hit_tokens": seq.prefix_hit,
            # dynacache prefix split: device_hit + host_restored + the
            # implied fresh remainder sum to prompt_blocks (conservation,
            # pinned by tests/test_cache_obs.py). router_overlap_blocks
            # is merged in by the frontend's KvRouter when the finish
            # cost block passes its attribution listener.
            "prompt_blocks": prompt_blocks,
            "device_hit_blocks": seq.device_hit_blocks,
            "host_restored_blocks": seq.host_restored_blocks,
            "restore_wait_ms": round(seq.restore_wait_s * 1000.0, 3),
            "decode_tokens": seq.generated,
            "kv_pages_peak": seq.max_pages,
            "kv_bytes_peak": seq.max_pages * self._page_bytes,
            "device_ms_est": (round(seq.dispatch_share * est, 3)
                              if est is not None else None),
            "finish_reason": seq.finished,
            # dynashard: which replica/submesh served this request —
            # /v1/traces/{rid} and the usage cost extension surface these
            "replica": self.worker_label,
            "mesh_shape": self.mesh_shape,
        }

    def _emit_finish(self, seq: Sequence) -> None:
        if seq.finish_emitted or seq.finished is None:
            return
        seq.finish_emitted = True
        # dynaslo e2e: arrival → finish emission (cancel/error finishes
        # included — a timed-out request IS a latency observation)
        self.latency.observe("e2e", time.monotonic() - seq.arrival)
        cost = self._attribution(seq)
        profiling.record_attribution(seq.context.id, cost)
        self._emit(seq, EngineOutput(token_ids=[], finish_reason=seq.finished,
                                     prompt_tokens=seq.num_prompt,
                                     completion_tokens=seq.generated,
                                     cost=cost))

    def _emit(self, seq: Sequence, out: EngineOutput) -> None:
        if out.token_ids:
            # dynaslo: first token-bearing emission is TTFT; later gaps
            # are per-token ITL (an n-token window emission records n
            # per-token gaps of gap/n, so window size never skews the
            # distribution). Host clock reads only.
            now = time.monotonic()
            if seq.last_emit_t is None:
                self.latency.observe("ttft", now - seq.arrival)
            else:
                n = len(out.token_ids)
                self.latency.observe("itl", (now - seq.last_emit_t) / n, n)
            seq.last_emit_t = now
        # steps run in the executor thread; asyncio.Queue is not thread-safe,
        # so route puts through the loop. Thread-id compare instead of an
        # asyncio.get_running_loop() probe: off-loop the probe RAISES
        # RuntimeError per emission (= per token on the decode path)
        tid = self._aio_loop_tid
        if tid is None or threading.get_ident() == tid:
            seq.out.put_nowait(out)
        else:
            self._aio_loop.call_soon_threadsafe(seq.out.put_nowait, out)

    def _reap(self) -> None:
        """Drop finished sequences that linger in running (safety net)."""
        self.running = [s for s in self.running if s.finished is None]

    # ------------------------------------------------- disaggregation plane
    # Engine-side primitives for prefill/decode disaggregation (reference
    # vllm_v0.7.2-dynamo-kv-disagg-patch: remote_prefill.py
    # RemotePrefillRequest staging + DynamoNixlConnector block reads/writes).
    # On TPU the RDMA path becomes: gather pages → host bytes → TCP/DCN →
    # donated scatter back into the destination pool (llm/disagg/transfer.py);
    # same-process transfers skip the host round-trip entirely.

    async def reserve_remote(self, token_ids: List[int]
                             ) -> Optional["RemoteReservation"]:
        """Decode-side page reservation for a remote prefill: claims pages
        covering the prompt (reusing the longest cached prefix) without
        admitting a sequence. Returns None when the pool is full."""
        loop = asyncio.get_running_loop()
        if len(token_ids) >= self.cap_tokens:
            # same warmed-grid clamp as _admit: a reservation past the
            # largest page bucket would force a mid-serving compile when
            # the sequence enters decode via submit_prefilled
            return None

        def _do():
            with self._pm_lock:
                alloc = self.pm.allocate_sequence(token_ids)
            if alloc is None:
                return None
            if alloc.restores:
                # the reservation's host-tier hits must be resident before
                # submit_prefilled starts decoding on them — no scheduler
                # drain is guaranteed to run in between, so the chunked
                # path cannot be relied on here
                self._drain_kv_tier(full=True)
            return RemoteReservation(pages=alloc[0], cached_tokens=alloc[1],
                                     page_size=self.ecfg.page_size)

        return await loop.run_in_executor(self._exec, _do)

    async def release_pages(self, pages: List[int]) -> None:
        """Return pages claimed by reserve_remote()/prefill_only()."""
        loop = asyncio.get_running_loop()

        def _do():
            with self._pm_lock:
                self.pm.release_sequence(list(pages))

        await loop.run_in_executor(self._exec, _do)

    async def extract_pages(self, page_ids: List[int], *,
                            drain: bool = True
                            ) -> Tuple[np.ndarray, np.ndarray]:
        """Gather KV pages to host memory: returns (k, v) arrays of shape
        [L, n, KV, page_size, hd] (kv-head-major pool layout). Serialized
        with engine steps on the single-worker executor so it never races
        buffer donation. ``drain=False`` skips the host-tier drain — safe
        only for follow-up ranged extracts of a request whose first
        extract already drained (the streaming transfer plane)."""
        loop = asyncio.get_running_loop()

        def _do():
            # restored pages must be resident first (full: the chunked
            # drain could leave some queued)
            if drain:
                self._drain_kv_tier(full=True)
            # pow2-pad the gather so extracts compile O(log n) programs
            # instead of one per distinct page count (dynajit DL015);
            # the D2H readback below is the extract's whole purpose
            npages = len(page_ids)
            idx = jnp.asarray(_pad_pow2(list(page_ids), 0), jnp.int32)
            k = np.asarray(_gather_pages(self.kv_k, idx))  # dynalint: disable=implicit-host-transfer
            v = np.asarray(_gather_pages(self.kv_v, idx))  # dynalint: disable=implicit-host-transfer
            return k[:, :npages], v[:, :npages]

        return await loop.run_in_executor(self._exec, _do)

    async def extract_pages_chunked(self, page_ids: List[int],
                                    chunk_pages: int):
        """Ranged/async extract for the streaming transfer plane: yields
        ``(offset, k, v, seconds)`` per ``chunk_pages``-sized slice of
        ``page_ids``. The device gather + D2H copy for slice i+1 is
        dispatched (``copy_to_host_async``) before slice i's host sync
        completes, so the device→host stage of the next chunk runs under
        whatever the consumer does with the current one (compress, socket
        write). ``seconds`` is the blocking time this chunk cost — the
        extract-stage figure for the transfer breakdown."""
        loop = asyncio.get_running_loop()
        cp = max(int(chunk_pages), 1)
        slices = [page_ids[i:i + cp] for i in range(0, len(page_ids), cp)]

        def _gather(ids, first):
            if first:
                self._drain_kv_tier(full=True)
            # pad the (only-ever-shorter) final slice to the chunk size:
            # every chunk of a stream then shares ONE gather program per
            # chunk_pages value instead of compiling the remainder length
            # mid-serving (dynajit DL015)
            idx = jnp.asarray(list(ids) + [0] * (cp - len(ids)), jnp.int32)
            kg = _gather_pages(self.kv_k, idx)
            vg = _gather_pages(self.kv_v, idx)
            for a in (kg, vg):
                if hasattr(a, "copy_to_host_async"):
                    a.copy_to_host_async()
            return kg, vg, len(ids)

        def _host(kg, vg, real):
            # the D2H sync IS the extract stage
            k = np.asarray(kg)  # dynalint: disable=implicit-host-transfer
            v = np.asarray(vg)  # dynalint: disable=implicit-host-transfer
            return k[:, :real], v[:, :real]

        if not slices:
            return
        t0 = time.monotonic()
        pending = await loop.run_in_executor(self._exec, _gather,
                                             slices[0], True)
        for i in range(len(slices)):
            nxt = (loop.run_in_executor(self._exec, _gather,
                                        slices[i + 1], False)
                   if i + 1 < len(slices) else None)
            k, v = await loop.run_in_executor(self._exec, _host, *pending)
            dt = time.monotonic() - t0
            yield i * cp, k, v, dt
            t0 = time.monotonic()
            if nxt is not None:
                pending = await nxt

    async def inject_pages(self, page_ids: List[int], k: np.ndarray,
                           v: np.ndarray) -> None:
        """Scatter host KV pages [L, n, KV, page_size, hd] into the pool at
        page_ids (donated jit — in-place on device; the block_copy.cu
        analog for ingest)."""
        loop = asyncio.get_running_loop()

        def _do():
            # evictions queued when these pages were reserved must capture
            # their OLD content before this injection overwrites it
            self._drain_kv_tier(full=True)
            # pow2-pad the scatter (pad target = num_pages → dropped by
            # the donated .at[...].set(mode="drop")) so injects compile
            # O(log n) programs, not one per page count (dynajit DL015)
            pad = _pad_pow2(list(page_ids), self.ecfg.num_pages)
            idx = jnp.asarray(pad, jnp.int32)
            kp = np.zeros((k.shape[0], len(pad) - k.shape[1],
                           *k.shape[2:]), k.dtype)
            vp = np.zeros((v.shape[0], len(pad) - v.shape[1],
                           *v.shape[2:]), v.dtype)
            self.kv_k = _inject_pages(
                self.kv_k, idx,
                jnp.asarray(np.concatenate([k, kp], axis=1)))
            self.kv_v = _inject_pages(
                self.kv_v, idx,
                jnp.asarray(np.concatenate([v, vp], axis=1)))
            jax.block_until_ready(self.kv_k)

        await loop.run_in_executor(self._exec, _do)

    async def prefill_only(self, request: PreprocessedRequest,
                           context: Context) -> Tuple[int, List[int]]:
        """Prefill worker path: compute the prompt's KV + sample the first
        token, holding the pages for extraction. Returns (first_token,
        page_ids); the caller MUST release_pages(page_ids) when done.
        (Reference prefill_worker.py:109-137 — max_tokens=1 generate.)"""
        import copy

        req = copy.copy(request)
        req.stop = copy.copy(request.stop)
        req.stop.max_tokens = 1
        self.start()
        seq = Sequence(req=req, context=context, out=asyncio.Queue(),
                       tokens=list(req.token_ids),
                       num_prompt=len(req.token_ids), hold_pages=True)
        if seq.num_prompt == 0:
            raise ValueError("empty prompt")
        self.waiting.append(seq)
        self._wake.set()
        first: Optional[int] = None
        while True:
            out: EngineOutput = await seq.out.get()
            if out.token_ids:
                first = out.token_ids[0]
            if out.finish_reason is not None:
                break
        if first is None:
            # failed before sampling: nothing to extract, so return the held
            # pages ourselves (hold_pages disabled the engine-side release)
            if seq.pages:
                await self.release_pages(seq.pages)
                seq.pages = []
            raise RuntimeError(f"prefill produced no token "
                               f"({out.finish_reason})")
        return first, seq.pages

    async def submit_prefilled(self, request: PreprocessedRequest,
                               context: Context, pages: List[int],
                               first_token: int) -> Sequence:
        """Decode-side entry after a remote prefill: the reserved pages now
        hold the prompt's KV (injected via inject_pages); enter decode
        directly with the remotely sampled first token already emitted."""
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.from_dict(request)
        if len(request.token_ids) >= self.cap_tokens:
            raise ValueError(
                f"prompt length {len(request.token_ids)} exceeds engine "
                f"context capacity {self.cap_tokens} (reserve_remote would "
                f"have refused this reservation)")
        self.start()
        seq = Sequence(req=request, context=context, out=asyncio.Queue(),
                       tokens=list(request.token_ids),
                       num_prompt=len(request.token_ids))
        seq.pages = list(pages)
        seq.computed = seq.num_prompt
        loop = asyncio.get_running_loop()

        def _do():
            self.prompt_tokens_total += seq.num_prompt
            # decode-side hits were claimed by reserve_remote, not here;
            # window the admission with the same zero-hit accounting the
            # lifetime counters use for this path
            self._hit_window.append((0, seq.num_prompt))
            with self._pm_lock:
                self._commit_full_pages(seq)  # prefix-cache publish + events
                self._append_token(seq, int(first_token))

        await loop.run_in_executor(self._exec, _do)
        if seq.finished is None:
            self.running.append(seq)
            self._wake.set()
        return seq


@dataclass
class RemoteReservation:
    """Decode-side pages claimed ahead of a remote prefill."""

    pages: List[int]
    cached_tokens: int  # prompt tokens already covered by the prefix cache
    page_size: int

    @property
    def skip_pages(self) -> int:
        """Leading pages the prefill worker need not transfer (already
        valid on the decode side via prefix-cache hits)."""
        return self.cached_tokens // self.page_size


def _make_decode_multi(model, cfg: ModelConfig, max_top_k: int,
                       mesh=None):
    """Fused K-step decode: forward → on-device sample → feed back, K
    times inside one jitted program, with the sequence carry (tok, pos,
    done, steps, remaining) staying on device so windows pipeline without
    a host sync between them. One dispatch + one (overlapped) host
    readback per K tokens — the decisive optimization when dispatch
    latency (remote/tunneled chips, Python overhead) exceeds step compute.

    Generic fallback for model modules without make_decode_window_fn
    (e.g. MLA): full forward per step with per-step pool writes; stopped
    rows write DROP_SLOT so nothing lands in their pages."""
    from ..models.llama import carry_active, carry_step_update, logits_at

    @partial(jax.jit, static_argnames=("k_steps", "logprobs_topn"),
             donate_argnames=("kv_k", "kv_v"))
    def decode_multi(params, tokens, positions, done, steps, remaining,
                     kv_k, kv_v, page_table, temperature, top_k, top_p,
                     seeds, eos_table, penalties=None, *, k_steps: int,
                     logprobs_topn: int = 0):
        B = tokens.shape[0]
        ps = kv_k.shape[3]
        P = page_table.shape[1]
        rows = jnp.arange(B)

        # UNROLLED (k_steps is static): an outer lax.scan would carry the
        # whole KV pools and XLA double-buffers scan carries — stacked on
        # the layer scan inside forward() that blows HBM. A straight-line
        # K-step program lets XLA alias the pool updates in place.
        tok, pos = tokens, positions
        toks = []
        lps, tvs, tis = [], [], []
        # mirror of the llama window fn's per-row valid-token count: the
        # host slices toks[i, :emitted[i]] instead of re-deriving stop
        # semantics token by token
        emitted = jnp.zeros((B,), jnp.int32)
        for i in range(k_steps):
            active = carry_active(done, pos)
            page = page_table[rows, jnp.clip(pos // ps, 0, P - 1)]
            slot = jnp.where(active, page * ps + pos % ps, DROP_SLOT)
            h, kv_k, kv_v = model.forward(
                params, cfg, tok[:, None], pos[:, None], kv_k, kv_v,
                page_table, slot[:, None], mesh=mesh)
            logits = logits_at(params, cfg, h, jnp.zeros(B, jnp.int32))
            nxt = sample_tokens(logits, temperature, top_k, top_p, seeds,
                                steps, max_top_k=max_top_k,
                                penalties=penalties)
            if logprobs_topn:
                lp, tv, ti = logprob_aux(logits, nxt, logprobs_topn)
                lps.append(lp); tvs.append(tv); tis.append(ti)
            penalties = update_penalty_state(penalties, nxt, done)
            emitted = emitted + active.astype(jnp.int32)
            tok, pos, done, steps, remaining = carry_step_update(
                nxt, tok, pos, done, steps, remaining, eos_table)
            toks.append(tok)
        out_toks = jnp.stack(toks, axis=1)
        carry = (tok, pos, done, steps, remaining)
        if logprobs_topn:
            aux = (jnp.stack(lps, axis=1), jnp.stack(tvs, axis=1),
                   jnp.stack(tis, axis=1))
            return out_toks, emitted, aux, carry, kv_k, kv_v
        return out_toks, emitted, carry, kv_k, kv_v

    return decode_multi


def _wants_count_state(s) -> bool:
    """True when the row needs ACCURATE token counts (the three
    count-driven penalties) — these force the pipelining barrier.
    logit_bias is static per request and needs neither counts nor the
    barrier."""
    return bool((getattr(s, "repetition_penalty", None) or 1.0) != 1.0
                or getattr(s, "frequency_penalty", None)
                or getattr(s, "presence_penalty", None))


@jax.jit
def _merge_carry(c_tok, c_pos, c_done, c_steps, c_rem, src, from_carry,
                 n_tok, n_pos, n_steps, n_rem):
    """Stitch window N+1's inputs: rows continuing from the in-flight
    window gather their state from its device carry (src indexes into the
    previous batch); fresh rows take the host-provided values. Runs as one
    tiny jitted program so no host sync enters the dispatch path."""
    src = jnp.clip(src, 0, c_tok.shape[0] - 1)
    tok = jnp.where(from_carry, c_tok[src], n_tok)
    pos = jnp.where(from_carry, c_pos[src], n_pos)
    done = jnp.where(from_carry, c_done[src], False)
    steps = jnp.where(from_carry, c_steps[src], n_steps)
    rem = jnp.where(from_carry, c_rem[src], n_rem)
    return tok, pos, done, steps, rem


@partial(jax.jit, donate_argnums=(0,))
def _inject_pages(pool: jax.Array, idx: jax.Array,
                  rows: jax.Array) -> jax.Array:
    """pool: [L, num_pages, KV, ps, hd]; rows: [L, n, KV, ps, hd].
    Out-of-range idx entries are dropped (padding)."""
    return pool.at[:, idx].set(rows.astype(pool.dtype), mode="drop")


@jax.jit
def _gather_pages(pool: jax.Array, idx: jax.Array) -> jax.Array:
    """pool: [L, num_pages, KV, ps, hd] → [L, n, KV, ps, hd]."""
    return pool[:, idx]


def _pad_pow2(lst: List[int], fill: int) -> List[int]:
    """Pad to the next power of two so batched page copies compile
    O(log n) distinct shapes instead of one per length."""
    n = 1
    while n < len(lst):
        n *= 2
    return list(lst) + [fill] * (n - len(lst))
