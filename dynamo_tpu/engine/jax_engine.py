"""The JAX serving engine: continuous batching over a paged KV cache.

This replaces the reference's engine integrations (patched vLLM/SGLang
subprocesses over ZMQ, lib/llm/src/engines/) with an in-process TPU-native
engine — the idiomatic choice on TPU where the engine IS the Python process
(SURVEY §5 "Distributed communication backend").

Design:

- one asyncio scheduler loop owns the device: it alternates chunked
  prefill steps and batched decode steps over static-shaped, bucketed
  programs (no data-dependent shapes under jit);
- per-request state is host-side (token lists, page tables from
  ``PageManager``); the device sees only padded arrays;
- device→host sync (sampled tokens) happens via ``run_in_executor`` so the
  event loop keeps serving other requests during a TPU step;
- sequences preempt (release pages, requeue) when the pool runs dry —
  prefix caching makes re-prefill cheap;
- the engine speaks the internal token-level protocol
  (``PreprocessedRequest`` in, ``EngineOutput`` chunks out) so it slots
  behind ``Backend`` exactly like the reference's ExecutionContext.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from functools import partial
from typing import AsyncIterator, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..llm.protocols.common import (FINISH_CANCELLED, FINISH_EOS,
                                    FINISH_LENGTH, EngineOutput,
                                    PreprocessedRequest)
from ..models.config import ModelConfig
from ..models.llama import DROP_SLOT, KVCacheSpec
from ..models.registry import get_model_module
from ..runtime.engine import Context
from .kv_manager import PageManager, chain_hashes
from .sampling import SamplingBatch, sample_tokens

log = logging.getLogger("dynamo_tpu.engine")


@dataclass
class EngineConfig:
    page_size: int = 64
    num_pages: int = 512
    max_batch: int = 64
    prefill_chunk: int = 512
    max_top_k: int = 64
    # host-DRAM offload tier: blocks evicted from HBM spill here and
    # restore on prefix hits (reference kv/ V2 multi-tier storage +
    # docs/kv_cache_manager.md "+40% TTFT"); 0 disables the tier
    host_pages: int = 0
    max_prefill_batch: int = 8  # prompts packed per prefill dispatch
    # fused decode window: run K decode+sample steps inside ONE jitted
    # program (sampling stays on device; tokens cross to the host once per
    # window). The serving loop is dispatch-latency-bound — per-step host
    # round-trips dwarf the ~ms device compute — so K amortizes dispatch
    # K-fold. Cancellation/stop conditions apply at window granularity.
    decode_steps: int = 4
    # bucketing (static shapes under jit); keep these sets SMALL — every
    # (bucket combination) is one XLA compile, and warmup() pre-compiles
    # the full grid so serving never compiles mid-flight
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    prefill_buckets: Tuple[int, ...] = (16, 64, 512)
    page_buckets: Tuple[int, ...] = (8, 64)
    watermark_pages: int = 4  # keep-free headroom before admitting

    @staticmethod
    def _pick(buckets: Tuple[int, ...], n: int) -> int:
        for b in buckets:
            if n <= b:
                return b
        b = buckets[-1]
        while b < n:
            b *= 2
        return b

    def bucket_batch(self, n: int) -> int:
        return min(self._pick(self.batch_buckets, n), self.max_batch)

    def prefill_bucket_batch(self, n: int) -> int:
        """Prefill batches only use the two warmed buckets
        (bucket_batch(1) and bucket_batch(max_prefill_batch)) so a
        mid-serving prompt mix never triggers a fresh XLA compile."""
        small = self.bucket_batch(1)
        return small if n <= small else self.bucket_batch(
            self.max_prefill_batch)

    def bucket_len(self, n: int) -> int:
        return min(self._pick(self.prefill_buckets, n), self.prefill_chunk)

    def bucket_pages(self, n: int) -> int:
        return self._pick(self.page_buckets, n)


@dataclass
class Sequence:
    req: PreprocessedRequest
    context: Context
    out: asyncio.Queue
    tokens: List[int]            # prompt + generated (host truth)
    num_prompt: int
    pages: List[int] = field(default_factory=list)
    computed: int = 0            # positions already in the KV cache
    generated: int = 0
    finished: Optional[str] = None
    last_token: int = 0          # next decode input
    arrival: float = field(default_factory=time.monotonic)
    # disaggregation: keep pages alive after finish so the prefill worker
    # can extract them (caller must release_pages() afterwards)
    hold_pages: bool = False

    def max_new(self) -> int:
        mt = self.req.stop.max_tokens
        return mt if mt is not None else 1 << 30

    @property
    def prefill_extent(self) -> int:
        """Tokens whose KV must exist before decode can run. Fresh request:
        the whole prompt (its last logits seed sampling). Resumed after
        preemption: everything except the final token, which is the next
        decode input (its KV is written by that decode step)."""
        return self.num_prompt if self.generated == 0 else len(self.tokens) - 1


class JaxEngine:
    """AsyncEngine over the JAX model (token-level core engine)."""

    def __init__(self, model_cfg: ModelConfig, engine_cfg: Optional[EngineConfig]
                 = None, params=None, seed: int = 0, dtype=None, mesh=None):
        self.cfg = model_cfg
        self.ecfg = engine_cfg or EngineConfig()
        model = get_model_module(model_cfg)
        if params is None:
            params = model.init_params(model_cfg, jax.random.PRNGKey(seed))
        self.params = params
        spec = KVCacheSpec(self.ecfg.num_pages, self.ecfg.page_size)
        self.kv_k, self.kv_v = model.init_kv_cache(model_cfg, spec, dtype)
        self.mesh = mesh
        if mesh is not None and mesh.size > 1:
            from ..parallel.mesh import shard_kv_cache, shard_params
            self.params = shard_params(self.params, model_cfg, mesh)
            self.kv_k, self.kv_v = shard_kv_cache(self.kv_k, self.kv_v,
                                                  model_cfg, mesh)
        # Pallas decode kernel only on unsharded pools: pallas_call has no
        # GSPMD partitioning rule, so a mesh-sharded KV operand would be
        # replicated per step (or fail to partition)
        allow_pallas = mesh is None or mesh.size == 1
        self.prefill_fn, self.decode_fn = model.make_step_fns(
            model_cfg, allow_pallas=allow_pallas)
        if hasattr(model, "make_decode_window_fn"):
            # model-provided fused window (read-only pool + window buffer:
            # one pool copy in HBM; see llama.make_decode_window_fn)
            self.decode_multi_fn = model.make_decode_window_fn(
                model_cfg, allow_pallas, self.ecfg.max_top_k)
        else:
            self.decode_multi_fn = _make_decode_multi(
                model, model_cfg, allow_pallas, self.ecfg.max_top_k)
        self.pm = PageManager(self.ecfg.num_pages, self.ecfg.page_size,
                              host_pages=self.ecfg.host_pages)
        # host-DRAM offload pools (same per-page layout as the HBM pool)
        self.host_k = self.host_v = None
        if self.ecfg.host_pages > 0:
            hshape = (model_cfg.num_layers, self.ecfg.host_pages,
                      model_cfg.num_kv_heads, self.ecfg.page_size,
                      model_cfg.head_dim_)
            hdtype = np.asarray(jnp.zeros((), self.kv_k.dtype)).dtype
            self.host_k = np.zeros(hshape, hdtype)
            self.host_v = np.zeros(hshape, hdtype)
        self.offload_pages_total = 0
        self.restore_pages_total = 0
        # guards PageManager between the event-loop thread (_admit) and
        # executor-thread disagg jobs (reserve/release/submit); engine steps
        # are already serialized with those jobs by the single-worker executor
        self._pm_lock = threading.Lock()
        self.waiting: List[Sequence] = []
        self.prefilling: List[Sequence] = []
        self.running: List[Sequence] = []
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._aio_loop: Optional[asyncio.AbstractEventLoop] = None
        self._stopped = False
        self._exec = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="jax-step")
        # observability (ForwardPassMetrics analog, kv_router/protocols.rs)
        self.steps = 0
        self.prefill_tokens_total = 0
        self.decode_tokens_total = 0
        self.prefix_hit_tokens_total = 0
        self.prompt_tokens_total = 0

    # ---------------------------------------------------------- lifecycle

    def warmup(self, progress: bool = False) -> int:
        """Pre-compile the full bucket grid (prefill T×P, decode B×P,
        sampling per B) so no compile ever happens mid-serving — a
        mid-flight compile stalls every in-flight request for the compile
        latency. Returns the number of programs compiled."""
        ecfg = self.ecfg
        page_buckets = [p for p in ecfg.page_buckets] or [8]
        t0 = time.monotonic()
        n = 0
        prefill_bs = {ecfg.bucket_batch(1),
                      ecfg.bucket_batch(ecfg.max_prefill_batch)}
        for P in page_buckets:
            for T in {ecfg.bucket_len(t) for t in ecfg.prefill_buckets}:
                for PB in prefill_bs:
                    logits, self.kv_k, self.kv_v = self.prefill_fn(
                        self.params, jnp.zeros((PB, T), jnp.int32),
                        jnp.zeros((PB, T), jnp.int32) - 1,
                        self.kv_k, self.kv_v, jnp.zeros((PB, P), jnp.int32),
                        jnp.full((PB, T), DROP_SLOT, jnp.int32),
                        jnp.zeros((PB,), jnp.int32))
                    sample_tokens(logits, jnp.zeros(PB),
                                  jnp.zeros(PB, jnp.int32), jnp.ones(PB),
                                  jnp.zeros(PB, jnp.uint32),
                                  jnp.zeros(PB, jnp.int32),
                                  max_top_k=ecfg.max_top_k)
                    n += 1
            for B in {ecfg.bucket_batch(b) for b in ecfg.batch_buckets}:
                tableB = jnp.zeros((B, P), jnp.int32)
                if ecfg.decode_steps > 1:
                    toks, self.kv_k, self.kv_v = self.decode_multi_fn(
                        self.params, jnp.zeros(B, jnp.int32),
                        jnp.zeros(B, jnp.int32) - 1, self.kv_k, self.kv_v,
                        tableB, jnp.zeros(B), jnp.zeros(B, jnp.int32),
                        jnp.ones(B), jnp.zeros(B, jnp.uint32),
                        jnp.zeros(B, jnp.int32),
                        k_steps=ecfg.decode_steps)
                else:
                    logits, self.kv_k, self.kv_v = self.decode_fn(
                        self.params, jnp.zeros(B, jnp.int32),
                        jnp.zeros(B, jnp.int32) - 1, self.kv_k, self.kv_v,
                        tableB, jnp.full((B,), DROP_SLOT, jnp.int32))
                    sample_tokens(logits, jnp.zeros(B),
                                  jnp.zeros(B, jnp.int32),
                                  jnp.ones(B), jnp.zeros(B, jnp.uint32),
                                  jnp.zeros(B, jnp.int32),
                                  max_top_k=ecfg.max_top_k)
                n += 1
                if progress:
                    print(f"warmup: {n} programs, {time.monotonic()-t0:.0f}s",
                          flush=True)
        jax.block_until_ready(self.kv_k)
        log.info("warmup compiled %d programs in %.1fs", n,
                 time.monotonic() - t0)
        return n

    def start(self) -> None:
        if self._loop_task is None:
            self._aio_loop = asyncio.get_running_loop()
            self._loop_task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        self._stopped = True
        self._wake.set()
        if self._loop_task:
            await self._loop_task
        self._exec.shutdown(wait=False)

    # ------------------------------------------------------ AsyncEngine API

    async def generate(self, request: PreprocessedRequest,
                       context: Context) -> AsyncIterator[EngineOutput]:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.from_dict(request)
        self.start()
        seq = Sequence(req=request, context=context, out=asyncio.Queue(),
                       tokens=list(request.token_ids),
                       num_prompt=len(request.token_ids))
        if seq.num_prompt == 0:
            yield EngineOutput(finish_reason="error", text="empty prompt")
            return
        self.waiting.append(seq)
        self._wake.set()
        while True:
            out: EngineOutput = await seq.out.get()
            yield out
            if out.finish_reason is not None:
                return

    # ------------------------------------------------------------- metrics

    def stats(self) -> dict:
        """ForwardPassMetrics analog for the KV router
        (reference kv_router/protocols.rs:18-30)."""
        return {
            "request_active_slots": len(self.running) + len(self.prefilling),
            "request_total_slots": self.ecfg.max_batch,
            "kv_active_blocks": self.pm.active,
            "kv_total_blocks": self.ecfg.num_pages - 1,
            "num_requests_waiting": len(self.waiting),
            "gpu_cache_usage_perc": self.pm.usage(),
            "gpu_prefix_cache_hit_rate":
                (self.prefix_hit_tokens_total /
                 max(self.prompt_tokens_total, 1)),
            "host_cache_usage_perc": self.pm.host_usage(),
            "host_offload_pages_total": self.offload_pages_total,
            "host_restore_pages_total": self.restore_pages_total,
        }

    # ------------------------------------------------------- scheduler loop

    async def _loop(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopped:
            if not (self.waiting or self.prefilling or self.running):
                self._wake.clear()
                await self._wake.wait()
                continue
            try:
                self._admit()
                # prefill-priority (measured better than interleaving
                # prefill+decode per iteration: TTFT and throughput both
                # win when prompt batches drain at full cadence)
                if self.prefilling:
                    await loop.run_in_executor(self._exec, self._prefill_step)
                elif self.running:
                    await loop.run_in_executor(self._exec, self._decode_step)
                self._reap()
            except Exception:  # noqa: BLE001 — engine loop must survive
                log.exception("engine step failed")
                for seq in self.prefilling + self.running:
                    with self._pm_lock:
                        self._release(seq)
                    self._finish(seq, "error")
                self.prefilling.clear()
                self.running.clear()
            # yield to the event loop so queues drain / new requests land
            await asyncio.sleep(0)

    # ----------------------------------------------------------- admission

    def _admit(self) -> None:
        while self.waiting and (len(self.running) + len(self.prefilling)
                                < self.ecfg.max_batch):
            seq = self.waiting[0]
            if seq.context.stopped:
                self.waiting.pop(0)
                self._finish(seq, FINISH_CANCELLED)
                continue
            with self._pm_lock:
                alloc = self.pm.allocate_sequence(seq.tokens)
                if (alloc is None
                        or self.pm.available < self.ecfg.watermark_pages):
                    if alloc is not None:
                        self.pm.release_sequence(alloc[0])
                    break  # out of pages; wait for frees
            self.waiting.pop(0)
            pages, cached_tokens = alloc
            seq.pages = pages
            seq.computed = min(cached_tokens, seq.prefill_extent)
            if seq.generated == 0:  # don't double-count resumed sequences
                self.prefix_hit_tokens_total += seq.computed
                self.prompt_tokens_total += seq.num_prompt
            self.prefilling.append(seq)

    # ------------------------------------------------------- KV tier drain

    def _drain_kv_tier(self) -> None:
        """Run queued HBM↔host page copies (executor thread, before any
        device step so offloads read pre-step content and restores land
        before their pages are attended to). Batched, pow2-padded gathers
        keep the compile count logarithmic in batch size."""
        if self.host_k is None:
            return
        with self._pm_lock:
            off, res = self.pm.drain_tier_ops()
        if off:
            pages = [p for p, _ in off]
            slots = [s for _, s in off]
            idx = jnp.asarray(_pad_pow2(pages, 0), jnp.int32)
            k = np.asarray(_gather_pages(self.kv_k, idx))
            v = np.asarray(_gather_pages(self.kv_v, idx))
            self.host_k[:, slots] = k[:, :len(off)]
            self.host_v[:, slots] = v[:, :len(off)]
            self.offload_pages_total += len(off)
        if res:
            pages = [p for p, _ in res]
            slots = [s for _, s in res]
            # pad targets out-of-range → dropped by the scatter; pad the
            # host gather with slot 0 (content discarded)
            idx = _pad_pow2(pages, self.ecfg.num_pages)
            hsl = _pad_pow2(slots, 0)
            self.kv_k = _inject_pages(self.kv_k, jnp.asarray(idx, jnp.int32),
                                      jnp.asarray(self.host_k[:, hsl]))
            self.kv_v = _inject_pages(self.kv_v, jnp.asarray(idx, jnp.int32),
                                      jnp.asarray(self.host_v[:, hsl]))
            self.restore_pages_total += len(res)

    # ------------------------------------------------------------- prefill

    def _prefill_step(self) -> None:
        """One chunked-prefill step over a BATCH of prefilling sequences
        (each contributes its next chunk). Batching prompts into one
        dispatch matters as much as the decode window when dispatch
        latency dominates: N prompts cost one round trip, not N."""
        self._drain_kv_tier()
        batch: List[Sequence] = []
        for seq in list(self.prefilling):
            if seq.context.stopped:
                self.prefilling.remove(seq)
                self._release(seq)
                self._finish(seq, FINISH_CANCELLED)
                continue
            if seq.prefill_extent - seq.computed <= 0:
                # resumed sequence fully covered by the prefix cache
                self.prefilling.remove(seq)
                seq.last_token = seq.tokens[-1]
                self.running.append(seq)
                continue
            batch.append(seq)
            if len(batch) >= self.ecfg.max_prefill_batch:
                break
        if not batch:
            return

        chunks = [min(s.prefill_extent - s.computed, self.ecfg.prefill_chunk)
                  for s in batch]
        B = self.ecfg.prefill_bucket_batch(len(batch))
        T = self.ecfg.bucket_len(max(chunks))
        P = self.ecfg.bucket_pages(max(len(s.pages) for s in batch))

        tokens = np.zeros((B, T), np.int32)
        positions = np.full((B, T), -1, np.int32)
        slots = np.full((B, T), DROP_SLOT, np.int32)
        table = np.zeros((B, P), np.int32)
        last_idx = np.zeros(B, np.int32)
        ps = self.ecfg.page_size
        for i, (seq, chunk) in enumerate(zip(batch, chunks)):
            start = seq.computed
            tokens[i, :chunk] = seq.tokens[start:start + chunk]
            positions[i, :chunk] = np.arange(start, start + chunk)
            pages = np.asarray(seq.pages, np.int64)
            pos = np.arange(start, start + chunk)
            slots[i, :chunk] = pages[pos // ps] * ps + pos % ps
            table[i, :len(seq.pages)] = seq.pages
            last_idx[i] = chunk - 1

        logits, self.kv_k, self.kv_v = self.prefill_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(positions),
            self.kv_k, self.kv_v, jnp.asarray(table), jnp.asarray(slots),
            jnp.asarray(last_idx))
        self.steps += 1

        finishing: List[Tuple[int, Sequence]] = []
        for i, (seq, chunk) in enumerate(zip(batch, chunks)):
            seq.computed += chunk
            self.prefill_tokens_total += chunk
            if seq.computed >= seq.prefill_extent:
                self.prefilling.remove(seq)
                finishing.append((i, seq))
        if not finishing:
            return
        # one sampling pass over the full bucket (avoids a fresh compile
        # per finishing-count); skipped entirely when every finishing row
        # is a preemption-resume (their next token was already sampled)
        if any(s.generated == 0 for _, s in finishing):
            sampled_all = self._sample(batch, logits)
            sampled = [sampled_all[i] for i, _ in finishing]
        else:
            sampled = [None] * len(finishing)
        for (i, seq), tok in zip(finishing, sampled):
            self._commit_full_pages(seq)
            if seq.generated == 0:
                self._append_token(seq, int(tok))
                if seq.finished is None:
                    self.running.append(seq)
            else:
                # resumed after preemption: last token already sampled
                seq.last_token = seq.tokens[-1]
                self.running.append(seq)

    # -------------------------------------------------------------- decode

    def _decode_step(self) -> None:
        self._drain_kv_tier()
        K = max(1, self.ecfg.decode_steps)
        batch = [s for s in self.running if s.finished is None]
        # submit_prefilled can push running past max_batch; overflow rows
        # simply wait a round (arrays below are sized ≤ max_batch)
        batch = batch[: self.ecfg.max_batch]
        if not batch:
            return
        # cancellations + page growth for the whole window (preempt newest
        # on OOM)
        for seq in list(batch):
            if seq.context.stopped:
                batch.remove(seq)
                self.running.remove(seq)
                self._release(seq)
                self._finish(seq, FINISH_CANCELLED)
                continue
            if not self.pm.grow(seq.pages, len(seq.tokens) + K):
                victim = max(self.running, key=lambda s: s.arrival)
                log.warning("KV pool exhausted; preempting %s", victim.context.id)
                if victim in batch:
                    batch.remove(victim)
                self.running.remove(victim)
                self._release(victim)
                victim.computed = 0  # keep tokens/generated: resume, not redo
                self.waiting.insert(0, victim)
                if victim is seq:
                    continue
                if not self.pm.grow(seq.pages, len(seq.tokens) + K):
                    batch.remove(seq)  # still no room; try next step
        if not batch:
            return

        B = self.ecfg.bucket_batch(len(batch))
        P = self.ecfg.bucket_pages(max(len(s.pages) for s in batch))
        tokens = np.zeros(B, np.int32)
        positions = np.full(B, -1, np.int32)
        table = np.zeros((B, P), np.int32)
        for i, seq in enumerate(batch):
            pos = len(seq.tokens) - 1  # position of last_token
            tokens[i] = seq.last_token
            positions[i] = pos
            table[i, :len(seq.pages)] = seq.pages

        if K == 1:
            slots = np.full(B, DROP_SLOT, np.int32)
            for i, seq in enumerate(batch):
                pos = len(seq.tokens) - 1
                page = seq.pages[pos // self.ecfg.page_size]
                slots[i] = (page * self.ecfg.page_size
                            + pos % self.ecfg.page_size)
            logits, self.kv_k, self.kv_v = self.decode_fn(
                self.params, jnp.asarray(tokens), jnp.asarray(positions),
                self.kv_k, self.kv_v, jnp.asarray(table), jnp.asarray(slots))
            sampled = self._sample(batch, logits)
            self.steps += 1
            self.decode_tokens_total += len(batch)
            for seq, tok in zip(batch, sampled):
                self._append_token(seq, int(tok))
            return

        # fused window: K forward+sample steps in one dispatch
        sb = SamplingBatch.build([s.req.sampling for s in batch], B)
        steps = np.zeros(B, np.int32)
        steps[:len(batch)] = [s.generated for s in batch]
        toks, self.kv_k, self.kv_v = self.decode_multi_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(positions),
            self.kv_k, self.kv_v, jnp.asarray(table),
            jnp.asarray(sb.temperature), jnp.asarray(sb.top_k),
            jnp.asarray(sb.top_p), jnp.asarray(sb.seeds),
            jnp.asarray(steps), k_steps=K)
        toks = np.asarray(toks)  # ONE host sync for the whole window
        self.steps += 1
        for i, seq in enumerate(batch):
            for j in range(K):
                if seq.finished is not None or seq.context.stopped:
                    break  # tokens past EOS/stop are discarded
                self._append_token(seq, int(toks[i, j]))
                self.decode_tokens_total += 1

    # ------------------------------------------------------------- helpers

    def _sample(self, seqs: List[Sequence], logits) -> np.ndarray:
        """logits: [B_padded, V] (bucketed); pads sampling params to match
        so every distinct batch bucket compiles exactly once."""
        pad_to = logits.shape[0]
        sb = SamplingBatch.build([s.req.sampling for s in seqs], pad_to)
        steps = np.zeros(pad_to, np.int32)
        steps[:len(seqs)] = [s.generated for s in seqs]
        toks = sample_tokens(logits, jnp.asarray(sb.temperature),
                             jnp.asarray(sb.top_k), jnp.asarray(sb.top_p),
                             jnp.asarray(sb.seeds), jnp.asarray(steps),
                             max_top_k=self.ecfg.max_top_k)
        return np.asarray(toks)[:len(seqs)]  # host sync (executor thread)

    def _append_token(self, seq: Sequence, tok: int) -> None:
        """Record a generated token: emit, check termination, commit pages."""
        seq.tokens.append(tok)
        seq.last_token = tok
        seq.generated += 1
        eos = (not seq.req.stop.ignore_eos and tok in seq.req.eos_token_ids) \
            or tok in (seq.req.stop.stop_token_ids or [])
        self._emit(seq, EngineOutput(token_ids=[tok],
                                     prompt_tokens=seq.num_prompt))
        # commit the page that just filled (prefix-cache publish)
        filled = len(seq.tokens)
        ps = self.ecfg.page_size
        if filled % ps == 0:
            nblocks = filled // ps
            hashes = chain_hashes(seq.tokens[:nblocks * ps], ps)
            parent = hashes[-2] if nblocks >= 2 else None
            self.pm.commit(seq.pages[nblocks - 1], hashes[-1],
                           parent_hash=parent)
        if eos:
            self._terminate(seq, FINISH_EOS)
        elif seq.generated >= seq.max_new():
            self._terminate(seq, FINISH_LENGTH)

    def _terminate(self, seq: Sequence, reason: str) -> None:
        if seq in self.running:
            self.running.remove(seq)
        self._release(seq)
        self._finish(seq, reason)

    def _commit_full_pages(self, seq: Sequence) -> None:
        ps = self.ecfg.page_size
        nblocks = seq.prefill_extent // ps
        hashes = chain_hashes(seq.tokens[:nblocks * ps], ps)
        for i, h in enumerate(hashes):
            self.pm.commit(seq.pages[i], h,
                           parent_hash=hashes[i - 1] if i else None,
                           token_ids=seq.tokens[i * ps:(i + 1) * ps])

    def _release(self, seq: Sequence) -> None:
        if seq.hold_pages:
            return  # disagg prefill-only: caller extracts, then releases
        if seq.pages:
            self.pm.release_sequence(seq.pages)
            seq.pages = []

    def _finish(self, seq: Sequence, reason: str) -> None:
        if seq.finished is None:
            seq.finished = reason
            self._emit(seq, EngineOutput(token_ids=[], finish_reason=reason,
                                         prompt_tokens=seq.num_prompt,
                                         completion_tokens=seq.generated))

    def _emit(self, seq: Sequence, out: EngineOutput) -> None:
        # steps run in the executor thread; asyncio.Queue is not thread-safe,
        # so route puts through the loop
        try:
            running_loop = asyncio.get_running_loop()
        except RuntimeError:
            running_loop = None
        if running_loop is self._aio_loop:
            seq.out.put_nowait(out)
        else:
            self._aio_loop.call_soon_threadsafe(seq.out.put_nowait, out)

    def _reap(self) -> None:
        """Drop finished sequences that linger in running (safety net)."""
        self.running = [s for s in self.running if s.finished is None]

    # ------------------------------------------------- disaggregation plane
    # Engine-side primitives for prefill/decode disaggregation (reference
    # vllm_v0.7.2-dynamo-kv-disagg-patch: remote_prefill.py
    # RemotePrefillRequest staging + DynamoNixlConnector block reads/writes).
    # On TPU the RDMA path becomes: gather pages → host bytes → TCP/DCN →
    # donated scatter back into the destination pool (llm/disagg/transfer.py);
    # same-process transfers skip the host round-trip entirely.

    async def reserve_remote(self, token_ids: List[int]
                             ) -> Optional["RemoteReservation"]:
        """Decode-side page reservation for a remote prefill: claims pages
        covering the prompt (reusing the longest cached prefix) without
        admitting a sequence. Returns None when the pool is full."""
        loop = asyncio.get_running_loop()

        def _do():
            with self._pm_lock:
                alloc = self.pm.allocate_sequence(token_ids)
            if alloc is None:
                return None
            return RemoteReservation(pages=alloc[0], cached_tokens=alloc[1],
                                     page_size=self.ecfg.page_size)

        return await loop.run_in_executor(self._exec, _do)

    async def release_pages(self, pages: List[int]) -> None:
        """Return pages claimed by reserve_remote()/prefill_only()."""
        loop = asyncio.get_running_loop()

        def _do():
            with self._pm_lock:
                self.pm.release_sequence(list(pages))

        await loop.run_in_executor(self._exec, _do)

    async def extract_pages(self, page_ids: List[int]
                            ) -> Tuple[np.ndarray, np.ndarray]:
        """Gather KV pages to host memory: returns (k, v) arrays of shape
        [L, n, KV, page_size, hd] (kv-head-major pool layout). Serialized
        with engine steps on the single-worker executor so it never races
        buffer donation."""
        loop = asyncio.get_running_loop()

        def _do():
            self._drain_kv_tier()  # restored pages must be resident first
            idx = jnp.asarray(page_ids, jnp.int32)
            return (np.asarray(self.kv_k[:, idx]),
                    np.asarray(self.kv_v[:, idx]))

        return await loop.run_in_executor(self._exec, _do)

    async def inject_pages(self, page_ids: List[int], k: np.ndarray,
                           v: np.ndarray) -> None:
        """Scatter host KV pages [L, n, KV, page_size, hd] into the pool at
        page_ids (donated jit — in-place on device; the block_copy.cu
        analog for ingest)."""
        loop = asyncio.get_running_loop()

        def _do():
            idx = jnp.asarray(page_ids, jnp.int32)
            self.kv_k = _inject_pages(self.kv_k, idx, jnp.asarray(k))
            self.kv_v = _inject_pages(self.kv_v, idx, jnp.asarray(v))
            jax.block_until_ready(self.kv_k)

        await loop.run_in_executor(self._exec, _do)

    async def prefill_only(self, request: PreprocessedRequest,
                           context: Context) -> Tuple[int, List[int]]:
        """Prefill worker path: compute the prompt's KV + sample the first
        token, holding the pages for extraction. Returns (first_token,
        page_ids); the caller MUST release_pages(page_ids) when done.
        (Reference prefill_worker.py:109-137 — max_tokens=1 generate.)"""
        import copy

        req = copy.copy(request)
        req.stop = copy.copy(request.stop)
        req.stop.max_tokens = 1
        self.start()
        seq = Sequence(req=req, context=context, out=asyncio.Queue(),
                       tokens=list(req.token_ids),
                       num_prompt=len(req.token_ids), hold_pages=True)
        if seq.num_prompt == 0:
            raise ValueError("empty prompt")
        self.waiting.append(seq)
        self._wake.set()
        first: Optional[int] = None
        while True:
            out: EngineOutput = await seq.out.get()
            if out.token_ids:
                first = out.token_ids[0]
            if out.finish_reason is not None:
                break
        if first is None:
            # failed before sampling: nothing to extract, so return the held
            # pages ourselves (hold_pages disabled the engine-side release)
            if seq.pages:
                await self.release_pages(seq.pages)
                seq.pages = []
            raise RuntimeError(f"prefill produced no token "
                               f"({out.finish_reason})")
        return first, seq.pages

    async def submit_prefilled(self, request: PreprocessedRequest,
                               context: Context, pages: List[int],
                               first_token: int) -> Sequence:
        """Decode-side entry after a remote prefill: the reserved pages now
        hold the prompt's KV (injected via inject_pages); enter decode
        directly with the remotely sampled first token already emitted."""
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.from_dict(request)
        self.start()
        seq = Sequence(req=request, context=context, out=asyncio.Queue(),
                       tokens=list(request.token_ids),
                       num_prompt=len(request.token_ids))
        seq.pages = list(pages)
        seq.computed = seq.num_prompt
        loop = asyncio.get_running_loop()

        def _do():
            self.prompt_tokens_total += seq.num_prompt
            with self._pm_lock:
                self._commit_full_pages(seq)  # prefix-cache publish + events
                self._append_token(seq, int(first_token))

        await loop.run_in_executor(self._exec, _do)
        if seq.finished is None:
            self.running.append(seq)
            self._wake.set()
        return seq


@dataclass
class RemoteReservation:
    """Decode-side pages claimed ahead of a remote prefill."""

    pages: List[int]
    cached_tokens: int  # prompt tokens already covered by the prefix cache
    page_size: int

    @property
    def skip_pages(self) -> int:
        """Leading pages the prefill worker need not transfer (already
        valid on the decode side via prefix-cache hits)."""
        return self.cached_tokens // self.page_size


def _make_decode_multi(model, cfg: ModelConfig, allow_pallas: bool,
                       max_top_k: int):
    """Fused K-step decode: forward → on-device sample → feed back, K
    times inside one jitted program (lax.scan). One dispatch + one host
    sync per K tokens — the decisive optimization when dispatch latency
    (remote/tunneled chips, Python overhead) exceeds step compute."""
    from ..models.llama import logits_at

    @partial(jax.jit, static_argnames=("k_steps",),
             donate_argnames=("kv_k", "kv_v"))
    def decode_multi(params, tokens, positions, kv_k, kv_v, page_table,
                     temperature, top_k, top_p, seeds, base_steps, *,
                     k_steps: int):
        B = tokens.shape[0]
        ps = kv_k.shape[3]
        P = page_table.shape[1]
        rows = jnp.arange(B)

        # UNROLLED (k_steps is static): an outer lax.scan would carry the
        # whole KV pools and XLA double-buffers scan carries — stacked on
        # the layer scan inside forward() that blows HBM. A straight-line
        # K-step program lets XLA alias the pool updates in place.
        tok, pos = tokens, positions
        toks = []
        for i in range(k_steps):
            page = page_table[rows, jnp.clip(pos // ps, 0, P - 1)]
            slot = jnp.where(pos >= 0, page * ps + pos % ps, DROP_SLOT)
            h, kv_k, kv_v = model.forward(
                params, cfg, tok[:, None], pos[:, None], kv_k, kv_v,
                page_table, slot[:, None], allow_pallas=allow_pallas)
            logits = logits_at(params, cfg, h, jnp.zeros(B, jnp.int32))
            nxt = sample_tokens(logits, temperature, top_k, top_p, seeds,
                                base_steps + i, max_top_k=max_top_k)
            tok = jnp.where(pos >= 0, nxt, 0)
            pos = jnp.where(pos >= 0, pos + 1, pos)
            toks.append(tok)
        return jnp.stack(toks, axis=1), kv_k, kv_v  # [B, k_steps]

    return decode_multi


@partial(jax.jit, donate_argnums=(0,))
def _inject_pages(pool: jax.Array, idx: jax.Array,
                  rows: jax.Array) -> jax.Array:
    """pool: [L, num_pages, KV, ps, hd]; rows: [L, n, KV, ps, hd].
    Out-of-range idx entries are dropped (padding)."""
    return pool.at[:, idx].set(rows.astype(pool.dtype), mode="drop")


@jax.jit
def _gather_pages(pool: jax.Array, idx: jax.Array) -> jax.Array:
    """pool: [L, num_pages, KV, ps, hd] → [L, n, KV, ps, hd]."""
    return pool[:, idx]


def _pad_pow2(lst: List[int], fill: int) -> List[int]:
    """Pad to the next power of two so batched page copies compile
    O(log n) distinct shapes instead of one per length."""
    n = 1
    while n < len(lst):
        n *= 2
    return list(lst) + [fill] * (n - len(lst))
