"""Batched token sampling (jitted, per-request parameters).

The sampling stage runs on-device right after the forward pass so only the
sampled token ids (a few bytes per sequence) cross back to the host — the
TPU-native replacement for the reference engines' sampler (vLLM
SamplingParams ← our SamplingOptions, lib/llm/src/protocols/common.rs).

Per-row temperature/top-k/top-p live in device arrays so one jitted function
serves heterogeneous batches (no recompile per request mix). Greedy rows are
temperature=0.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class SamplingBatch:
    """Per-row sampling parameters, padded to the decode batch size."""

    temperature: np.ndarray  # [B] float32; 0 → greedy
    top_k: np.ndarray        # [B] int32; 0 → disabled
    top_p: np.ndarray        # [B] float32; 1.0 → disabled
    seeds: np.ndarray        # [B] uint32 per-row RNG streams
    # OpenAI/HF penalties; neutral values disable each
    rep: np.ndarray          # [B] float32; 1.0 → disabled (HF semantics)
    freq: np.ndarray         # [B] float32; 0.0 → disabled
    pres: np.ndarray         # [B] float32; 0.0 → disabled

    @classmethod
    def build(cls, rows, pad_to: int) -> "SamplingBatch":
        """rows: list of SamplingOptions-like objects with .temperature,
        .top_k, .top_p, .seed (+ the penalty fields)."""
        B = pad_to
        temperature = np.zeros(B, np.float32)
        top_k = np.zeros(B, np.int32)
        top_p = np.ones(B, np.float32)
        seeds = np.zeros(B, np.uint32)
        rep = np.ones(B, np.float32)
        freq = np.zeros(B, np.float32)
        pres = np.zeros(B, np.float32)
        for i, s in enumerate(rows):
            temperature[i] = s.temperature if s.temperature is not None else 0.0
            top_k[i] = s.top_k or 0
            top_p[i] = s.top_p if s.top_p is not None else 1.0
            seeds[i] = (s.seed if s.seed is not None
                        else np.random.randint(0, 2**31)) & 0xFFFFFFFF
            rep[i] = (s.repetition_penalty
                      if getattr(s, "repetition_penalty", None) else 1.0)
            freq[i] = getattr(s, "frequency_penalty", None) or 0.0
            pres[i] = getattr(s, "presence_penalty", None) or 0.0
        return cls(temperature, top_k, top_p, seeds, rep, freq, pres)

    @property
    def has_penalties(self) -> bool:
        return bool((self.rep != 1.0).any() or (self.freq != 0.0).any()
                    or (self.pres != 0.0).any())


def apply_penalties(logits: jax.Array, counts: jax.Array,
                    presence: jax.Array, rep: jax.Array,
                    freq: jax.Array, pres: jax.Array,
                    bias=None) -> jax.Array:
    """Sampling penalties on raw logits (before temperature), vLLM
    order and semantics:

    - repetition (HF `RepetitionPenaltyLogitsProcessor`): tokens present
      ANYWHERE in the context (prompt + generated) get positive logits
      divided / negative logits multiplied by the penalty;
    - frequency/presence (OpenAI): subtract ``freq·count`` and
      ``pres·(count>0)`` where ``count`` is over GENERATED tokens only.

    counts: [B, V] generated-token counts; presence: [B, V] context
    presence (bool-ish); penalties are per-row [B].
    """
    present = presence > 0
    rp = rep[:, None]
    logits = jnp.where(
        present & (rp != 1.0),
        jnp.where(logits > 0, logits / rp, logits * rp), logits)
    cf = counts.astype(jnp.float32)
    logits = logits - freq[:, None] * cf - pres[:, None] * (cf > 0)
    if bias is not None:
        # OpenAI logit_bias [B, V]: plain additive, before sampling
        logits = logits + bias
    return logits


def update_penalty_state(penalties, sampled: jax.Array, done: jax.Array):
    """Fold a window step's sampled tokens into the penalty state — ONE
    implementation shared by both fused decode windows (llama
    decode_window and the engine's generic fallback), so the live-mask
    timing vs carry_step_update can never drift between them. ``done``
    is the PRE-step mask: tokens sampled while a row was live are the
    ones the host will append. Returns the updated tuple (or None
    through the penalty-free path)."""
    if penalties is None:
        return None
    counts, presence, rest = penalties[0], penalties[1], penalties[2:]
    if counts.shape[1] == 1:
        # bias-only placeholder state ([B, 1]): counts are unused by
        # apply_penalties (neutral rep/freq/pres) — nothing to fold in,
        # and a real scatter would index out of bounds
        return penalties
    rows = jnp.arange(counts.shape[0])
    live = jnp.logical_not(done).astype(counts.dtype)
    counts = counts.at[rows, sampled].add(live)
    presence = presence.at[rows, sampled].max(live.astype(presence.dtype))
    return (counts, presence) + rest


@partial(jax.jit, static_argnames=("max_top_k",))
def sample_tokens(logits: jax.Array, temperature: jax.Array,
                  top_k: jax.Array, top_p: jax.Array, seeds: jax.Array,
                  step: jax.Array, max_top_k: int = 64,
                  penalties=None) -> jax.Array:
    """Sample one token per row. logits: [B, V] float32; ``step`` is a
    scalar or per-row [B] decode-step counter (advances the RNG stream).

    Greedy rows (temperature==0) take argmax. Sampled rows apply
    [penalties →] temperature → top-k (static bound ``max_top_k``,
    per-row effective k) → top-p (nucleus) → categorical draw from a
    per-row fold_in'd key. ``penalties``, when given, is the tuple
    ``(counts [B,V], presence [B,V], rep [B], freq [B], pres [B])``
    consumed by :func:`apply_penalties`; None (the default and the only
    pre-compiled variant) keeps the penalty-free program.
    """
    if penalties is not None:
        logits = apply_penalties(logits, *penalties)
    step = jnp.broadcast_to(step, temperature.shape)
    B, V = logits.shape

    temp = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / temp

    # top-k within a static bound: take max_top_k once, mask per-row k.
    # Greedy rows reuse this pass too: argmax == top-1, and a separate
    # jnp.argmax over the full vocab costs ~2.5x the top_k call on TPU
    k_vals, k_idx = jax.lax.top_k(scaled, max_top_k)  # [B, K]
    greedy = k_idx[:, 0]
    ranks = jnp.arange(max_top_k)[None, :]
    eff_k = jnp.where(top_k[:, None] > 0,
                      jnp.minimum(top_k[:, None], max_top_k), max_top_k)
    k_vals = jnp.where(ranks < eff_k, k_vals, -jnp.inf)

    # top-p over the (sorted) top-k candidates
    probs = jax.nn.softmax(k_vals, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]  # always keep the first candidate
    k_vals = jnp.where(keep, k_vals, -jnp.inf)

    def row_sample(i):
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), seeds[i]), step[i])
        choice = jax.random.categorical(key, k_vals[i])
        return k_idx[i, choice]

    sampled = jax.vmap(row_sample)(jnp.arange(B))
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


@partial(jax.jit, static_argnames=("max_top_k",))
def verify_greedy_draft(logits: jax.Array, draft: jax.Array,
                        draft_len: jax.Array, max_top_k: int = 64
                        ) -> Tuple[jax.Array, jax.Array]:
    """Vectorized accept-mask + bonus-token draw for self-speculative
    decode (greedy rows only — the engine bypasses speculation for
    sampled/penalized/logprobs requests).

    logits: [B, K+1, V] from the multi-token verify forward, where
    position j's logits predict the token AFTER input j (input 0 is the
    row's pending decode token, inputs 1..K the draft); draft: [B, K];
    draft_len: [B] valid draft tokens per row (rows ride with shorter —
    or padded-empty — drafts in the same static program).

    Returns (out_tokens [B, K+1], accepted [B]): row i emits
    ``out_tokens[i, :accepted[i] + 1]`` — the accepted draft prefix plus
    the bonus token greedily drawn at the first divergent (or final)
    position; entries past that are -1.

    The greedy target is computed exactly as :func:`sample_tokens`'
    greedy arm (``lax.top_k`` first element over the temperature-1
    logits), so speculation on/off is token-identical by construction,
    tie-breaking included.
    """
    B, K1, V = logits.shape
    K = K1 - 1
    _, k_idx = jax.lax.top_k(logits.reshape(B * K1, V), max_top_k)
    greedy = k_idx[:, 0].reshape(B, K1).astype(jnp.int32)
    match = jnp.logical_and(draft == greedy[:, :K],
                            jnp.arange(K)[None, :] < draft_len[:, None])
    # longest all-true prefix: cumprod zeroes everything past a miss
    accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    bonus = jnp.take_along_axis(greedy, accepted[:, None], axis=1)
    steps = jnp.arange(K1)[None, :]
    draft_ext = jnp.concatenate(
        [draft.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1)
    out = jnp.where(steps < accepted[:, None], draft_ext,
                    jnp.where(steps == accepted[:, None], bonus, -1))
    return out.astype(jnp.int32), accepted


def _gather_rows(logp: jax.Array, chosen: jax.Array) -> jax.Array:
    return logp[jnp.arange(logp.shape[0]), chosen]


def compute_logprobs(logits: jax.Array, chosen: jax.Array) -> jax.Array:
    """Log-probability of the chosen tokens: logits [B, V], chosen [B]."""
    return _gather_rows(jax.nn.log_softmax(logits, axis=-1), chosen)


def logprob_aux(logits: jax.Array, chosen: jax.Array, topn: int):
    """(chosen_logprob [B], top_vals [B, topn], top_ids [B, topn]) over
    the RAW model logits — OpenAI logprobs describe the model's
    distribution, so penalties/temperature are not reflected (vLLM's
    default differs; this is the documented contract here)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    tv, ti = jax.lax.top_k(logp, topn)
    return _gather_rows(logp, chosen), tv, ti
