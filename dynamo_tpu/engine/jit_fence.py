"""Runtime compile fence: detect XLA compilation after warmup.

``JaxEngine.warmup()`` pre-compiles the full bucket grid so no compile
ever happens mid-serving — a mid-flight compile stalls every in-flight
request for the compile latency (seconds on TPU). The static side of
that invariant is dynajit (tools/dynalint, DL015-DL017); this module is
the runtime side: a fence armed at the end of ``warmup()`` that counts
every XLA compilation afterwards via JAX's monitoring hook
(``/jax/core/compile/backend_compile_duration`` fires once per real
backend compile and never on cache hits).

``DYN_JIT_FENCE`` picks the reaction:

- unset/empty — count only: the counter is exported through
  ``stats()`` → ``ForwardPassMetrics`` →
  ``dyn_engine_post_warmup_compiles_total`` so the fleet metrics
  aggregator sees a mid-serving compile on any worker;
- ``warn`` — additionally log a warning with the compile duration;
- ``raise`` — raise ``PostWarmupCompileError`` from the compile path
  (the CI/test mode: the offending jit call fails loudly).

Every compile also lands a ``compile`` event in the engine's dyntrace
step timeline, so ``/v1/traces`` shows exactly where in the serving
schedule the stall happened.

The monitoring event carries only a duration — no call info — so the
engine stamps every fenced jit dispatch via ``note_dispatch`` (one
attribute store of raw refs, no formatting on the hot path). When the
fence trips, warn/raise messages and the blackbox trigger render that
note lazily into a call-form key (jit name + per-operand dtype[shape]
and static kwarg values): the runtime twin of dynaform's DL026
warmup-form-drift key.

The JAX monitoring API has no unregister, so ONE process-wide listener
is installed lazily and dispatches to live fences (weakly referenced —
a dropped engine stops counting). Compiles are process-global: with two
engines in one process (disagg smoke tests) a compile triggered by
either increments both armed fences, which is the honest reading — the
process stalled.
"""

from __future__ import annotations

import logging
import threading
import weakref
from typing import Optional

from ..runtime.config import env_str

log = logging.getLogger("dynamo_tpu.engine.fence")

# the per-compile duration event (fires on real backend compiles only;
# cache hits and device_put do not record it)
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_fences: "weakref.WeakSet[CompileFence]" = weakref.WeakSet()
_install_lock = threading.Lock()
_installed = False


class PostWarmupCompileError(RuntimeError):
    """An XLA compile happened after warmup with DYN_JIT_FENCE=raise."""


def _dispatch(event: str, duration_secs: float, **_kw) -> None:
    if event != COMPILE_EVENT:
        return
    for fence in list(_fences):
        fence.on_compile(duration_secs)


def _install_listener() -> None:
    global _installed
    with _install_lock:
        if _installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_dispatch)
        _installed = True


class CompileFence:
    """Per-engine post-warmup compile counter + warn/raise tripwire."""

    def __init__(self, name: str, timeline=None,
                 mode: Optional[str] = None):
        self.name = name
        self.timeline = timeline
        self._mode_override = mode
        self.armed = False
        self.post_warmup_compiles = 0
        # (jit name, args, kwargs) of the most recent fenced dispatch —
        # raw refs only; the call-form summary is rendered lazily when a
        # fence trips (never on the dispatch hot path)
        self._last_dispatch: Optional[tuple] = None

    def note_dispatch(self, name: str, args: tuple = (),
                      kwargs: Optional[dict] = None) -> None:
        """Stamp the jitted call about to run. The compile monitoring
        event carries only a duration, so when the fence trips this note
        is the only way to name the offending call form. Cheap by
        design: one attribute store, no formatting."""
        self._last_dispatch = (name, args, kwargs)

    @staticmethod
    def _summ(x, depth: int = 0) -> str:
        dt = getattr(x, "dtype", None)
        sh = getattr(x, "shape", None)
        if dt is not None and sh is not None:
            return f"{dt}[{','.join(str(d) for d in sh)}]"
        if x is None or isinstance(x, (bool, int, float, str)):
            return repr(x)
        if isinstance(x, (tuple, list)) and depth < 2:
            inner = ", ".join(
                CompileFence._summ(e, depth + 1) for e in x[:4])
            if len(x) > 4:
                inner += f", ...{len(x)} items"
            return f"({inner})"
        return type(x).__name__

    def last_dispatch_form(self) -> str:
        """Render the most recent dispatch as a call-form key: jit name
        plus per-operand dtype[shape] / static-value summary."""
        if self._last_dispatch is None:
            return "<no dispatch recorded>"
        name, args, kwargs = self._last_dispatch
        try:
            parts = [self._summ(a) for a in args]
            for k, v in (kwargs or {}).items():
                parts.append(f"{k}={self._summ(v)}")
            return f"{name}({', '.join(parts)})"
        except Exception:  # never let diagnostics mask the real trip
            return f"{name}(<unprintable args>)"

    @property
    def mode(self) -> str:
        if self._mode_override is not None:
            return self._mode_override
        return (env_str("DYN_JIT_FENCE") or "").strip().lower()

    def arm(self) -> None:
        """Called at the end of warmup(): from here on, every backend
        compile counts against the zero-compile serving invariant."""
        _install_listener()
        _fences.add(self)
        self.armed = True
        # end of warmup = steady state begins: snapshot the pre-incident
        # cost-table/cache baseline dynablack postmortems diff against
        from ..runtime import blackbox
        rec = blackbox.get_recorder()
        if rec.enabled:
            rec.refresh_baseline()

    def disarm(self) -> None:
        self.armed = False

    def on_compile(self, duration_secs: float) -> None:
        if not self.armed:
            return
        self.post_warmup_compiles += 1
        if self.timeline is not None:
            self.timeline.add("compile",
                              duration_ms=round(duration_secs * 1e3, 3),
                              post_warmup_total=self.post_warmup_compiles)
        # a post-warmup compile is an incident by definition (the
        # zero-compile invariant broke); already on the cold compile path
        from ..runtime import blackbox
        blackbox.notify_trigger("post_warmup_compile", {
            "fence": self.name,
            "duration_ms": round(duration_secs * 1e3, 3),
            "post_warmup_total": self.post_warmup_compiles,
            "last_dispatch_form": self.last_dispatch_form(),
        })
        mode = self.mode
        if mode == "raise":
            raise PostWarmupCompileError(
                f"XLA compile after warmup on {self.name} "
                f"({duration_secs * 1e3:.1f} ms, "
                f"{self.post_warmup_compiles} total): an unbucketed "
                f"shape or request-varying static arg reached a jitted "
                f"call — last dispatched form: "
                f"{self.last_dispatch_form()} — see dynajit/dynaform "
                f"(docs/static_analysis.md)")
        if mode == "warn":
            log.warning(
                "XLA compile after warmup on %s (%.1f ms, %d total): "
                "an unbucketed shape or request-varying static arg "
                "reached a jitted call — last dispatched form: %s",
                self.name, duration_secs * 1e3,
                self.post_warmup_compiles, self.last_dispatch_form())
