"""dynaprof engine layer: sampled device/host split + per-bucket cost.

The serving loop's time goes three places: device compute, host dispatch
(Python building arrays + enqueueing the jitted call), and event-loop /
queue stalls. The runtime layer (runtime/profiling.py) measures the
third; this module measures the first two — *per compiled program* — so
"383 vs 1129 tok/s is scheduler overhead, not FLOPs" becomes a table,
not an inference.

Mechanism: every ``DYN_PROF_SAMPLE``-th scheduler iteration is a
*sampled* iteration. On a sampled iteration each dispatch is bracketed —
``t0 → dispatch returns (host cost) → block_until_ready (device
queue+compute drain)`` — and the figures accumulate into a per-bucket
cost table keyed by ``kind:B..xP..[xT/K..]``, i.e. exactly the compiled
program the warmed grid provides. The ``block_until_ready`` is a
DELIBERATE host sync: it serializes that one iteration's pipeline (the
documented sampling overhead), which is why it is

- gated behind ``self.sampling`` (dynalint DL018 fails an unguarded
  sync in profiler code paths), and
- completely absent at ``DYN_PROF_SAMPLE=0`` (default): the per-dispatch
  cost is one integer compare — the compile fence + step timeline stay
  byte-identical (tests/test_profiling.py pins this).

The table exposes which ``(bucket_len, bucket_batch)`` programs the
ROADMAP item-3 hot-path overhaul must attack: dispatch-µs per program is
the scheduler-overhead term, tokens/s per program the FLOPs term.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax

from ..runtime import profiling
from ..runtime.config import env_int


class EngineProfiler:
    """Per-engine sampled dispatch timer + cost table. All mutation
    happens on the engine's single-worker executor thread (the same
    serialization the scheduler itself relies on); ``summary()`` reads
    are snapshot-style dict builds."""

    def __init__(self, name: str, timeline=None,
                 sample: Optional[int] = None):
        if sample is None:
            sample = env_int("DYN_PROF_SAMPLE") or 0
        self.name = name
        self.sample = max(int(sample), 0)
        self.timeline = timeline
        self.sampling = False      # True while the CURRENT iteration samples
        self._iter = 0
        self.profiled_steps = 0
        self.device_seconds_total = 0.0
        self.dispatch_seconds_total = 0.0
        # "kind:B8xP64[xT512|xK4]" -> {samples, device_us, dispatch_us, tokens}
        self.buckets: Dict[str, dict] = {}
        profiling.register_profile(name, self)

    # ------------------------------------------------------------ sampling

    def tick(self) -> None:
        """Once per scheduler iteration. At sample=0 this is the whole
        hot-path cost: one compare, no syncs, no timeline writes."""
        if self.sample <= 0:
            self.sampling = False
            return
        self._iter += 1
        self.sampling = (self._iter % self.sample) == 0

    def begin(self) -> Optional[float]:
        """Dispatch-bracket start, or None when this iteration is not
        sampled (so ``end`` is a no-op and not even perf_counter runs)."""
        return time.perf_counter() if self.sampling else None

    def end(self, t0: Optional[float], kind: str, key: Tuple[int, ...],
            tokens: int = 0, sync_ref=None) -> None:
        """Dispatch-bracket end: host cost = return-from-dispatch − t0;
        device cost = the drain until ``sync_ref`` is ready (queue +
        compute — under pipelining this includes previously enqueued
        work, which is the honest figure for "what the device is doing
        while the host dispatches")."""
        if self.sampling and t0 is not None:
            t1 = time.perf_counter()
            # the deliberate sampled sync (see module docstring)
            jax.block_until_ready(sync_ref)
            t2 = time.perf_counter()
            self._record(kind, key, t1 - t0, t2 - t1, tokens)

    def _record(self, kind: str, key: Tuple[int, ...], dispatch_s: float,
                device_s: float, tokens: int) -> None:
        label = f"{kind}:" + "x".join(str(k) for k in key)
        # bounded-by: labels are pow2-padded bucket shapes (fixed vocab)
        row = self.buckets.setdefault(label, {
            "samples": 0, "device_us": 0.0, "dispatch_us": 0.0,
            "tokens": 0})
        row["samples"] += 1
        row["device_us"] += device_s * 1e6
        row["dispatch_us"] += dispatch_s * 1e6
        row["tokens"] += int(tokens)
        self.profiled_steps += 1
        self.device_seconds_total += device_s
        self.dispatch_seconds_total += dispatch_s
        if self.timeline is not None:
            # bounded-by: StepTimeline is a deque(maxlen=) ring
            self.timeline.add(
                "prof_sample", bucket=label,
                dispatch_us=round(dispatch_s * 1e6, 1),
                device_us=round(device_s * 1e6, 1), tokens=int(tokens))

    # ------------------------------------------------------------- exports

    def device_time_fraction(self) -> float:
        total = self.device_seconds_total + self.dispatch_seconds_total
        return self.device_seconds_total / total if total > 0 else 0.0

    def mean_device_ms_per_step(self) -> Optional[float]:
        """Mean sampled device-drain per dispatch — the scale factor the
        per-request attribution uses to turn occupancy-weighted step
        shares into an estimated device-ms figure. None when nothing has
        been sampled (sample=0)."""
        if self.profiled_steps == 0:
            return None
        return self.device_seconds_total / self.profiled_steps * 1000.0

    def cost_table(self) -> Dict[str, dict]:
        """Per-bucket means: dispatch/device µs per dispatch plus
        device-side tokens/s — the regression surface for scheduler
        overhead per compiled program."""
        out: Dict[str, dict] = {}
        for label, row in sorted(self.buckets.items()):
            n = max(row["samples"], 1)
            dev_s = row["device_us"] / 1e6
            out[label] = {
                "samples": row["samples"],
                "dispatch_us": round(row["dispatch_us"] / n, 1),
                "device_us": round(row["device_us"] / n, 1),
                "tokens_per_s": (round(row["tokens"] / dev_s, 1)
                                 if dev_s > 0 and row["tokens"] else 0.0),
            }
        return out

    def summary(self) -> dict:
        return {
            "sample_every": self.sample,
            "profiled_steps": self.profiled_steps,
            "device_time_fraction": round(self.device_time_fraction(), 4),
            "device_seconds_total": round(self.device_seconds_total, 6),
            "dispatch_seconds_total": round(self.dispatch_seconds_total, 6),
            "buckets": self.cost_table(),
        }


def memory_snapshot(pm, page_bytes: int) -> dict:
    """HBM/page occupancy accounting from a PageManager: live (allocated,
    refcounted), cached (reusable prefix pages), free — in pages and KV
    bytes — plus the host tier when configured. Host-side reads only."""
    free = len(pm.free)
    cached = len(pm.reusable)
    live = pm.num_pages - 1 - free - cached
    out = {
        "page_bytes": page_bytes,
        "hbm": {
            "live_pages": live, "cached_pages": cached, "free_pages": free,
            "live_bytes": live * page_bytes,
            "cached_bytes": cached * page_bytes,
            "free_bytes": free * page_bytes,
        },
    }
    if pm.host_pages > 0:
        host_free = len(pm.host_free)
        host_used = pm.host_pages - host_free
        out["host"] = {
            "used_pages": host_used, "free_pages": host_free,
            "used_bytes": host_used * page_bytes,
        }
    return out
