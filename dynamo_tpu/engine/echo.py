"""Deterministic echo engines — the framework's no-TPU test engines.

Reference launch/dynamo-run/src/output/{echo_core.rs,echo_full.rs}:1-89:
``EchoEngineCore`` echoes the prompt tokens back one-by-one at a fixed
cadence (token-level, sits behind the Backend detokenizer);
``EchoEngineFull`` echoes at the OpenAI level. They exercise the entire
serving stack (HTTP → preprocessor → router → worker → backend → SSE) with
no accelerator, making the distributed plane CI-testable (SURVEY §4).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from ..llm.protocols.common import EngineOutput, PreprocessedRequest
from ..runtime.engine import Context

DEFAULT_DELAY_MS = 1.0


class EchoEngineCore:
    """Token-level echo: yields the prompt's tokens back as output tokens."""

    def __init__(self, delay_ms: float = DEFAULT_DELAY_MS):
        self.delay_ms = delay_ms

    async def generate(self, request: PreprocessedRequest,
                       context: Context) -> AsyncIterator[EngineOutput]:
        ids = list(request.token_ids)
        max_tokens = request.stop.max_tokens or len(ids)
        prompt_tokens = len(ids)
        for i, tid in enumerate(ids[:max_tokens]):
            if context.stopped:
                return
            if self.delay_ms:
                await asyncio.sleep(self.delay_ms / 1000.0)
            yield EngineOutput(token_ids=[tid], prompt_tokens=prompt_tokens)
        yield EngineOutput(token_ids=[], finish_reason="length"
                           if max_tokens < len(ids) else "stop",
                           prompt_tokens=prompt_tokens)


class EchoEngineFull:
    """OpenAI-level echo: streams the last user message's text back in
    word-sized deltas (bypasses tokenization entirely)."""

    def __init__(self, delay_ms: float = DEFAULT_DELAY_MS):
        self.delay_ms = delay_ms

    async def generate(self, request, context: Context):
        # request: ChatCompletionRequest-shaped dict or object
        messages = request["messages"] if isinstance(request, dict) else request.messages

        def _text(m) -> str:
            if not isinstance(m, dict):
                return m.text()
            content = m.get("content")
            if isinstance(content, str):
                return content
            if isinstance(content, list):  # OpenAI multipart content
                return "".join(p.get("text", "") for p in content
                               if isinstance(p, dict) and p.get("type") == "text")
            return ""

        text = ""
        for m in reversed(messages):
            role = m["role"] if isinstance(m, dict) else m.role
            if role == "user":
                text = _text(m)
                break
        for word in text.split(" "):
            if context.stopped:
                return
            if self.delay_ms:
                await asyncio.sleep(self.delay_ms / 1000.0)
            yield {"text": word + " "}
        yield {"text": "", "finish_reason": "stop"}
