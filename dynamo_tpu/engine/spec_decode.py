"""Self-speculative decoding: model-free prompt-lookup drafting.

The decode loop is memory-bandwidth-bound — one token per device step
leaves the MXU idle between HBM sweeps. Speculative decoding (Leviathan
et al. 2023) converts that slack into accepted tokens: a cheap drafter
proposes K candidates, ONE batched multi-token forward verifies them
against the target model, and the longest matching prefix (plus the
"bonus" token from the first divergent position) is accepted — every
step emits between 1 and K+1 tokens for roughly the cost of one.

The drafter here is the model-free prompt-lookup scheme (Saxena 2023,
"Prompt Lookup Decoding"): the sequence's OWN history (prompt +
generated tokens) is the draft model. The longest suffix n-gram that
also occurs earlier in the history predicts its historical continuation.
This costs no second model, no extra HBM, and shines exactly where
serving workloads repeat themselves — code, RAG quotes, multi-turn
summaries, JSON schemas.

Host-side by design: the lookup is a few-microsecond numpy scan per
sequence per step, and keeping it on the host means the device program
set stays a single static-[B, K+1] verify forward (see
models/llama.py:make_verify_fn and jax_engine._decode_step_spec).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def propose_ngram_draft(tokens: Sequence[int], max_draft: int,
                        ngram_max: int, ngram_min: int = 1) -> List[int]:
    """Propose up to ``max_draft`` tokens continuing ``tokens``.

    Matches the longest suffix n-gram (``ngram_max`` down to
    ``ngram_min`` tokens, the last of which is the pending decode input)
    against every earlier position in the history. Among the hits, the
    MOST RECENT one that can supply a full ``max_draft``-token
    continuation wins — recency because generation loops continue their
    latest cycle, fullness because short-period loops (the common greedy
    cycle) would otherwise always truncate the draft to the period
    length. Returns [] when nothing matches (the caller falls back to
    the standard decode path for this row).
    """
    L = len(tokens)
    if max_draft <= 0 or L < ngram_min + 1:
        return []
    arr = np.asarray(tokens, dtype=np.int64)
    for n in range(min(ngram_max, L - 1), max(ngram_min, 1) - 1, -1):
        pat = arr[L - n:]
        # candidate starts 0..L-1-n: strictly earlier than the suffix
        # itself, but allowed to overlap it (self-periodic continuations)
        hay = np.lib.stride_tricks.sliding_window_view(arr[:L - 1], n)
        hits = np.nonzero((hay == pat).all(axis=1))[0]
        if hits.size == 0:
            continue
        avail = (L - hits) - n  # continuation tokens before history ends
        full = hits[avail >= max_draft]
        # hits ascend, so avail descends: argmax picks the longest
        # continuation when no hit can fill the whole draft
        start = int(full[-1]) if full.size else int(hits[np.argmax(avail)])
        follow = arr[start + n:start + n + max_draft]
        if follow.size:
            return [int(t) for t in follow]
    return []
