"""Serving engines: the JAX engine (paged KV, continuous batching) and the
deterministic echo engines used for accelerator-free testing."""

from .echo import EchoEngineCore, EchoEngineFull
