"""int8 compression for KV pages crossing a slow boundary.

KV pages leave HBM in two places: the host-DRAM tier (engine/kv_manager
multi-tier pool — reference KV block manager V2's host tier) and the
disaggregation transfer plane (llm/disagg/transfer.py — the NIXL
replacement). Both move whole pages ``[L, n, KV, ps, hd]`` over links
that are orders of magnitude slower than HBM (PCIe/relay for D2H, DCN
TCP for disagg). Quantizing per (token, head) row to int8 with an f32
amax/127 scale halves the bytes on those links (hd bytes + 4 vs 2·hd
bf16) at a per-element error ≤ s/2 — the LMCache/CacheGen-style KV
compression the GPU stacks apply at the same boundary.

Lossy ⇒ strictly OPT-IN (EngineConfig.host_tier_int8, PrefillWorker
compress_kv / DYN_KV_TRANSFER_INT8): restored pages round-trip through
int8, so decode on them is no longer bit-identical to a run that never
offloaded. Pages inside HBM always stay in the pool dtype.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def quantize_pages(pages: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Device-side: [L, n, KV, ps, hd] → (int8 same shape, f32 scales
    [L, n, KV, ps, 1]). Runs BEFORE the D2H copy so the slow link moves
    int8, not bf16."""
    a32 = pages.astype(jnp.float32)
    amax = jnp.max(jnp.abs(a32), axis=-1, keepdims=True)
    s = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.rint(a32 / s), -127, 127).astype(jnp.int8)
    return q, s


@jax.jit
def dequantize_pages(q: jax.Array, s: jax.Array) -> jax.Array:
    """Device-side inverse (f32; the pool scatter casts to pool dtype).
    Runs AFTER the H2D copy, for the same reason."""
    return q.astype(jnp.float32) * s


def quantize_pages_np(pages: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side variant for the transfer plane (pages are already host
    arrays there — extract_pages staged them)."""
    a32 = np.asarray(pages, np.float32)
    amax = np.max(np.abs(a32), axis=-1, keepdims=True)
    s = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.rint(a32 / s), -127, 127).astype(np.int8)
    return q, s


def dequantize_pages_np(q: np.ndarray, s: np.ndarray,
                        dtype) -> np.ndarray:
    return (np.asarray(q, np.float32) * s).astype(dtype)
