"""`dynamo-run` — the single-command launcher: ``in=… out=…``.

Reference launch/dynamo-run (SURVEY §2.5): one binary wiring an input
frontend to an output engine:

    python -m dynamo_tpu.run in=http out=jax --model-path /models/llama
    python -m dynamo_tpu.run in=text out=echo_core
    python -m dynamo_tpu.run in=batch:prompts.jsonl out=jax --model tiny
    python -m dynamo_tpu.run in=dyn://ns.comp.generate out=jax ...  # worker
    python -m dynamo_tpu.run in=http out=dyn                        # frontend

Inputs (reference dynamo-run lib.rs Input):
  http           OpenAI HTTP frontend (chat + completions + models + metrics)
  text           interactive chat REPL
  batch:<jsonl>  benchmark mode: per-request tokens_in/tokens_out/elapsed_ms
                 + aggregate throughput (reference input/batch.rs:42-105)
  dyn://path     worker mode: serve the engine behind the LLM pipeline on
                 the distributed runtime + register the model for discovery
                 (reference input/endpoint.rs:35-117)
  none           construct the engine, idle until SIGINT (warmup/debug)

Outputs (reference dynamo-run Output):
  jax            the JAX paged-KV engine (this framework's vLLM analog)
  echo_core      token-level echo fake engine (CI, no TPU)
  echo_full      OpenAI-level echo fake engine
  dyn[://path]   remote engines discovered from the control plane
                 (in=http becomes the standalone frontend, components/http)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import signal
import sys
import time

from .runtime.config import env_int, env_str
from typing import Optional, Tuple

log = logging.getLogger("dynamo_tpu.run")


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="dynamo-run", usage="%(prog)s in=<input> out=<engine> [flags]")
    ap.add_argument("io", nargs="*", help="in=… and out=… positionals")
    ap.add_argument("--model-path", help="local HF-style model directory")
    ap.add_argument("--model-id", default=None,
                    help="HuggingFace model id (or local path) — resolved "
                         "cache-first via the HF hub (reference "
                         "launch/dynamo-run/src/hub.rs)")
    ap.add_argument("--model-name", help="served model name")
    ap.add_argument("--model", default=None,
                    help="preset when no --model-path: tiny|1b|8b")
    ap.add_argument("--http-port", type=int, default=8080)
    ap.add_argument("--http-host", default="0.0.0.0")
    ap.add_argument("--dcp", default=None, help="control-plane address "
                    "(default: DYN_DCP_ADDRESS or embedded)")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--endpoint", default=None,
                    help="override dyn:// endpoint path")
    ap.add_argument("--context-length", type=int, default=None)
    ap.add_argument("--kv-cache-block-size", type=int, default=None,
                    help="tokens per KV page (reference flag name)")
    ap.add_argument("--num-pages", type=int, default=None)
    ap.add_argument("--max-batch-size", type=int, default=None)
    ap.add_argument("--tensor-parallel-size", type=int, default=1)
    ap.add_argument("--sequence-parallel-size", type=int, default=1,
                    help="seq-axis mesh size for ring-attention long "
                         "prefill (long-context serving)")
    ap.add_argument("--mesh-shape", default=env_str("DYN_MESH_SHAPE"),
                    help="dynashard: per-replica mesh as 'axis=N' pairs "
                         "(e.g. 'model=2', 'data=2,model=4'); overrides "
                         "--tensor-parallel-size/--sequence-parallel-size")
    ap.add_argument("--dp-replicas", type=int,
                    default=env_int("DYN_DP_REPLICAS") or 1,
                    help="dynashard: data-parallel engine replicas, each "
                         "on its own submesh with its own worker identity "
                         "behind the KV router (worker mode, out=jax)")
    ap.add_argument("--spec-decode", action="store_true",
                    help="self-speculative decoding: prompt-lookup drafts "
                         "verified in one [B, K+1] forward; greedy rows "
                         "only (token-identical), others bypass "
                         "(docs/serve.md 'Speculative decoding')")
    ap.add_argument("--spec-tokens", type=int, default=4,
                    help="max draft tokens verified per step (K)")
    ap.add_argument("--prefill-token-budget", type=int, default=None,
                    help="cap prompt tokens prefilled per engine "
                         "iteration and interleave decode windows "
                         "(chunked-prefill mixing; bounds ITL p99 under "
                         "prompt bursts at some TTFT cost)")
    ap.add_argument("--long-prefill-threshold", type=int, default=None,
                    help="prompts longer than this take the sequence-"
                         "parallel ring prefill (needs "
                         "--sequence-parallel-size > 1)")
    # multi-host SPMD bootstrap (replaces the reference's Ray head/follower
    # for vLLM multi-node TP, lib/llm/src/engines/vllm/ray.rs, and
    # SGLang's leader-addr handshake, engines/sglang/main.rs:48-76):
    # every process runs THIS same command with its own --process-id; JAX
    # forms the global device mesh across them
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 for jax.distributed "
                         "(multi-host TP; all processes pass the same value)")
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--max-tokens", type=int, default=128,
                    help="text/batch mode generation cap")
    ap.add_argument("--profile-dir", default=env_str(
        "DYN_PROFILE_DIR"), help="capture a JAX/XLA profiler trace of the "
        "serving session into this directory (view with xprof/tensorboard)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "int8"],
                    help="int8 = weight-only quantized serving "
                         "(models/quant.py): checkpoints quantize on the "
                         "host at load; ~half the HBM and decode "
                         "bytes/token of bf16")
    args = ap.parse_args(argv)

    if args.model_id and not args.model_path:
        from .models.hub import resolve_model
        args.model_path = resolve_model(args.model_id)
        if not args.model_name:
            args.model_name = args.model_id

    args.input, args.output = "http", "jax"
    for tok in args.io:
        if tok.startswith("in="):
            args.input = tok[3:]
        elif tok.startswith("out="):
            args.output = tok[4:]
        else:
            ap.error(f"positional args must be in=…/out=…, got {tok!r}")
    return args


# ------------------------------------------------------------ engine build


def build_model_config(args):
    from .models.config import ModelConfig

    if args.model_path:
        return ModelConfig.from_local_path(args.model_path)
    preset = args.model or "tiny"
    if preset == "tiny":
        return ModelConfig.tiny()
    if preset == "1b":
        return ModelConfig(vocab_size=128256, hidden_size=2048,
                           intermediate_size=8192, num_layers=16,
                           num_heads=32, num_kv_heads=8, head_dim=64,
                           dtype="bfloat16")
    if preset == "8b":
        return ModelConfig.llama3_8b()
    raise SystemExit(f"unknown --model preset {preset!r}")


def build_mdc(args):
    from .llm.model_card import ModelDeploymentCard

    if args.model_path:
        mdc = ModelDeploymentCard.from_local_path(
            args.model_path, name=args.model_name)
    else:
        mdc = ModelDeploymentCard(name=args.model_name or
                                  (args.model or "echo"))
    if args.context_length:
        mdc.context_length = args.context_length
    if args.kv_cache_block_size:
        mdc.kv_block_size = args.kv_cache_block_size
    return mdc


def build_engine(args) -> Tuple[object, object, bool]:
    """Returns (core_or_full_engine, mdc, is_full_level)."""
    from .engine.echo import EchoEngineCore, EchoEngineFull

    mdc = build_mdc(args)
    if args.output == "echo_core":
        return EchoEngineCore(), mdc, False
    if args.output == "echo_full":
        return EchoEngineFull(), mdc, True
    if args.output.startswith(("pystr:", "pytok:")):
        # user Python engines (reference engines/python.rs: pystr = full
        # OpenAI level, pytok = token-level core behind the Backend)
        kind, path = args.output.split(":", 1)
        return _load_python_engine(path, kind), mdc, kind == "pystr"
    if args.output == "jax":
        from .engine.jax_engine import JaxEngine

        cfg, ecfg, params, quant, mesh = _jax_engine_setup(args)
        mdc.kv_block_size = ecfg.page_size
        engine = JaxEngine(cfg, ecfg, params=params, seed=args.seed,
                           mesh=mesh, quant=quant)
        if not args.no_warmup:
            engine.warmup(progress=True)
        return engine, mdc, False
    raise SystemExit(f"unknown out={args.output!r}")


def mesh_axes_for(args) -> dict:
    """The per-replica mesh axes: --mesh-shape (or DYN_MESH_SHAPE) wins;
    the per-axis convenience flags otherwise."""
    from .parallel.serving import parse_mesh_shape

    if getattr(args, "mesh_shape", None):
        return parse_mesh_shape(args.mesh_shape)
    axes = {}
    if args.tensor_parallel_size > 1:
        axes["model"] = args.tensor_parallel_size
    if args.sequence_parallel_size > 1:
        axes["seq"] = args.sequence_parallel_size
    return axes


def _jax_engine_setup(args):
    """The out=jax configuration assembly, shared by the single-engine
    build and the dynashard replica set: returns
    (model_cfg, engine_cfg, params, quant, mesh). ``mesh`` is the
    whole-local-device mesh of the single-engine path; the replica set
    ignores it and partitions submeshes itself (mesh_axes_for)."""
    import dataclasses

    from .engine.jax_engine import EngineConfig
    from .models.loader import load_params

    cfg = build_model_config(args)
    ecfg = EngineConfig()
    if args.model in (None, "tiny") and not args.model_path:
        ecfg = EngineConfig(page_size=16, num_pages=256, max_batch=16,
                            prefill_chunk=128, prefill_buckets=(128,),
                            batch_buckets=(4, 16), page_buckets=(16,))
    overrides = {}
    if args.kv_cache_block_size:
        overrides["page_size"] = args.kv_cache_block_size
        # keep the chunk a page multiple (the page-granular KV commit
        # invariant __post_init__ enforces)
        overrides["prefill_chunk"] = max(
            ecfg.prefill_chunk // args.kv_cache_block_size, 1
        ) * args.kv_cache_block_size
    if args.num_pages:
        overrides["num_pages"] = args.num_pages
    if args.max_batch_size:
        overrides["max_batch"] = args.max_batch_size
    if args.prefill_token_budget is not None:
        overrides["prefill_token_budget"] = args.prefill_token_budget
    if args.spec_decode:
        overrides["spec_decode"] = True
        overrides["spec_tokens"] = args.spec_tokens
    if overrides:
        # replace() re-runs __post_init__ — CLI overrides get the same
        # validation as direct construction
        ecfg = dataclasses.replace(ecfg, **overrides)
    params = None
    mesh = None
    if args.coordinator:
        from .parallel.mesh import initialize_multihost
        initialize_multihost(args.coordinator, args.num_processes,
                             args.process_id)
        log.info("joined multi-host group %s as process %d/%d "
                 "(%d global devices)", args.coordinator,
                 args.process_id, args.num_processes,
                 len(__import__("jax").devices()))
    axes = mesh_axes_for(args)
    if axes and getattr(args, "dp_replicas", 1) <= 1:
        from .parallel.mesh import MeshSpec
        mesh = MeshSpec(**axes).build()
    if args.long_prefill_threshold is not None:
        if axes.get("seq", 1) <= 1:
            raise SystemExit(
                "--long-prefill-threshold needs a seq mesh axis > 1 "
                "(--sequence-parallel-size or --mesh-shape seq=N: the "
                "ring prefill runs over the mesh's seq axis)")
        ecfg = dataclasses.replace(
            ecfg, long_prefill_threshold=args.long_prefill_threshold)
    quant = "int8" if args.dtype == "int8" else None
    if args.model_path:
        try:
            params = load_params(args.model_path, cfg, quant=quant)
            quant = None  # already applied on the host at load
        except FileNotFoundError:
            log.warning("no weights at %s; random init", args.model_path)
    return cfg, ecfg, params, quant, mesh


def _load_python_engine(path: str, kind: str):
    """Load a user engine file (reference engines/python.rs:16-90 —
    ``pystr:<file.py>`` / ``pytok:<file.py>``): the module must define
    ``async def generate(request, context)`` (async generator). pystr
    yields OpenAI chunk dicts; pytok yields EngineOutput-shaped dicts."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("dyn_user_engine", path)
    if spec is None or spec.loader is None:
        raise SystemExit(f"cannot load python engine from {path!r}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    gen = getattr(mod, "generate", None)
    if gen is None:
        raise SystemExit(f"{path} must define `async def generate(request, "
                         f"context)`")

    if kind == "pystr":
        class _PyStrEngine:
            def __call__(self, request, context):
                payload = request.model_dump(exclude_none=True) \
                    if hasattr(request, "model_dump") else request
                return gen(payload, context)

        return _PyStrEngine()

    class _PyTokEngine:
        async def generate(self, request, context):
            from .llm.protocols.common import EngineOutput

            payload = request.to_dict() if hasattr(request, "to_dict") \
                else request
            async for out in gen(payload, context):
                yield out if isinstance(out, EngineOutput) \
                    else EngineOutput.from_dict(out)

    return _PyTokEngine()


# -------------------------------------------------------------- input modes


async def run_http(args) -> None:
    from .llm.engines import LocalChatChain, LocalCompletionChain
    from .llm.http.discovery import ModelWatcher
    from .llm.http.service import HttpService, ModelManager

    manager = ModelManager()
    svc = HttpService(manager)
    watcher = None
    drt = None
    if args.output.startswith("dyn"):
        # standalone frontend: discover models from the control plane
        # (reference components/http/src/main.rs + model watcher)
        drt = await _attach(args)
        watcher = ModelWatcher(drt, manager)
        await watcher.start()
    else:
        engine, mdc, full = await asyncio.to_thread(build_engine, args)
        if full:
            manager.add_chat_model(mdc.name, engine)
        else:
            pre = None
            chat = LocalChatChain(mdc, engine)
            comp = LocalCompletionChain(mdc, engine, chat.preprocessor)
            manager.add_chat_model(mdc.name, chat)
            manager.add_completions_model(mdc.name, comp)
        from .runtime import revive

        if hasattr(engine, "stats"):
            # dynarevive admission control over the local engine's own
            # signals; sheds nothing until DYN_SHED_* thresholds are set
            svc.set_admission(revive.AdmissionController(
                lambda: revive.signals_from_stats(engine.stats())))
        if hasattr(engine, "drain"):
            # POST /drain: stop admitting, finish in-flight bounded by
            # DYN_DRAIN_TIMEOUT_MS
            svc.on_drain(lambda: engine.drain(revive.drain_timeout_s()))
    await svc.start(args.http_host, args.http_port)
    log.info("OpenAI frontend on %s:%d", args.http_host, args.http_port)
    await _wait_for_signal()
    await svc.stop()
    if watcher:
        await watcher.stop()
    if drt:
        await drt.shutdown()


async def run_text(args) -> None:
    from .llm.engines import LocalChatChain
    from .runtime.engine import Context

    engine, mdc, full = await asyncio.to_thread(build_engine, args)
    chain = engine if full else LocalChatChain(mdc, engine)
    print(f"chat with {mdc.name} — empty line or ^D to exit", flush=True)
    history = []
    loop = asyncio.get_running_loop()
    while True:
        try:
            line = await loop.run_in_executor(None, lambda: input("> "))
        except (EOFError, KeyboardInterrupt):
            break
        if not line.strip():
            break
        history.append({"role": "user", "content": line})
        req = {"model": mdc.name, "messages": history, "stream": True,
               "max_tokens": args.max_tokens}
        from .llm.protocols.openai import ChatCompletionRequest

        from .llm.http.service import _chunk_dict

        text = []
        async for chunk in chain(ChatCompletionRequest(**req), Context()):
            d = _chunk_dict(chunk)
            if not isinstance(d, dict):
                continue
            for c in d.get("choices", []):
                delta = (c.get("delta") or {}).get("content")
                if delta:
                    text.append(delta)
                    print(delta, end="", flush=True)
        print()
        history.append({"role": "assistant", "content": "".join(text)})
    if hasattr(engine, "stop"):
        await engine.stop()


async def run_batch(args, path: str) -> None:
    """Benchmark mode (reference input/batch.rs:42-105): JSONL in
    ({"text": …} or {"prompt": …}), JSONL out with per-request tokens_in/
    tokens_out/elapsed_ms; aggregate printed at the end."""
    from .llm.engines import LocalChatChain
    from .llm.protocols.openai import ChatCompletionRequest
    from .runtime.engine import Context

    engine, mdc, full = await asyncio.to_thread(build_engine, args)
    chain = engine if full else LocalChatChain(mdc, engine)

    def _read_jsonl() -> list:
        entries = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    entries.append(json.loads(line))
        return entries

    # file IO off the event loop: batch inputs can be large
    entries = await asyncio.to_thread(_read_jsonl)
    results = []
    t0 = time.monotonic()

    async def one(i, entry):
        text = entry.get("text") or entry.get("prompt") or ""
        req = ChatCompletionRequest(
            model=mdc.name, stream=True,
            messages=[{"role": "user", "content": text}],
            max_tokens=entry.get("max_tokens", args.max_tokens))
        from .llm.http.service import _chunk_dict

        start = time.monotonic()
        n_out = 0
        async for chunk in chain(req, Context()):
            d = _chunk_dict(chunk)
            if isinstance(d, dict) and d.get("choices"):
                if (d["choices"][0].get("delta") or {}).get("content"):
                    n_out += 1
        elapsed = time.monotonic() - start
        results.append({"index": i, "tokens_in": len(text.split()),
                        "tokens_out": n_out,
                        "elapsed_ms": round(elapsed * 1000, 1)})

    await asyncio.gather(*(one(i, e) for i, e in enumerate(entries)))
    wall = time.monotonic() - t0
    for r in sorted(results, key=lambda r: r["index"]):
        print(json.dumps(r))
    total_out = sum(r["tokens_out"] for r in results)
    print(json.dumps({"aggregate": {
        "requests": len(results), "wall_s": round(wall, 3),
        "output_tok_per_s": round(total_out / wall, 1) if wall else 0.0}}))
    if hasattr(engine, "stop"):
        await engine.stop()


async def run_worker(args, path: str) -> None:
    """``in=dyn://ns.comp[.ep]``: serve the engine as a discoverable model
    worker (reference input/endpoint.rs worker mode). With
    ``--dp-replicas N > 1`` (out=jax only) the process serves a dynashard
    :class:`ShardedReplicaSet` instead: N mesh-sharded engine replicas on
    partitioned submeshes, each its own worker instance behind the KV
    router."""
    from .llm.worker import serve_openai_model
    from .runtime.component import EndpointAddress

    if args.dp_replicas > 1:
        if args.output != "jax":
            raise SystemExit("--dp-replicas needs out=jax")
        await _run_sharded_worker(args, path)
        return
    engine, mdc, full = await asyncio.to_thread(build_engine, args)
    if full:
        raise SystemExit("worker mode needs a token-level engine "
                         "(out=jax or out=echo_core)")
    addr = EndpointAddress.parse(path)
    drt = await _attach(args)
    handle = await serve_openai_model(
        drt, mdc, engine, namespace=addr.namespace,
        component=addr.component, endpoint=addr.endpoint,
        stats_handler=getattr(engine, "stats", None))
    log.info("worker serving %s", path)
    sig = await _wait_for_signal()
    if sig == signal.SIGTERM:
        # rolling restart: discovery record out first (no new
        # admissions), in-flight sequences finish bounded by
        # DYN_DRAIN_TIMEOUT_MS, then the lease releases (dynarevive)
        from .runtime import revive

        await revive.drain_worker(handle, engine=engine)
    else:
        await handle.stop()
    if hasattr(engine, "stop"):
        await engine.stop()
    await drt.shutdown()


async def _run_sharded_worker(args, path: str) -> None:
    """dynashard worker mode: N data-parallel sharded replicas of one
    token-level component, each with its own lease/instance id and KV
    publisher (parallel/serving.py)."""
    from .parallel.serving import ShardedReplicaSet
    from .runtime.component import EndpointAddress

    addr = EndpointAddress.parse(path)
    cfg, ecfg, params, quant, _mesh = await asyncio.to_thread(
        _jax_engine_setup, args)
    # card construction can read model files — off the event loop
    mdc = await asyncio.to_thread(build_mdc, args)
    mdc.kv_block_size = ecfg.page_size
    replica_set = ShardedReplicaSet(
        cfg, ecfg, mesh_axes=mesh_axes_for(args),
        replicas=args.dp_replicas, namespace=addr.namespace,
        component=addr.component, mdc=mdc,
        dcp_address=args.dcp or env_str("DYN_DCP_ADDRESS"),
        params=params, seed=args.seed, quant=quant,
        warmup=not args.no_warmup)
    await replica_set.start()
    log.info("sharded worker serving %s: %s", path, replica_set.describe())
    sig = await _wait_for_signal()
    if sig == signal.SIGTERM:
        # lifecycle drain bounded internally by DYN_DRAIN_TIMEOUT_MS
        await replica_set.drain()  # dynalint: disable=unbounded-await
    else:
        await replica_set.stop()


async def run_none(args) -> None:
    engine, mdc, _ = await asyncio.to_thread(build_engine, args)
    log.info("engine %s ready (in=none); ^C to exit", mdc.name)
    await _wait_for_signal()
    if hasattr(engine, "stop"):
        await engine.stop()


# ----------------------------------------------------------------- helpers


async def _attach(args):
    from .runtime.runtime import DistributedRuntime

    address = args.dcp or env_str("DYN_DCP_ADDRESS")
    if address:
        return await DistributedRuntime.attach(address)
    log.warning("no control plane configured; starting embedded DCP server")
    return await DistributedRuntime.detached()


async def _wait_for_signal() -> int:
    """Park until SIGINT/SIGTERM; returns the signal number so callers
    can pick fast teardown (SIGINT) vs graceful drain (SIGTERM — the
    rolling-restart signal, dynarevive docs/robustness.md)."""
    ev = asyncio.Event()
    fired: list = []
    loop = asyncio.get_running_loop()

    def _on_signal(signum: int) -> None:
        if not fired:
            fired.append(signum)
        ev.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, _on_signal, sig)
        except NotImplementedError:
            pass
    await ev.wait()
    return fired[0] if fired else signal.SIGINT


async def amain(args) -> int:
    profiling = False
    if args.profile_dir:
        # tracing/profiling plane (reference keeps tracing-crate spans;
        # on TPU the device story is the JAX profiler / XLA dumps)
        import jax

        jax.profiler.start_trace(args.profile_dir)
        profiling = True
    try:
        return await _dispatch(args)
    finally:
        if profiling:
            import jax

            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", args.profile_dir)


async def _dispatch(args) -> int:
    if args.input == "http":
        await run_http(args)
    elif args.input == "text":
        await run_text(args)
    elif args.input.startswith("batch:"):
        await run_batch(args, args.input[len("batch:"):])
    elif args.input.startswith("dyn://") or args.input.startswith("dyn"):
        await run_worker(args, args.input)
    elif args.input == "none":
        await run_none(args)
    else:
        raise SystemExit(f"unknown in={args.input!r}")
    return 0


def main(argv=None) -> int:
    logging.basicConfig(level=env_str("DYN_LOG"))
    return asyncio.run(amain(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
