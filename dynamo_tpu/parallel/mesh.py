"""Device mesh construction + sharding specs.

The TPU-native replacement for the reference's engine-delegated parallelism
(SURVEY §2.4: vLLM `--tensor-parallel-size` + Ray head/follower for
multi-node TP, engines/vllm/ray.rs; SGLang per-rank subprocesses): here one
worker = one SPMD program over a ``jax.sharding.Mesh``, and GSPMD inserts
the collectives that NCCL calls performed in the reference.

Axes:
- ``data``  — batch rows (independent sequences; DP within one engine)
- ``seq``   — sequence/context parallelism (ring attention over ICI for
  long-context prefill; absent in the reference — SURVEY §5 long-context)
- ``model`` — tensor parallelism: attention heads / MLP hidden / vocab
- ``expert``— MoE expert parallelism (falls back onto ``model`` when absent)

Multi-host: ``initialize_multihost`` wraps ``jax.distributed.initialize``
(coordinator address per slice — the Ray replacement; SURVEY §5).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig


@dataclass
class MeshSpec:
    data: int = 1
    model: int = 1
    expert: int = 1
    seq: int = 1
    stage: int = 1  # pipeline parallelism (parallel/pipeline_parallel.py)

    @property
    def num_devices(self) -> int:
        return self.data * self.model * self.expert * self.seq * self.stage

    def build(self, devices=None) -> Mesh:
        devices = devices if devices is not None else jax.devices()
        if len(devices) < self.num_devices:
            raise ValueError(
                f"mesh needs {self.num_devices} devices, have {len(devices)}")
        # stage sits outside seq/model (its activation handoffs are
        # infrequent bulk transfers, fine across slower links); seq
        # innermost-but-one so ring ppermute hops ride neighbouring ICI
        # links; model innermost (highest-bandwidth all-reduces)
        devs = np.asarray(devices[: self.num_devices]).reshape(
            self.data, self.expert, self.stage, self.seq, self.model)
        return Mesh(devs, ("data", "expert", "stage", "seq", "model"))

    @classmethod
    def single(cls) -> "MeshSpec":
        return cls()


def initialize_multihost(coordinator: str, num_processes: int,
                         process_id: int) -> None:
    """Join a multi-host SPMD group (replaces the reference's Ray/torch-dist
    bootstrap, engines/vllm/ray.rs + sglang MultiGPUConfig)."""
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def param_pspecs(cfg: ModelConfig) -> Dict[str, P]:
    """PartitionSpecs for the params pytree (megatron-style TP):
    column-parallel qkv/gate/up, row-parallel o/down, vocab-sharded
    embed/lm_head; GSPMD derives the psums."""
    specs: Dict[str, P] = {
        "embed": P("model", None),          # vocab-sharded
        "wq": P(None, None, "model"),
        "wk": P(None, None, "model"),
        "wv": P(None, None, "model"),
        "wo": P(None, "model", None),
        "ln_attn": P(None, None),
        "ln_mlp": P(None, None),
        "ln_attn_post": P(None, None),  # Gemma-2 sandwich norms
        "ln_mlp_post": P(None, None),
        "q_norm": P(None, None),        # Qwen3 per-head q/k norms
        "k_norm": P(None, None),
        "ln_final": P(None),
        "lm_head": P(None, "model"),
    }
    if cfg.is_mla:
        # MLA: heads live only in the up-projections — shard those over
        # "model"; the rank-r latent path + tiny cache stay replicated
        specs.update({
            "w_dkv": P(None, None, None),
            "kv_norm": P(None, None),
            "w_uk": P(None, None, "model"),
            "w_uv": P(None, None, "model"),
            "w_o": P(None, "model", None),
            "w_q": P(None, None, "model"),
            "w_dq": P(None, None, None),
            "q_norm": P(None, None),
            "w_uq": P(None, None, "model"),
            # DeepSeek-MoE segments (models/mla.py): routed experts over
            # the expert axis (TP inside each expert), dense-first and
            # shared-expert MLPs megatron-style
            "w_gate_d": P(None, None, "model"),
            "w_up_d": P(None, None, "model"),
            "w_down_d": P(None, "model", None),
            "w_gate_e": P(None, "expert", None, "model"),
            "w_up_e": P(None, "expert", None, "model"),
            "w_down_e": P(None, "expert", "model", None),
            "w_gate_s": P(None, None, "model"),
            "w_up_s": P(None, None, "model"),
            "w_down_s": P(None, "model", None),
            "router_bias": P(None, None),
        })
    if cfg.attn_bias:
        specs.update({"bq": P(None, "model"), "bk": P(None, "model"),
                      "bv": P(None, "model")})
    if cfg.num_experts > 0:
        specs.update({
            "w_router": P(None, None, None),
            # experts sharded over the expert axis; per-expert matrices
            # additionally TP-sharded over model
            "w_gate": P(None, "expert", None, "model"),
            "w_up": P(None, "expert", None, "model"),
            "w_down": P(None, "expert", "model", None),
        })
    else:
        specs.update({
            "w_gate": P(None, None, "model"),
            "w_up": P(None, None, "model"),
            "w_down": P(None, "model", None),
        })
    return specs


def kv_cache_pspec(cfg: ModelConfig) -> P:
    """KV pool [L, pages, kv_heads, page_size, head_dim]: heads over
    "model" (requires kv_heads % model_parallel == 0 — true for Llama-3
    8B/70B GQA at TP<=8); replicated over "data" so any data row can
    reference any page."""
    return P(None, None, "model", None, None)


def shard_params(params, cfg: ModelConfig, mesh: Mesh):
    from ..models.quant import QuantInt8

    specs = param_pspecs(cfg)
    out = {}
    for k, v in params.items():
        spec = specs.get(k, P(*([None] * v.ndim)))
        if isinstance(v, QuantInt8):
            # scale shape = weight shape with the contraction axis (-2)
            # collapsed to 1 — that axis must stay unsharded in the
            # scale's spec (can't split a size-1 dim over "model")
            s_spec = P(*spec[:-2], None, spec[-1])
            out[k] = QuantInt8(
                jax.device_put(v.q, NamedSharding(mesh, spec)),
                jax.device_put(v.s, NamedSharding(mesh, s_spec)))
        else:
            out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def shard_kv_cache(kv_k, kv_v, cfg: ModelConfig, mesh: Mesh):
    if cfg.is_mla:
        # latent cache has a single shared "head" — replicate over TP
        s = NamedSharding(mesh, P(None, None, None, None, None))
    else:
        s = NamedSharding(mesh, kv_cache_pspec(cfg))
    return jax.device_put(kv_k, s), jax.device_put(kv_v, s)


def shard_batch(mesh: Mesh, **arrays):
    """device_put step inputs sharded batch-first over "data" (every
    per-step array — tokens/positions/page_table/flat_slots/last_idx — has
    the batch as its leading axis); returns dict keyed by name."""
    import jax.numpy as jnp

    out = {}
    for name, arr in arrays.items():
        arr = jnp.asarray(arr)
        spec = P("data", *([None] * (arr.ndim - 1))) if arr.ndim else P()
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out
