"""Pipeline parallelism over the mesh's ``stage`` axis.

SURVEY §2.4's last unbuilt row. The reference inherits pipeline
parallelism from its engines (vLLM ``--pipeline-parallel-size``, which
its own disagg deployments force to 1 — reference
docs/disagg_serving.md); the TPU-native shape is not NCCL
point-to-points between per-rank processes but a single SPMD program:
layers are stacked on a leading axis (models/llama.py init_params), so
stage-sharding is nothing more than ``P("stage")`` on that axis, and the
GPipe-style schedule is a ``lax.scan`` whose carry rotates activations
one stage forward with ``lax.ppermute`` each tick.

Schedule: with S stages and M microbatches (split over the batch dim),
the scan runs S+M-1 ticks; at tick t stage s computes microbatch t-s
(bubble fraction (S-1)/(S+M-1), amortized by M). Stage 0 embeds fresh
microbatches; the last stage collects hidden states, applies the final
norm + LM head, and a masked ``psum`` replicates the logits to every
stage so the caller sees a plain array.

This module provides the forward plane (full-attention prefill → logits,
the compute that dominates PP deployments) + param shardings; paged
decode under PP would additionally stage-shard the KV pool's layer axis
and is deliberately out of scope until a deployment needs it (the
reference ships PP=1 everywhere it matters).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.llama import (Params, _layer_keys, _sliding_flag,
                            embed_tokens, full_attention_layer,
                            project_logits, rms_norm, rope_freqs)

# params stacked on a leading layer axis get that axis stage-sharded;
# everything else (embed, final norm, head) is replicated
# every per-layer param name any config can produce (superset of
# llama._layer_keys across configs — pp_param_specs has no cfg in hand,
# it shards whatever per-layer keys are present in the pytree)
_STACKED = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
            "ln_attn", "ln_mlp", "ln_attn_post", "ln_mlp_post",
            "q_norm", "k_norm", "bq", "bk", "bv", "w_router")


def pp_param_specs(params: Params) -> Dict[str, P]:
    return {k: (P("stage") if k in _STACKED else P())
            for k in params}


def shard_params_pp(params: Params, mesh: Mesh) -> Params:
    specs = pp_param_specs(params)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def make_pp_forward(cfg: ModelConfig, mesh: Mesh,
                    num_microbatches: int = 4):
    """Jitted pipelined forward: ``fn(params, tokens[B, T]) -> logits
    [B, T, V]`` (float32), numerically matching
    ``models.llama.reference_forward``.

    B must divide into ``num_microbatches`` equal microbatches and
    ``cfg.num_layers`` into ``mesh.shape['stage']`` equal stages.
    """
    S = mesh.shape["stage"]
    if cfg.num_layers % S != 0:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                         f"{S} stages")
    if cfg.num_experts > 0:
        raise NotImplementedError("PP forward covers dense models; "
                                  "stage-shard MoE when a deployment "
                                  "needs both PP and EP")
    M = num_microbatches
    inv_freq = rope_freqs(cfg)
    scale = cfg.attn_scale

    def _local_layers(h, lp_stack, layer_off):
        """Run this stage's layer slice (leading axis L/S) over h
        [b, T, D] — the shared full-attention layer body. ``layer_off``
        is the stage's global layer offset (Gemma-2's sliding-window
        parity is indexed by GLOBAL layer, not stage-local)."""
        b, T = h.shape[:2]
        n_local = cfg.num_layers // S
        pos = jnp.broadcast_to(jnp.arange(T)[None, :], (b, T))

        def layer(h, xs):
            lp, li = xs
            return full_attention_layer(
                cfg, h, lp, pos, inv_freq, scale,
                is_sliding=_sliding_flag(cfg, layer_off + li)), None

        h, _ = lax.scan(layer, h,
                        (lp_stack, jnp.arange(n_local)))
        return h

    # the per-layer key set is owned by llama._layer_keys — PP stages
    # scan exactly the params the shared layer body consumes
    stacked_keys = _layer_keys(cfg)

    def _fwd(params, tokens):
        """Per-stage body (under shard_map over 'stage'): tokens
        [M, b, T] replicated; stacked params arrive as the local
        [L/S, ...] slice."""
        ax = lax.axis_index("stage")
        lp_stack = {k: params[k] for k in stacked_keys}
        _, b, T = tokens.shape
        D = params["embed"].shape[1]
        dt = params["embed"].dtype

        def tick(carry, t):
            recv, outbuf = carry
            # stage 0 injects microbatch t (clamped once the injection
            # phase is over; the result is masked out by collection)
            emb = embed_tokens(params, cfg,
                               tokens[jnp.clip(t, 0, M - 1)])
            my_in = jnp.where(ax == 0, emb, recv)
            out = _local_layers(my_in, lp_stack,
                                ax * (cfg.num_layers // S))
            # last stage collects microbatch t-(S-1) once it emerges
            oidx = t - (S - 1)
            oidx_c = jnp.clip(oidx, 0, M - 1)
            valid = (oidx >= 0) & (ax == S - 1)
            cur = lax.dynamic_index_in_dim(outbuf, oidx_c, 0,
                                           keepdims=False)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(valid, out, cur), oidx_c, 0)
            # rotate activations one stage forward
            nxt = lax.ppermute(out, "stage",
                               [(i, i + 1) for i in range(S - 1)])
            return (nxt, outbuf), None

        recv0 = jnp.zeros((b, T, D), dt)
        outbuf0 = jnp.zeros((M, b, T, D), dt)
        (_, outbuf), _ = lax.scan(tick, (recv0, outbuf0),
                                  jnp.arange(S + M - 1))

        h = rms_norm(outbuf, params["ln_final"], cfg.rms_norm_eps,
                     cfg.norm_unit_offset)
        logits = project_logits(params, cfg, h)
        # only the last stage holds real outputs; masked psum replicates
        logits = jnp.where(ax == S - 1, logits, 0.0)
        return lax.psum(logits, "stage")

    def forward(params, tokens):
        B, T = tokens.shape
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible into {M} "
                             f"microbatches")
        mb = tokens.reshape(M, B // M, T)
        in_specs = (pp_param_specs(params), P())
        fn = shard_map(_fwd, mesh=mesh, in_specs=in_specs, out_specs=P(),
                       check_vma=False)
        out = fn(params, mb)           # [M, b, T, V]
        return out.reshape(B, T, -1)

    return jax.jit(forward)
