"""dynashard: mesh-sharded serving — data-parallel engine replicas on
partitioned submeshes behind the KV router.

The multichip machinery (``parallel/mesh.py`` sharding specs, ring
attention, the sharded Pallas wrappers) existed only as kernels and
dryruns; this module is the subsystem that serves REAL requests through
it:

- :func:`parse_mesh_shape` / :data:`DYN_MESH_SHAPE` — one string knob
  (``"model=2"``, ``"data=2,model=4"``) naming the per-replica mesh.
- :class:`DevicePool` — deterministic submesh assignment over the local
  device set: replicas acquire contiguous device groups lowest-index
  first, drained replicas return theirs, and joins re-partition onto the
  freed devices. Pure bookkeeping (devices are opaque), shared by the
  real replica set below and the fleet simulator's sharded scenario.
- :class:`ShardedReplicaSet` — N data-parallel :class:`JaxEngine`
  replicas, each pjit-sharded over its own submesh, each attached to the
  control plane as its OWN worker (own ``DistributedRuntime`` → own
  lease → own instance id, exactly like a separate worker process) with
  its own KV-event publisher — so the real HTTP frontend + KV router see
  N workers of one component and overlap-route between them.

Reference: SURVEY §2.4's parallelism inventory (vLLM
``--tensor-parallel-size`` + Ray bootstrap; SGLang per-rank
subprocesses) made real behind the frontend. On TPU one replica = one
SPMD program over its submesh; GSPMD inserts the collectives.

This module imports jax lazily: the pure partitioning pieces are used by
the (jax-free) fleet simulator.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..runtime.config import env_int, env_str

log = logging.getLogger("dynamo_tpu.parallel.serving")

MESH_AXES = ("data", "model", "expert", "seq", "stage")


def parse_mesh_shape(spec: Optional[str]) -> Dict[str, int]:
    """``"data=2,model=4"`` → ``{"data": 2, "model": 4}``. Empty/None →
    ``{}`` (single-device). Unknown axes and non-positive sizes raise —
    a typo'd DYN_MESH_SHAPE must fail loudly, not serve unsharded."""
    axes: Dict[str, int] = {}
    if not spec:
        return axes
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"mesh shape entry {part!r} is not axis=N "
                f"(axes: {', '.join(MESH_AXES)})")
        name, _, val = part.partition("=")
        name = name.strip()
        if name not in MESH_AXES:
            raise ValueError(f"unknown mesh axis {name!r} "
                             f"(axes: {', '.join(MESH_AXES)})")
        n = int(val)
        if n < 1:
            raise ValueError(f"mesh axis {name}={n} must be >= 1")
        axes[name] = n
    return axes


def mesh_shape_str(axes: Dict[str, int]) -> str:
    """Canonical wire/report form: ``"data=2,model=4"`` (axis order fixed,
    size-1 axes elided); ``"single"`` for the unsharded case."""
    parts = [f"{a}={axes[a]}" for a in MESH_AXES if axes.get(a, 1) > 1]
    return ",".join(parts) if parts else "single"


def devices_per_replica(axes: Dict[str, int]) -> int:
    n = 1
    for a in MESH_AXES:
        n *= axes.get(a, 1)
    return n


class NoFreeDevices(RuntimeError):
    """The pool cannot satisfy a submesh acquisition."""


class DevicePool:
    """Deterministic submesh assignment over an ordered device list.

    Acquisition hands out the ``n`` lowest-index free devices (contiguous
    groups when the pool is unfragmented — neighbouring devices share the
    fastest ICI links); release returns a replica's devices to the free
    set, so a later join re-partitions onto them. Devices are opaque
    objects (real ``jax.Device``s, or plain ints in the fleet sim)."""

    def __init__(self, devices: Sequence):
        self.devices = list(devices)
        self.assigned: Dict[str, List] = {}

    @property
    def free(self) -> List:
        taken = {id(d) for devs in self.assigned.values() for d in devs}
        return [d for d in self.devices if id(d) not in taken]

    def acquire(self, name: str, n: int) -> List:
        if name in self.assigned:
            raise ValueError(f"replica {name!r} already holds devices")
        free = self.free
        if len(free) < n:
            raise NoFreeDevices(
                f"replica {name!r} needs {n} devices; only {len(free)} of "
                f"{len(self.devices)} free")
        devs = free[:n]
        self.assigned[name] = devs
        return devs

    def release(self, name: str) -> List:
        return self.assigned.pop(name, [])

    def assignment(self) -> Dict[str, List[int]]:
        """Per-replica device INDEX lists (stable, report-friendly)."""
        index = {id(d): i for i, d in enumerate(self.devices)}
        return {name: [index[id(d)] for d in devs]
                for name, devs in sorted(self.assigned.items())}


@dataclass
class ReplicaSpec:
    """One planned replica: name, its devices, the per-replica mesh."""

    index: int
    name: str
    devices: List
    mesh_axes: Dict[str, int] = field(default_factory=dict)

    @property
    def mesh_shape(self) -> str:
        return mesh_shape_str(self.mesh_axes)


def plan_replicas(mesh_axes: Dict[str, int], replicas: int,
                  devices: Sequence) -> List[ReplicaSpec]:
    """Partition ``devices`` into ``replicas`` submeshes of
    ``devices_per_replica(mesh_axes)`` each (lowest-index-first)."""
    per = devices_per_replica(mesh_axes)
    pool = DevicePool(devices)
    return [ReplicaSpec(index=i, name=f"r{i}",
                        devices=pool.acquire(f"r{i}", per),
                        mesh_axes=dict(mesh_axes))
            for i in range(replicas)]


def apply_forced_host_devices() -> Optional[int]:
    """CPU bring-up: honor ``DYN_FORCE_HOST_DEVICES=N`` by appending
    ``--xla_force_host_platform_device_count=N`` to ``XLA_FLAGS``.

    MUST run before the jax backend initializes (the flag is read once at
    backend init — setting it later is silently ignored, which is why the
    tier-1 sharded tests run in a subprocess). Returns N when applied."""
    import os

    n = env_int("DYN_FORCE_HOST_DEVICES")
    if not n or n <= 1:
        return None
    flags = env_str("XLA_FLAGS") or ""
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip())
    return n


def build_replica_engine(model_cfg, engine_cfg, spec: ReplicaSpec, *,
                         params=None, seed: int = 0, quant=None,
                         warmup: bool = True):
    """Build (and warm) one replica's :class:`JaxEngine` on its submesh.

    ``params=None`` + a shared ``seed`` gives every replica an identical
    host-side init (the data-parallel contract: same weights, disjoint
    devices); a provided host params tree is device_put onto the submesh
    by the engine's ``shard_params``. Blocking (XLA compiles) — callers
    on an event loop run this in a thread."""
    from ..engine.jax_engine import JaxEngine
    from .mesh import MeshSpec

    mesh = None
    if devices_per_replica(spec.mesh_axes) > 1:
        mesh = MeshSpec(**spec.mesh_axes).build(spec.devices)
    engine = JaxEngine(model_cfg, engine_cfg, params=params, seed=seed,
                       mesh=mesh, quant=quant, worker_label=spec.name)
    if warmup:
        engine.warmup()
    return engine


class ShardedReplica:
    """One live replica: engine + its own runtime attachment + endpoint +
    KV-event publisher. The per-replica ``DistributedRuntime`` is what
    gives each replica its own lease → instance id → stats subject, so
    N replicas in one process look exactly like N worker processes to
    the router, the metrics aggregator and discovery."""

    def __init__(self, spec: ReplicaSpec, engine, namespace: str,
                 component: str, mdc):
        self.spec = spec
        self.name = spec.name
        self.engine = engine
        self.namespace = namespace
        self.component = component
        self.mdc = mdc
        self.drt = None
        self._handle = None
        self._publisher = None

    @property
    def instance_id(self) -> int:
        return self.drt.instance_id if self.drt else 0

    async def start(self, dcp_address: str) -> None:
        from ..llm.worker import serve_token_model
        from ..runtime.runtime import DistributedRuntime

        self.drt = await DistributedRuntime.attach(dcp_address)
        self._handle, self._publisher = await serve_token_model(
            self.drt, self.mdc, self.engine, namespace=self.namespace,
            component=self.component)
        log.info("replica %s serving as instance %x on %d device(s) "
                 "(mesh %s)", self.name, self.instance_id,
                 len(self.spec.devices), self.spec.mesh_shape)

    async def drain(self) -> None:
        """Withdraw from discovery and cancel in-flight streams
        (ServeHandle.stop kills their contexts; the processor's
        round-robin fallback re-routes the callers). Claim-before-await
        so concurrent drain/stop never double-stops."""
        handle, self._handle = self._handle, None
        if handle is not None:
            await handle.stop()

    async def drain_graceful(self, timeout_s=None) -> bool:
        """dynarevive graceful drain: discovery withdrawn first, then
        in-flight sequences finish (bounded by DYN_DRAIN_TIMEOUT_MS /
        ``timeout_s``), KV events flush, and only then does the handle
        stop. Returns True when everything finished inside the budget."""
        from ..runtime import revive

        handle, self._handle = self._handle, None
        if handle is None:
            return True
        return await revive.drain_worker(
            handle, engine=self.engine, publisher=self._publisher,
            timeout_s=timeout_s)

    async def stop(self) -> None:
        # lifecycle drain (discovery withdrawal), not a socket drain
        await self.drain()  # dynalint: disable=unbounded-await
        publisher, self._publisher = self._publisher, None
        if publisher is not None:
            await publisher.stop()
        if self.engine is not None:
            await self.engine.stop()
        drt, self.drt = self.drt, None
        if drt is not None:
            await drt.shutdown()


class ShardedReplicaSet:
    """N data-parallel sharded engine replicas behind one component.

    Each replica: a :class:`JaxEngine` pjit-sharded over its own submesh
    of the local device set, attached to the control plane as its own
    worker instance serving ``generate_tokens``, with its own KV-event
    publisher feeding the router's radix index. ``scale_to`` joins and
    drains replicas at runtime, re-partitioning the submesh assignment
    through the shared :class:`DevicePool` (drained replicas' devices are
    what the next join builds on)."""

    def __init__(self, model_cfg, engine_cfg, *,
                 mesh_axes: Optional[Dict[str, int]] = None,
                 replicas: Optional[int] = None,
                 namespace: str = "dynamo", component: str = "sharded",
                 mdc=None, dcp_address: Optional[str] = None,
                 params=None, seed: int = 0, quant=None,
                 warmup: bool = True):
        if mesh_axes is None:
            mesh_axes = parse_mesh_shape(env_str("DYN_MESH_SHAPE"))
        if replicas is None:
            replicas = env_int("DYN_DP_REPLICAS") or 1
        if replicas < 1:
            raise ValueError(f"replicas ({replicas}) must be >= 1")
        self.model_cfg = model_cfg
        self.engine_cfg = engine_cfg
        self.mesh_axes = dict(mesh_axes)
        self.initial_replicas = replicas
        self.namespace = namespace
        self.component = component
        self.mdc = mdc
        self.dcp_address = dcp_address
        self.params = params
        self.seed = seed
        self.quant = quant
        self.warmup = warmup
        self.pool: Optional[DevicePool] = None
        self.replicas: List[ShardedReplica] = []
        self._spawned = 0
        self._anchor = None  # embedded DCP server owner when no address

    @property
    def mesh_shape(self) -> str:
        return mesh_shape_str(self.mesh_axes)

    @property
    def per_replica_devices(self) -> int:
        return devices_per_replica(self.mesh_axes)

    async def start(self) -> None:
        import jax

        if self.mdc is None:
            from ..llm.model_card import ModelDeploymentCard

            self.mdc = ModelDeploymentCard(
                name="sharded", tokenizer_kind="byte",
                kv_block_size=self.engine_cfg.page_size,
                model_type="completions")
        if self.dcp_address is None:
            # single-process bring-up: embed a DCP server; every replica
            # still attaches separately (own lease each)
            from ..runtime.runtime import DistributedRuntime

            anchor = await DistributedRuntime.detached()
            if self.dcp_address is None:  # re-check: concurrent start()
                self._anchor = anchor
                self.dcp_address = anchor.dcp.address
            else:
                await anchor.shutdown()
        self.pool = DevicePool(jax.devices())
        per = self.per_replica_devices
        need = per * self.initial_replicas
        if len(self.pool.devices) < need:
            raise NoFreeDevices(
                f"{self.initial_replicas} replicas x {per} devices "
                f"(mesh {self.mesh_shape}) need {need} devices, have "
                f"{len(self.pool.devices)} (CPU: set "
                f"DYN_FORCE_HOST_DEVICES before jax initializes)")
        for _ in range(self.initial_replicas):
            await self._join()

    async def _join(self) -> ShardedReplica:
        name = f"r{self._spawned}"
        self._spawned += 1
        spec = ReplicaSpec(
            index=self._spawned - 1, name=name,
            devices=self.pool.acquire(name, self.per_replica_devices),
            mesh_axes=dict(self.mesh_axes))
        # the compile fence is process-global (engine/jit_fence.py): the
        # joining replica's warmup compiles would count against every
        # LIVE replica's armed fence. A join is an intentional, visible
        # compile phase — mask the siblings' fences for its duration so
        # per-replica post_warmup_compiles keeps meaning "THIS replica's
        # serving path compiled mid-flight".
        live_fences = [r.engine.fence for r in self.replicas]
        for fence in live_fences:
            fence.disarm()
        try:
            # build + warmup are blocking XLA work; keep the loop serving
            engine = await asyncio.to_thread(
                build_replica_engine, self.model_cfg, self.engine_cfg,
                spec, params=self.params, seed=self.seed, quant=self.quant,
                warmup=self.warmup)
        except BaseException:
            self.pool.release(name)
            raise
        finally:
            for fence in live_fences:
                fence.arm()
        replica = ShardedReplica(spec, engine, self.namespace,
                                 self.component, self.mdc)
        await replica.start(self.dcp_address)
        self.replicas.append(replica)
        return replica

    async def scale_to(self, n: int) -> Dict[str, List[str]]:
        """Converge to ``n`` live replicas: joins build fresh engines on
        free (possibly previously-released) devices; drains retire the
        newest replicas first and return their submeshes to the pool.
        Returns {"joined": [...], "drained": [...]} replica names."""
        if n < 0:
            raise ValueError("scale_to needs n >= 0")
        joined: List[str] = []
        drained: List[str] = []
        while len(self.replicas) > n:
            replica = self.replicas.pop()  # newest-first
            await replica.stop()
            self.pool.release(replica.name)
            drained.append(replica.name)
        while len(self.replicas) < n:
            joined.append((await self._join()).name)
        return {"joined": joined, "drained": drained}

    async def flush_kv_events(self) -> None:
        """Push every replica's pending stored-block events onto the bus
        NOW (the publishers run on an interval) — wave-boundary settling
        for benches/tests that need the router's index current before the
        next wave routes."""
        for replica in self.replicas:
            if replica._publisher is not None:
                await replica._publisher.flush()

    # ------------------------------------------------------ observability

    def assignment(self) -> Dict[str, List[int]]:
        return self.pool.assignment() if self.pool else {}

    def stats_by_replica(self) -> Dict[str, dict]:
        return {r.name: r.engine.stats() for r in self.replicas}

    def post_warmup_compiles(self) -> Dict[str, int]:
        return {r.name: r.engine.fence.post_warmup_compiles
                for r in self.replicas}

    def device_time_fractions(self) -> Dict[str, float]:
        return {r.name: round(r.engine.profiler.device_time_fraction(), 4)
                for r in self.replicas}

    def describe(self) -> dict:
        """Report block: mesh shape, the live submesh assignment, and the
        per-replica instance ids (the KV router's worker ids)."""
        return {
            "mesh_shape": self.mesh_shape,
            "devices_per_replica": self.per_replica_devices,
            "replicas": len(self.replicas),
            "assignment": self.assignment(),
            "instances": {r.name: f"{r.instance_id:x}"
                          for r in self.replicas},
        }

    async def drain(self, timeout_s=None) -> bool:
        """dynarevive graceful shutdown (the SIGTERM path): every replica
        withdraws from discovery, finishes in-flight sequences bounded by
        DYN_DRAIN_TIMEOUT_MS, flushes KV events, then the set stops and
        leases release. Returns True when every replica drained clean."""
        results = []
        for replica in self.replicas:
            # lifecycle drain (state machine in runtime/revive.py), not
            # a socket drain
            results.append(  # dynalint: disable=unbounded-await
                await replica.drain_graceful(timeout_s))
        await self.stop()
        return all(results)

    async def stop(self) -> None:
        while self.replicas:
            replica = self.replicas.pop()
            try:
                await replica.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                log.exception("replica %s stop failed", replica.name)
            if self.pool is not None:
                self.pool.release(replica.name)
        anchor, self._anchor = self._anchor, None
        if anchor is not None:
            await anchor.shutdown()
