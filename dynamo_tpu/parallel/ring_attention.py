"""Ring attention: sequence/context-parallel attention over an ICI ring.

The reference has NO long-context story beyond KV reuse and disaggregating
long prefills (SURVEY §5: "long-context / sequence parallelism: absent in
the reference"); this module adds it as a first-class sharding strategy of
the JAX prefill program, per the SURVEY's TPU plan.

Design (blockwise/ring attention, Liu et al. style, TPU-idiomatic):

- the sequence axis of Q/K/V activations is sharded over the mesh axis
  ``seq``; each device holds a contiguous chunk;
- K/V chunks rotate around the ring with ``lax.ppermute`` while each device
  accumulates its queries' attention over every chunk using an online
  (streaming) softmax — numerically identical to full softmax attention;
- causality is enforced with absolute positions, so the same kernel serves
  packed/padded and chunk-offset layouts (padding rows carry position -1);
- the loop is a ``lax.scan`` of ``seq`` steps: one K/V block dot per step
  on the MXU while the next block is in flight on ICI (XLA overlaps the
  ppermute with compute since the carry has no data dependence on it until
  the next step).

``make_long_prefill_fn`` builds the full sequence-parallel prefill program:
the Llama/Mixtral stack with activations sharded over ("data", "seq") and
self-attention replaced by the ring kernel — producing per-layer K/V for
the whole prompt (to be scattered into the paged pool / shipped to decode)
plus last-position logits.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..models.config import ModelConfig

NEG_INF = -1e30


# ------------------------------------------------------------- ring kernel


def _ring_attention_inner(q, k, v, q_pos, kv_pos, is_sliding, *,
                          axis_name: str, scale: float,
                          softcap=None, window=None):
    """Per-device body (runs under shard_map over ``axis_name``).

    q: [B, Tq, KV, G, hd] local query chunk (grouped GQA heads);
    k: [B, Tk, KV, hd]; v: [B, Tk, KV, dv] local key/value chunks —
    dv may differ from hd (MLA rides this kernel with keys
    [c_kv | k_rope] of width r+dr and values c_kv of width r);
    q_pos/kv_pos: [B, T] absolute positions (-1 = padding);
    is_sliding: traced scalar bool (Gemma-2 layer parity under scan).
    ``softcap``/``window`` are the static Gemma-2 knobs: tanh softcap
    applied BEFORE masking (models/llama._softcap_mask), and the
    sliding window as a pure POSITION predicate (j > t - window) — it
    needs no block locality, so any window size composes with any ring
    chunking; blocks wholly outside a query's window just contribute
    zero mass to its online softmax.
    Returns [B, Tq, KV, G, dv].
    """
    from ..models.llama import _softcap_mask, _visible

    n = lax.psum(1, axis_name)
    B, Tq, KV, G, hd = q.shape
    dv = v.shape[-1]
    qf = q.astype(jnp.float32)

    m0 = jnp.full((B, KV, G, Tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, KV, G, Tq), jnp.float32)
    acc0 = jnp.zeros((B, KV, G, Tq, dv), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        k_blk, v_blk, pos_blk, m, l, acc = carry
        scores = jnp.einsum("btkgh,bskh->bkgts", qf,
                            k_blk.astype(jnp.float32)) * scale
        kvp = pos_blk[:, None, None, None, :]
        qp = q_pos[:, None, None, :, None]
        # same helpers as the paged path — ONE copy of the Gemma-2
        # softcap-before-mask ordering and window-visibility invariants
        valid = (kvp >= 0) & _visible(kvp, qp, window, is_sliding)
        scores = _softcap_mask(scores, valid, softcap)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # exp only where valid: when a row has no valid keys yet, m_new is
        # still NEG_INF and exp(scores - m_new) would be exp(0)=1 — mask it
        p = jnp.where(valid, jnp.exp(scores - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bkgts,bskh->bkgth", p, v_blk.astype(jnp.float32))
        k_blk, v_blk, pos_blk = (
            lax.ppermute(k_blk, axis_name, perm),
            lax.ppermute(v_blk, axis_name, perm),
            lax.ppermute(pos_blk, axis_name, perm))
        return (k_blk, v_blk, pos_blk, m_new, l, acc), None

    (_, _, _, _, l, acc), _ = lax.scan(
        step, (k, v, kv_pos, m0, l0, acc0), None, length=n)
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, KV, G, Tq, hd]
    return out.transpose(0, 3, 1, 2, 4).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   positions: jax.Array, mesh: Mesh, *,
                   scale: float, seq_axis: str = "seq",
                   softcap=None, window=None,
                   is_sliding=False) -> jax.Array:
    """Causal GQA attention with the sequence sharded over ``seq_axis``.

    q: [B, T, H, hd]; k/v: [B, T, KV, hd]; positions: [B, T] absolute
    (-1 for padding). All sequence-sharded over ``seq_axis``; heads may be
    additionally sharded over "model" (the kernel is per-head, so TP
    composes freely). ``softcap``/``window``/``is_sliding`` are the
    Gemma-2 semantics (see _ring_attention_inner). Returns [B, T, H, hd]
    with q's sharding.
    """
    B, T, H, hd = q.shape
    KV = k.shape[2]
    qg = q.reshape(B, T, KV, H // KV, hd)

    # TP shards KV heads over "model" (consistent with mesh.kv_cache_pspec);
    # each TP rank runs the ring over its own head slice
    qspec = P("data", seq_axis, "model", None, None)
    kvspec = P("data", seq_axis, "model", None)
    pspec = P("data", seq_axis)

    inner = partial(_ring_attention_inner, axis_name=seq_axis, scale=scale,
                    softcap=softcap, window=window)
    out = shard_map(
        inner, mesh=mesh,
        in_specs=(qspec, kvspec, kvspec, pspec, pspec, P()),
        out_specs=qspec, check_vma=False,
    )(qg, k, v, positions, positions, jnp.asarray(is_sliding))
    return out.reshape(B, T, H, hd)


def ring_attention_mqa(q: jax.Array, k: jax.Array, v: jax.Array,
                       positions: jax.Array, mesh: Mesh, *,
                       scale: float, seq_axis: str = "seq") -> jax.Array:
    """Ring attention with ONE shared key/value stream (MQA form) — the
    MLA latent exchange: every query head attends to the same compressed
    stream, so only [B, T, dk] keys + [B, T, dv] values rotate on ICI
    (~an order of magnitude less ring traffic than per-head GQA K/V).

    q: [B, T, H, dk]; k: [B, T, dk]; v: [B, T, dv]; positions [B, T]
    absolute (-1 padding). Query heads shard over "model" (scores are
    per-head); the shared stream replicates across TP shards — it has no
    head axis to split. Returns [B, T, H, dv].
    """
    B, T, H, dk = q.shape
    qg = q.reshape(B, T, 1, H, dk)  # KV=1, G=H

    qspec = P("data", seq_axis, None, "model", None)
    kvspec = P("data", seq_axis, None, None)
    pspec = P("data", seq_axis)

    inner = partial(_ring_attention_inner, axis_name=seq_axis, scale=scale)
    out = shard_map(
        inner, mesh=mesh,
        in_specs=(qspec, kvspec, kvspec, pspec, pspec, P()),
        out_specs=qspec, check_vma=False,
    )(qg, k[:, :, None], v[:, :, None], positions, positions,
      jnp.asarray(False))
    return out.reshape(B, T, H, -1)


# -------------------------------------------- sequence-parallel prefill fn


def make_long_prefill_fn(cfg: ModelConfig, mesh: Mesh, *,
                         seq_axis: str = "seq"):
    """Jitted long-context prefill: the model stack with activations
    sharded over ("data", seq) and ring attention.

    Returns ``fn(params, tokens, positions) -> (logits [B, V], k_all, v_all)``
    where k_all/v_all are [L, B, T, KV, hd] (per-layer KV for the whole
    prompt — scatter into the paged pool with
    :func:`scatter_prefill_kv`, or ship to the decode mesh via the disagg
    transfer plane). ``positions`` are absolute; -1 marks padding.
    """
    from ..models.llama import (_act, _layer_keys, _mlp, _moe_mlp,
                                _qk_headnorm, _residual_add, _sliding_flag,
                                apply_rope, embed_tokens, project_logits,
                                rms_norm, rope_freqs)

    inv_freq = rope_freqs(cfg)
    scale = cfg.attn_scale
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_

    act_spec = NamedSharding(mesh, P("data", seq_axis, None))

    @jax.jit
    def long_prefill(params, tokens, positions):
        B, T = tokens.shape
        h = embed_tokens(params, cfg, tokens)
        h = lax.with_sharding_constraint(h, act_spec)
        safe_pos = jnp.maximum(positions, 0)

        layer_params = {kk: params[kk] for kk in _layer_keys(cfg)}

        def layer(h, xs):
            lp, l_idx = xs
            x = rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.norm_unit_offset)
            xq, xk, xv = x @ lp["wq"], x @ lp["wk"], x @ lp["wv"]
            if cfg.attn_bias:  # Qwen2-style qkv bias (matches llama.forward)
                xq, xk, xv = xq + lp["bq"], xk + lp["bk"], xv + lp["bv"]
            q, k = _qk_headnorm(xq.reshape(B, T, H, hd),
                                xk.reshape(B, T, KV, hd), lp, cfg)
            q = apply_rope(q, safe_pos, inv_freq)
            k = apply_rope(k, safe_pos, inv_freq)
            v = xv.reshape(B, T, KV, hd)
            attn = ring_attention(q, k, v, positions, mesh, scale=scale,
                                  seq_axis=seq_axis,
                                  softcap=cfg.attn_logit_softcap,
                                  window=cfg.sliding_window,
                                  is_sliding=_sliding_flag(cfg, l_idx))
            h = _residual_add(h, attn.reshape(B, T, H * hd) @ lp["wo"],
                              lp, "ln_attn_post", cfg)
            x = rms_norm(h, lp["ln_mlp"], cfg.rms_norm_eps, cfg.norm_unit_offset)
            if cfg.num_experts > 0:
                mlp_out = _moe_mlp(x, lp["w_router"], lp["w_gate"],
                                   lp["w_up"], lp["w_down"],
                                   cfg.num_experts_per_tok, mesh=mesh)
            else:
                mlp_out = _mlp(x, lp["w_gate"], lp["w_up"], lp["w_down"],
                               _act(cfg))
            h = _residual_add(h, mlp_out, lp, "ln_mlp_post", cfg)
            h = lax.with_sharding_constraint(h, act_spec)
            return h, (k, v)

        h, (k_all, v_all) = lax.scan(
            layer, h, (layer_params, jnp.arange(cfg.num_layers)))
        h = rms_norm(h, params["ln_final"], cfg.rms_norm_eps, cfg.norm_unit_offset)
        # logits at the true last token of each row (max position)
        last_idx = jnp.argmax(positions, axis=1)
        h_last = h[jnp.arange(B), last_idx]
        return project_logits(params, cfg, h_last), k_all, v_all

    return long_prefill


def make_mla_long_prefill_fn(cfg: ModelConfig, mesh: Mesh, *,
                             seq_axis: str = "seq"):
    """Sequence-parallel long prefill for the MLA family
    (models/mla.py): the latent-only ring exchange. Only the shared
    compressed stream (c_kv [B, T, r] + k_rope [B, T, dr]) rotates on
    the ring — per-head K/V are never materialized, matching the
    absorbed decode form.

    Same contract as :func:`make_long_prefill_fn`: ``fn(params, tokens,
    positions) -> (logits [B, V], c_all, r_all)`` with c_all/r_all
    [L, B, T, 1, r|dr] — KV-head axis fixed at 1 exactly like the MLA
    paged pools (mla.cache_shapes), so the engine's generic
    :func:`scatter_prefill_kv` commits them unchanged.
    """
    import math

    from ..models.llama import apply_rope, rms_norm, rope_freqs
    from ..models.mla import _mla_layer_keys

    if cfg.num_experts > 0:
        raise ValueError(
            "MLA ring long-prefill covers dense MLA only; the DeepSeek-"
            "MoE segmented stack is not wired through the ring — unset "
            "long_prefill_threshold")
    from ..models.llama import _mlp, _moe_mlp, project_logits

    inv_freq = rope_freqs(cfg, dim=cfg.qk_rope_head_dim)
    H = cfg.num_heads
    r, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
    scale = 1.0 / math.sqrt(dn + dr)

    act_spec = NamedSharding(mesh, P("data", seq_axis, None))

    @jax.jit
    def long_prefill(params, tokens, positions):
        B, T = tokens.shape
        h = params["embed"][tokens]
        h = lax.with_sharding_constraint(h, act_spec)
        safe_pos = jnp.maximum(positions, 0)
        layer_params = {k: params[k] for k in _mla_layer_keys(cfg)}

        def layer(h, lp):
            x = rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps)
            if cfg.q_lora_rank > 0:
                q_all = rms_norm(x @ lp["w_dq"], lp["q_norm"],
                                 cfg.rms_norm_eps) @ lp["w_uq"]
            else:
                q_all = x @ lp["w_q"]
            q_all = q_all.reshape(B, T, H, dn + dr)
            q_nope, q_rope = q_all[..., :dn], q_all[..., dn:]
            q_rope = apply_rope(q_rope, safe_pos, inv_freq)
            ckr = x @ lp["w_dkv"]
            c_kv = rms_norm(ckr[..., :r], lp["kv_norm"], cfg.rms_norm_eps)
            k_rope = apply_rope(ckr[..., None, r:], safe_pos,
                                inv_freq)[..., 0, :]
            # absorbed queries + concatenated shared stream: scores =
            # q_lat·c + q_rope·k_rope in ONE MQA ring pass
            w_uk = lp["w_uk"].reshape(r, H, dn)
            q_lat = jnp.einsum("bthd,rhd->bthr",
                               q_nope.astype(jnp.float32),
                               w_uk.astype(jnp.float32))
            q_cat = jnp.concatenate(
                [q_lat, q_rope.astype(jnp.float32)], axis=-1)
            k_cat = jnp.concatenate(
                [c_kv.astype(jnp.float32),
                 k_rope.astype(jnp.float32)], axis=-1)
            out_lat = ring_attention_mqa(
                q_cat, k_cat, c_kv.astype(jnp.float32), positions, mesh,
                scale=scale, seq_axis=seq_axis)  # [B, T, H, r]
            w_uv = lp["w_uv"].reshape(r, H, dv)
            out = jnp.einsum("bthr,rhd->bthd", out_lat,
                             w_uv.astype(jnp.float32))
            h = h + out.reshape(B, T, H * dv).astype(h.dtype) @ lp["w_o"]
            x = rms_norm(h, lp["ln_mlp"], cfg.rms_norm_eps)
            if cfg.num_experts > 0:
                h = h + _moe_mlp(x, lp["w_router"], lp["w_gate"],
                                 lp["w_up"], lp["w_down"],
                                 cfg.num_experts_per_tok, mesh=mesh)
            else:
                h = h + _mlp(x, lp["w_gate"], lp["w_up"], lp["w_down"])
            h = lax.with_sharding_constraint(h, act_spec)
            return h, (c_kv.astype(h.dtype), k_rope.astype(h.dtype))

        h, (c_all, r_all) = lax.scan(layer, h, layer_params)
        h = rms_norm(h, params["ln_final"], cfg.rms_norm_eps)
        last_idx = jnp.argmax(positions, axis=1)
        h_last = h[jnp.arange(B), last_idx]
        # KV-head axis = 1, matching the MLA paged pools
        return (project_logits(params, cfg, h_last),
                c_all[:, :, :, None], r_all[:, :, :, None])

    return long_prefill


@partial(jax.jit, donate_argnums=(0, 1))
def scatter_prefill_kv(kv_k: jax.Array, kv_v: jax.Array, k_all: jax.Array,
                       v_all: jax.Array, flat_slots: jax.Array
                       ) -> Tuple[jax.Array, jax.Array]:
    """Write long-prefill K/V ([L, B, T, KV, hd]) into the paged pools
    ([L, pages, KV, ps, hd]) at ``flat_slots`` [B, T] (page*ps + offset;
    out-of-range = drop). The pools are DONATED — like every other pool
    update in the engine, XLA scatters in place instead of materializing
    a second full-pool copy (which would double peak KV memory on pools
    sized to fill HBM)."""
    from ..models.llama import _scatter_pages

    def per_layer(cache_layer, new):
        return _scatter_pages(cache_layer, new, flat_slots)

    return (jax.vmap(per_layer)(kv_k, k_all),
            jax.vmap(per_layer)(kv_v, v_all))
