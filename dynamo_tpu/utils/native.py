"""Loader for the native C++ runtime library (native/*.cpp).

Builds ``native/build/libdynamo_native.so`` on first use (g++, cached by
a sha256 over the sources — mtimes are meaningless after a fresh clone)
and exposes it via ctypes. Every consumer has a pure-Python fallback, so
a missing toolchain degrades gracefully (reference layering: the Rust/C
bits are performance substrate, not features).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional

from ..runtime.config import env_flag

log = logging.getLogger("dynamo_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libdynamo_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


_STAMP_PATH = _LIB_PATH + ".srchash"


def _src_hash() -> str:
    h = hashlib.sha256()
    for f in sorted(os.listdir(_NATIVE_DIR)):
        if f.endswith((".cpp", ".h")) or f == "Makefile":
            h.update(f.encode())
            with open(os.path.join(_NATIVE_DIR, f), "rb") as fh:
                h.update(fh.read())
    return h.hexdigest()


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    try:
        with open(_STAMP_PATH) as fh:
            return fh.read().strip() != _src_hash()
    except OSError:
        return True  # no stamp → binary of unknown provenance: rebuild


def _declare(lib: ctypes.CDLL) -> None:
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.dyn_radix_create.restype = ctypes.c_void_p
    lib.dyn_radix_destroy.argtypes = [ctypes.c_void_p]
    lib.dyn_radix_apply_stored.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        u64p, ctypes.c_size_t]
    lib.dyn_radix_apply_removed.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u64p, ctypes.c_size_t]
    lib.dyn_radix_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.dyn_radix_find_matches.restype = ctypes.c_size_t
    lib.dyn_radix_find_matches.argtypes = [
        ctypes.c_void_p, u64p, ctypes.c_size_t, u64p, u32p, ctypes.c_size_t]
    lib.dyn_radix_block_count.restype = ctypes.c_size_t
    lib.dyn_radix_block_count.argtypes = [ctypes.c_void_p]
    lib.dynamo_llm_init.restype = ctypes.c_int32
    lib.dynamo_llm_init.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_int64, ctypes.c_uint32]
    lib.dynamo_kv_event_publish_stored.restype = ctypes.c_int32
    lib.dynamo_kv_event_publish_stored.argtypes = [
        ctypes.c_uint64, u32p, ctypes.POINTER(ctypes.c_size_t), u64p,
        ctypes.c_size_t, u64p, ctypes.c_uint64]
    lib.dynamo_kv_event_publish_removed.restype = ctypes.c_int32
    lib.dynamo_kv_event_publish_removed.argtypes = [
        ctypes.c_uint64, u64p, ctypes.c_size_t]
    lib.dynamo_kv_events_drain.restype = ctypes.c_size_t
    lib.dynamo_kv_events_drain.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if needed; None when unavailable
    (no compiler / build failure / DYN_DISABLE_NATIVE=1)."""
    global _lib, _tried
    if env_flag("DYN_DISABLE_NATIVE"):
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _needs_build():
                log.info("building native library in %s", _NATIVE_DIR)
                # -B: make's own mtime comparison is exactly what the hash
                # stamp exists to replace — force the recompile
                # one-time toolchain build: serializing concurrent first
                # callers behind the lock is the point, and the loader
                # only ever runs from sync init paths, never on a loop
                subprocess.run(["make", "-B", "-C", _NATIVE_DIR],  # dynalint: disable=lock-across-blocking
                               check=True, capture_output=True, timeout=120)
                with open(_STAMP_PATH, "w") as fh:  # dynalint: disable=lock-across-blocking
                    fh.write(_src_hash())
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except Exception as e:  # noqa: BLE001 — fall back to pure Python
            log.warning("native library unavailable (%s); using Python "
                        "fallbacks", e)
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None
