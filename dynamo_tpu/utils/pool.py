"""RAII object pool (reference lib/runtime/src/utils/pool.rs:23-241:
``Pool<T: Returnable>`` whose ``PoolItem`` returns to the pool on Drop;
the backbone of the reference's KV block reuse pool).

asyncio re-design: ``acquire()`` awaits a free object; the returned
``PoolItem`` is a context manager (sync or async) that returns the object
on exit; ``SharedPoolItem`` keeps it out until the last clone drops."""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")


class Pool(Generic[T]):
    def __init__(self, items: Optional[List[T]] = None,
                 factory: Optional[Callable[[], T]] = None,
                 max_size: Optional[int] = None):
        self._free: asyncio.Queue = asyncio.Queue()
        self._factory = factory
        self._created = 0
        self._max = max_size
        for it in items or []:
            self._free.put_nowait(it)
            self._created += 1

    @property
    def available(self) -> int:
        return self._free.qsize()

    @property
    def size(self) -> int:
        return self._created

    async def acquire(self) -> "PoolItem[T]":
        """Awaits a free object; grows via the factory up to max_size."""
        if (self._free.empty() and self._factory is not None
                and (self._max is None or self._created < self._max)):
            self._created += 1
            return PoolItem(self, self._factory())
        return PoolItem(self, await self._free.get())

    def try_acquire(self) -> Optional["PoolItem[T]"]:
        try:
            return PoolItem(self, self._free.get_nowait())
        except asyncio.QueueEmpty:
            if self._factory is not None and (
                    self._max is None or self._created < self._max):
                self._created += 1
                return PoolItem(self, self._factory())
            return None

    def _return(self, obj: T) -> None:
        self._free.put_nowait(obj)


class PoolItem(Generic[T]):
    """Holds one pooled object; returns it on release/context exit
    (the Drop-returns-to-pool semantics of the reference)."""

    def __init__(self, pool: Pool[T], value: T):
        self._pool: Optional[Pool[T]] = pool
        self.value = value

    def release(self) -> None:
        if self._pool is not None:
            pool, self._pool = self._pool, None
            pool._return(self.value)

    def share(self) -> "SharedPoolItem[T]":
        item = SharedPoolItem(self._pool, self.value)
        self._pool = None  # ownership moved
        return item

    def __enter__(self) -> T:
        return self.value

    def __exit__(self, *exc) -> None:
        self.release()

    async def __aenter__(self) -> T:
        return self.value

    async def __aexit__(self, *exc) -> None:
        self.release()


class SharedPoolItem(Generic[T]):
    """Clone-counted pool item: returns to the pool when the last clone
    is released (reference SharedPoolItem)."""

    def __init__(self, pool: Optional[Pool[T]], value: T):
        self._pool = pool
        self.value = value
        self._refs = [1]  # shared cell across clones

    def clone(self) -> "SharedPoolItem[T]":
        other = SharedPoolItem.__new__(SharedPoolItem)
        other._pool = self._pool
        other.value = self.value
        other._refs = self._refs
        self._refs[0] += 1
        return other

    def release(self) -> None:
        if self._refs[0] <= 0:
            return
        self._refs[0] -= 1
        if self._refs[0] == 0 and self._pool is not None:
            self._pool._return(self.value)
