"""Protocol types: internal (engine-facing) + OpenAI API surface."""
