"""Internal (engine-facing) request/response model.

Reference lib/llm/src/protocols/common.rs:43-633 (StopConditions,
SamplingOptions, OutputOptions) and protocols/common/llm_backend.rs
(BackendInput/BackendOutput/LLMEngineOutput): the preprocessor lowers an
OpenAI request into these token-level types; engines speak only these.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class StopConditions:
    """When to stop generating (reference common.rs StopConditions)."""

    max_tokens: Optional[int] = None
    stop: Optional[List[str]] = None            # stop strings (detok'd match)
    stop_token_ids: Optional[List[int]] = None  # exact token matches
    min_tokens: Optional[int] = None
    ignore_eos: bool = False

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if v not in (None, False)}

    @classmethod
    def from_dict(cls, d: dict) -> "StopConditions":
        return cls(**{k: d.get(k) for k in
                      ("max_tokens", "stop", "stop_token_ids", "min_tokens")},
                   ignore_eos=bool(d.get("ignore_eos", False)))


@dataclass
class SamplingOptions:
    """How to sample (reference common.rs SamplingOptions)."""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    # OpenAI logit_bias: token id -> additive bias (-100..100), applied
    # to the logits before sampling
    logit_bias: Optional[dict] = None
    seed: Optional[int] = None
    n: int = 1

    @property
    def greedy(self) -> bool:
        return self.temperature is None or self.temperature <= 0.0

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if v is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "SamplingOptions":
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class OutputOptions:
    """What to return (reference common.rs OutputOptions)."""

    logprobs: Optional[int] = None
    echo_prompt: bool = False
    skip_special_tokens: bool = True

    def to_dict(self) -> dict:
        return {"logprobs": self.logprobs, "echo_prompt": self.echo_prompt,
                "skip_special_tokens": self.skip_special_tokens}

    @classmethod
    def from_dict(cls, d: dict) -> "OutputOptions":
        return cls(logprobs=d.get("logprobs"),
                   echo_prompt=bool(d.get("echo_prompt", False)),
                   skip_special_tokens=bool(d.get("skip_special_tokens", True)))


@dataclass
class PreprocessedRequest:
    """Token-level request handed to engines (reference
    llm_backend.rs BackendInput)."""

    token_ids: List[int]
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    output: OutputOptions = field(default_factory=OutputOptions)
    eos_token_ids: List[int] = field(default_factory=list)
    mdc_sum: Optional[str] = None       # model-deployment-card checksum
    annotations: List[str] = field(default_factory=list)
    # disaggregation plumbing (set by the disagg path, not the preprocessor)
    disagg: Optional[dict] = None

    def to_dict(self) -> dict:
        return {
            "token_ids": list(self.token_ids),
            "sampling": self.sampling.to_dict(),
            "stop": self.stop.to_dict(),
            "output": self.output.to_dict(),
            "eos_token_ids": list(self.eos_token_ids),
            "mdc_sum": self.mdc_sum,
            "annotations": self.annotations,
            "disagg": self.disagg,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d["token_ids"]),
            sampling=SamplingOptions.from_dict(d.get("sampling", {})),
            stop=StopConditions.from_dict(d.get("stop", {})),
            output=OutputOptions.from_dict(d.get("output", {})),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            mdc_sum=d.get("mdc_sum"),
            annotations=list(d.get("annotations", [])),
            disagg=d.get("disagg"),
        )


FINISH_EOS = "eos"
FINISH_STOP = "stop"
FINISH_LENGTH = "length"
FINISH_CANCELLED = "cancelled"
# the request's end-to-end deadline expired: cancelled by the budget, not
# the caller — clients see finish_reason "timeout" / HTTP 504
FINISH_TIMEOUT = "timeout"
FINISH_ERROR = "error"


@dataclass
class EngineOutput:
    """One streamed chunk from an engine (reference
    llm_backend.rs LLMEngineOutput): new token ids since the last chunk,
    optional engine-decoded text, cumulative counts, finish reason."""

    token_ids: List[int] = field(default_factory=list)
    text: Optional[str] = None
    cum_log_prob: Optional[float] = None
    logprobs: Optional[List[float]] = None
    top_logprobs: Optional[List[Dict[str, Any]]] = None
    finish_reason: Optional[str] = None
    # engine-side metrics (filled on the final chunk)
    prompt_tokens: Optional[int] = None
    completion_tokens: Optional[int] = None
    # KV routing side-channel: overlap blocks seen by the engine
    kv_overlap_blocks: Optional[int] = None
    # dynaprof: per-request cost attribution (queue wait, device-step
    # share, KV footprint) attached to the finish chunk by the engine;
    # absent on every other chunk and on legacy peers (optional field =
    # wire-compatible)
    cost: Optional[dict] = None

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    def to_dict(self) -> dict:
        d: dict = {"token_ids": list(self.token_ids)}
        for k in ("text", "cum_log_prob", "logprobs", "top_logprobs",
                  "finish_reason", "prompt_tokens", "completion_tokens",
                  "kv_overlap_blocks", "cost"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "EngineOutput":
        return cls(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text"),
            cum_log_prob=d.get("cum_log_prob"),
            logprobs=d.get("logprobs"),
            top_logprobs=d.get("top_logprobs"),
            finish_reason=d.get("finish_reason"),
            prompt_tokens=d.get("prompt_tokens"),
            completion_tokens=d.get("completion_tokens"),
            kv_overlap_blocks=d.get("kv_overlap_blocks"),
            cost=d.get("cost"),
        )
