"""OpenAI API protocol types (pydantic).

Reference lib/llm/src/protocols/openai/ (chat_completions.rs,
completions.rs, delta.rs, aggregator.rs, nvext.rs): request/response models
for ``/v1/chat/completions`` and ``/v1/completions``, SSE delta generators,
and stream→full-response aggregation. The reference's ``nvext`` extension
block maps to ``ext`` here (``ignore_eos``, ``annotations``,
``use_raw_prompt``, plus routing hints).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field


class Ext(BaseModel):
    """Framework extension block (reference nvext.rs:28)."""

    model_config = ConfigDict(extra="allow")
    ignore_eos: Optional[bool] = None
    use_raw_prompt: Optional[bool] = None
    annotations: Optional[List[str]] = None
    greedy_sampling: Optional[bool] = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None

    def text(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if isinstance(self.content, list):
            return "".join(
                part.get("text", "") for part in self.content
                if isinstance(part, dict) and part.get("type") == "text")
        return ""


class StreamOptions(BaseModel):
    include_usage: Optional[bool] = None


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    messages: List[ChatMessage]
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None  # non-OpenAI but widely used
    n: int = 1
    stop: Optional[Union[str, List[str]]] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    logit_bias: Optional[Dict[str, float]] = None
    seed: Optional[int] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    user: Optional[str] = None
    min_tokens: Optional[int] = None
    # end-to-end request deadline in SECONDS (dynaguard); overrides the
    # X-Request-Deadline-Ms header and the DYN_REQUEST_DEADLINE_MS default
    timeout: Optional[float] = None
    ext: Optional[Ext] = None
    # accept the reference's field name too
    nvext: Optional[Ext] = None

    def extension(self) -> Ext:
        return self.ext or self.nvext or Ext()

    def stop_list(self) -> Optional[List[str]]:
        if self.stop is None:
            return None
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def max_output_tokens(self) -> Optional[int]:
        return self.max_completion_tokens or self.max_tokens


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    max_tokens: Optional[int] = 16
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: int = 1
    stop: Optional[Union[str, List[str]]] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    logit_bias: Optional[Dict[str, float]] = None
    seed: Optional[int] = None
    logprobs: Optional[int] = None
    echo: bool = False
    user: Optional[str] = None
    min_tokens: Optional[int] = None
    # end-to-end request deadline in SECONDS (dynaguard); overrides the
    # X-Request-Deadline-Ms header and the DYN_REQUEST_DEADLINE_MS default
    timeout: Optional[float] = None
    ext: Optional[Ext] = None
    nvext: Optional[Ext] = None

    def extension(self) -> Ext:
        return self.ext or self.nvext or Ext()

    def stop_list(self) -> Optional[List[str]]:
        if self.stop is None:
            return None
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0
    # dynaprof extension (DYN_PROF_USAGE=1): per-request cost attribution
    # (queue wait, device-step share, KV footprint). Non-OpenAI field,
    # omitted from payloads when None (exclude_none serialization).
    cost: Optional[dict] = None


class ChatChoiceDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: ChatChoiceDelta = Field(default_factory=ChatChoiceDelta)
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int
    model: str
    choices: List[ChatChunkChoice]
    usage: Optional[Usage] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int
    model: str
    choices: List[ChatChoice]
    usage: Optional[Usage] = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int
    model: str
    choices: List[CompletionChoice]
    usage: Optional[Usage] = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelInfo] = Field(default_factory=list)


# --------------------------------------------------------------------------
# Delta generation + aggregation (reference delta.rs / aggregator.rs)


def _finish_reason_openai(reason: Optional[str]) -> Optional[str]:
    """Engine finish reason → client-visible OpenAI finish_reason.
    "cancelled" and "timeout" pass through distinctly (the seed collapsed
    cancelled→stop, which hid deadline expiry from clients entirely)."""
    if reason is None:
        return None
    return {"eos": "stop", "stop": "stop", "length": "length",
            "cancelled": "cancelled", "timeout": "timeout",
            "error": "error"}.get(reason, reason)


class ChatDeltaGenerator:
    """Builds SSE chunks for a chat stream (reference
    openai/chat_completions/delta.rs)."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = f"chatcmpl-{request_id or uuid.uuid4().hex}"
        self.model = model
        self.created = int(time.time())
        self._first = True

    def role_chunk(self) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id, created=self.created, model=self.model,
            choices=[ChatChunkChoice(delta=ChatChoiceDelta(role="assistant",
                                                           content=""))])

    def content_chunk(self, text: str,
                      finish_reason: Optional[str] = None,
                      logprobs: Optional[Dict[str, Any]] = None,
                      ) -> ChatCompletionChunk:
        delta = ChatChoiceDelta(content=text) if text else ChatChoiceDelta()
        return ChatCompletionChunk(
            id=self.id, created=self.created, model=self.model,
            choices=[ChatChunkChoice(
                delta=delta, logprobs=logprobs,
                finish_reason=_finish_reason_openai(finish_reason))])

    def usage_chunk(self, usage: Usage) -> ChatCompletionChunk:
        return ChatCompletionChunk(
            id=self.id, created=self.created, model=self.model,
            choices=[], usage=usage)


class ChatAggregator:
    """Folds a chunk stream into a full ChatCompletionResponse (reference
    openai/chat_completions/aggregator.rs)."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = f"chatcmpl-{request_id or uuid.uuid4().hex}"
        self.model = model
        self.created = int(time.time())
        # keyed by choice index — n>1 streams interleave their chunks
        self.text_parts: Dict[int, List[str]] = {}
        self.finish_reason: Dict[int, str] = {}
        self.lp_content: Dict[int, List[dict]] = {}
        self.usage: Optional[Usage] = None

    def add_chunk(self, chunk: ChatCompletionChunk) -> None:
        for choice in chunk.choices:
            if choice.delta.content:
                self.text_parts.setdefault(choice.index, []).append(
                    choice.delta.content)
            if choice.logprobs and choice.logprobs.get("content"):
                self.lp_content.setdefault(choice.index, []).extend(
                    choice.logprobs["content"])
            if choice.finish_reason:
                self.finish_reason[choice.index] = choice.finish_reason
        if chunk.usage is not None:
            # last-wins: engines may report CUMULATIVE usage per chunk;
            # summing belongs to the n>1 fan-out, which guarantees
            # exactly one (already-merged) usage chunk per stream
            self.usage = chunk.usage

    def response(self) -> ChatCompletionResponse:
        idxs = sorted(set(self.text_parts) | set(self.finish_reason)) or [0]
        return ChatCompletionResponse(
            id=self.id, created=self.created, model=self.model,
            choices=[ChatChoice(
                index=i,
                message=ChatMessage(
                    role="assistant",
                    content="".join(self.text_parts.get(i, []))),
                logprobs=({"content": self.lp_content[i]}
                          if i in self.lp_content else None),
                finish_reason=self.finish_reason.get(i) or "stop")
                for i in idxs],
            usage=self.usage)


class CompletionAggregator:
    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = f"cmpl-{request_id or uuid.uuid4().hex}"
        self.model = model
        self.created = int(time.time())
        self.text_parts: Dict[int, List[str]] = {}
        self.finish_reason: Dict[int, str] = {}
        self.lp: Dict[int, dict] = {}
        self.usage: Optional[Usage] = None

    def add_text(self, text: str, finish_reason: Optional[str] = None,
                 index: int = 0, logprobs: Optional[dict] = None) -> None:
        if text:
            self.text_parts.setdefault(index, []).append(text)
        if logprobs:
            cur = self.lp.setdefault(index, {
                "tokens": [], "token_logprobs": [], "top_logprobs": [],
                "text_offset": []})
            for k in cur:
                cur[k].extend(logprobs.get(k) or [])
        if finish_reason:
            self.finish_reason[index] = finish_reason

    def response(self) -> CompletionResponse:
        idxs = sorted(set(self.text_parts) | set(self.finish_reason)) or [0]
        return CompletionResponse(
            id=self.id, created=self.created, model=self.model,
            choices=[CompletionChoice(
                index=i, text="".join(self.text_parts.get(i, [])),
                logprobs=self.lp.get(i),
                finish_reason=_finish_reason_openai(
                    self.finish_reason.get(i)) or "stop")
                for i in idxs],
            usage=self.usage)


def _merge_usage(cur: Optional["Usage"], new: "Usage") -> "Usage":
    """n>1: completion tokens SUM across choices; the shared prompt is
    counted once (OpenAI semantics)."""
    if cur is None:
        return new
    return Usage(
        prompt_tokens=max(cur.prompt_tokens, new.prompt_tokens),
        completion_tokens=cur.completion_tokens + new.completion_tokens,
        total_tokens=max(cur.prompt_tokens, new.prompt_tokens)
        + cur.completion_tokens + new.completion_tokens,
        cost=cur.cost or new.cost)
