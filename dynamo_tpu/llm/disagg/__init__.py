"""Prefill/decode disaggregation plane.

The reference's core feature (docs/disagg_serving.md): long prefills run on
dedicated prefill workers; computed KV pages migrate to the decode worker's
pool; decode continues locally. Mapping to TPU:

- NATS JetStream prefill queue      → DCP work queue (queue.py)
- disagg_router.rs threshold        → DisaggRouter (router.py)
- NIXL RDMA KV block transfer       → host-staged TCP page transfer with
  DCP-registered endpoints (transfer.py); same-process: direct device copy
- vLLM RemotePrefillRequest staging → engine.reserve_remote /
  submit_prefilled / prefill_only (engine/jax_engine.py)
"""

from .decode import DisaggDecodeEngine
from .prefill_worker import PrefillWorker
from .protocols import RemotePrefillRequest
from .queue import PrefillQueue
from .router import DisaggRouter
from .transfer import KvTransferClient, KvTransferServer, TransferStats

__all__ = [
    "DisaggDecodeEngine", "DisaggRouter", "KvTransferClient",
    "KvTransferServer", "PrefillQueue", "PrefillWorker",
    "RemotePrefillRequest", "TransferStats",
]
