"""KV page transfer plane — the TPU-native NIXL replacement.

Reference: the vLLM patch's ``DynamoNixlConnector`` (patch:811-1216) RDMA-reads/
writes KV blocks directly between GPU VRAM of prefill and decode engines,
with agent metadata exchanged through etcd (``utils/nixl.py``
NixlMetadataStore:56-105). TPUs expose no peer-to-peer RDMA API to user
code, so the idiomatic equivalent is the reference's *cross-slice* path
made primary: device→host gather (one XLA op), raw bytes over a dedicated
TCP side channel framed by the TwoPartCodec, host→device donated scatter on
the receiver (DCN host-staged transfer, SURVEY §5 "Distributed
communication backend"). Endpoint metadata lives in the DCP KV store under
the decode worker's lease, exactly like NIXL metadata in etcd.

Streaming protocol (the DistServe/Mooncake-style chunk pipeline): a
request's pages travel as ``chunk_pages``-sized frames tagged
``{request_id, chunk_idx, n_chunks}``, interleaved freely with other
requests' frames on one connection. The sender pipelines device→host
extract (and optional int8 compression) of chunk *i+1* under the socket
write of chunk *i*; the receiver ingests each chunk as it arrives through
a per-request worker task and resolves the decode-side waiter only on the
final commit chunk. Acks are demultiplexed by request_id, so nothing holds
a lock across a remote wait and concurrent sends to one decode engine make
progress together. The legacy single-frame bulk format (``chunk_pages=0``)
stays on the same wire, bit-compatible.

Layout conversion between prefill TP and decode TP (the Triton
``kv_rearrange`` kernel, patch:743) is unnecessary here: pages travel in
the logical host layout ``[L, n, KV, page_size, hd]`` and each side's
sharded pool scatter applies its own GSPMD sharding on ingest.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ...runtime import codec, guard, tracing, wire
from ...runtime.codec import TwoPartMessage
from ...runtime.config import env_float
from ...runtime.dcp_client import DcpClient

log = logging.getLogger("dynamo_tpu.llm.disagg")


def _io_timeout() -> float:
    return env_float("DYN_IO_TIMEOUT", 30.0) or 30.0


def _ack_timeout(timeout: Optional[float]) -> float:
    return timeout if timeout is not None \
        else (env_float("DYN_REQUEST_TIMEOUT", 60.0) or 60.0)


def metadata_key(namespace: str, engine_id: int) -> str:
    return f"{namespace}/disagg/transfer/{engine_id:x}"


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bundled with jax

        return np.dtype(getattr(ml_dtypes, name))


@dataclass
class TransferStats:
    """Sender-side per-stage accounting for the streaming pipeline.

    The stages run overlapped (extract of chunk i+1 under the wire write
    of chunk i), so ``extract + compress + wire`` legitimately exceeds
    ``wall`` — that inequality is the observable proof the pipeline is
    actually pipelining (bench stage breakdown)."""

    extract_seconds: float = 0.0
    compress_seconds: float = 0.0
    wire_seconds: float = 0.0
    ack_wait_seconds: float = 0.0
    wall_seconds: float = 0.0
    bytes_sent: int = 0
    chunks_sent: int = 0
    sends: int = 0

    def to_dict(self) -> dict:
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self.__dict__.items()}

    def merge(self, other: "TransferStats") -> None:
        """Fold a per-send accumulator into this (shared) one — how the
        worker keeps exact per-request stage figures for trace spans while
        the fleet totals still aggregate."""
        for k, v in other.__dict__.items():
            setattr(self, k, getattr(self, k) + v)


_KV_FRAMES = (wire.KV_TRANSFER_BULK, wire.KV_TRANSFER_CHUNK)


def _decode_body(h: dict, body: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """Frame body → (k, v) host arrays in the header's declared layout.
    Shared by the bulk and chunk paths so both speak one body format:
    raw ``k‖v`` or int8 ``k_q‖v_q‖k_s‖v_s`` (engine/kv_compress.py)."""
    h = wire.decoded(_KV_FRAMES, h)
    shape = tuple(h["shape"])  # [L, n, KV, ps, hd]
    dtype = _np_dtype(h["dtype"])
    k_len = h["k_len"]
    if h.get("quant") == "int8":
        # the header dtype is the ORIGINAL pool dtype to restore to
        from ...engine.kv_compress import dequantize_pages_np

        sshape = shape[:-1] + (1,)
        s_len = int(np.prod(sshape)) * 4
        kq = np.frombuffer(body[:k_len], np.int8).reshape(shape)
        vq = np.frombuffer(body[k_len:2 * k_len], np.int8).reshape(shape)
        ks = np.frombuffer(body[2 * k_len:2 * k_len + s_len],
                           np.float32).reshape(sshape)
        vs = np.frombuffer(body[2 * k_len + s_len:],
                           np.float32).reshape(sshape)
        k = dequantize_pages_np(kq, ks, dtype)
        v = dequantize_pages_np(vq, vs, dtype)
    else:
        k = np.frombuffer(body[:k_len], dtype).reshape(shape)
        v = np.frombuffer(body[k_len:], dtype).reshape(shape)
    return k, v


class _IngestState:
    """Per-request receive state: frames from one connection funnel into
    ``queue``; ``task`` drains it so a slow inject for one request never
    head-of-line-blocks other requests sharing the connection."""

    __slots__ = ("queue", "task", "received", "injected", "failed", "error",
                 "committed", "inject_seconds", "bytes")

    def __init__(self):
        self.queue: asyncio.Queue = asyncio.Queue()
        self.task: Optional[asyncio.Task] = None
        self.received = 0
        self.injected: List[int] = []
        self.failed = False
        self.error: Optional[str] = None
        self.committed = False
        self.inject_seconds = 0.0   # per-stream inject time (trace span)
        self.bytes = 0


class KvTransferServer:
    """Decode-side ingest listener.

    Accepts KV page payloads — chunked streams or legacy single bulk
    frames — scatters them into the engine's pool, and resolves the waiter
    registered under the request id with the remotely sampled first token
    once the stream commits. Each frame is acked
    ``{ok, request_id, chunk_idx[, committed]}`` (the NIXL
    completion-notification analog); a mid-stream failure sets the error
    on the waiter immediately so the decode side falls back without
    burning its prefill timeout, and partial state is torn down without
    ever writing into pages the decode side may have reassigned
    (per-chunk late-write guard)."""

    def __init__(self, engine):
        self.engine = engine
        self._server: Optional[asyncio.AbstractServer] = None
        self._waiters: Dict[str, asyncio.Future] = {}
        self._ingests: Dict[str, _IngestState] = {}
        self.host: str = ""
        self.port: int = 0
        self._conns: Set[asyncio.StreamWriter] = set()
        # transfer-plane accounting (disagg bench breakdown)
        self.bytes_ingested = 0
        self.pages_ingested = 0
        self.chunks_ingested = 0
        self.ingest_seconds = 0.0
        self.streams_failed = 0

    async def start(self, host: str = "0.0.0.0") -> None:
        self._server = await asyncio.start_server(self._on_conn, host, 0)
        self.port = self._server.sockets[0].getsockname()[1]
        self.host = _local_ip()

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await asyncio.wait_for(self._server.wait_closed(), _io_timeout())
        # drop established connections too — a stop() is a restart from the
        # sender's point of view, and senders probe liveness through the
        # socket, not the (gone) listener
        for w in list(self._conns):
            w.close()
        self._conns.clear()
        for st in list(self._ingests.values()):
            if st.task is not None:
                st.task.cancel()
        self._ingests.clear()
        for fut in self._waiters.values():
            if not fut.done():
                fut.cancel()
        self._waiters.clear()

    async def register(self, dcp: DcpClient, namespace: str, engine_id: int,
                       lease: int = 0) -> None:
        """Publish this listener for prefill workers (NixlMetadataStore
        analog — dies with the worker's lease)."""
        meta = {"host": self.host, "port": self.port}
        await dcp.kv_put(metadata_key(namespace, engine_id),
                         json.dumps(meta).encode(), lease=lease)

    def expect(self, request_id: str) -> asyncio.Future:
        """Future resolving to the first sampled token once the KV for
        request_id has been injected (or failing fast when the stream
        errors — the decode side falls back immediately)."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters[request_id] = fut
        return fut

    def cancel(self, request_id: str) -> None:
        fut = self._waiters.pop(request_id, None)
        if fut and not fut.done():
            fut.cancel()

    def stats(self) -> dict:
        return {
            "kv_transfer_bytes_total": self.bytes_ingested,
            "kv_transfer_pages_total": self.pages_ingested,
            "kv_transfer_chunks_total": self.chunks_ingested,
            "kv_transfer_inject_seconds_total": round(self.ingest_seconds, 4),
            "kv_transfer_streams_failed_total": self.streams_failed,
        }

    def _fail_waiter(self, request_id: Optional[str], exc: Exception) -> None:
        """Surface a stream failure to the decode side NOW instead of
        letting it idle out the full prefill timeout."""
        fut = self._waiters.pop(request_id, None) if request_id else None
        if fut is not None and not fut.done():
            fut.set_exception(exc)

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        wlock = asyncio.Lock()  # ack frames from concurrent workers
        conn_rids: Set[str] = set()
        self._conns.add(writer)
        try:
            while True:
                try:
                    # idle ingest read: frames arrive whenever a prefill
                    # worker sends; stream lifetime == connection lifetime
                    msg = await codec.decode(reader)  # dynalint: disable=unbounded-await
                    await guard.chaos_point("kv.recv", writer)
                except (asyncio.IncompleteReadError, ConnectionError,
                        codec.CodecError):
                    return
                h = wire.decoded(
                    _KV_FRAMES + (wire.KV_TRANSFER_ABORT,), msg.header)
                rid = h.get("request_id")
                kind = h.get("kind")
                if kind not in (None, "chunk", "abort") or \
                        int(h.get("v", 1)) > wire.frame_version(
                            wire.KV_TRANSFER_CHUNK):
                    # schema mismatch from a newer/foreign peer: reject
                    # with a logged, typed error — never a KeyError three
                    # frames down the ingest worker. Absent kind/v =
                    # legacy, still accepted above.
                    err = wire.WireVersionMismatch(
                        f"unsupported transfer frame kind={kind!r} "
                        f"v={h.get('v', 1)} (speak "
                        f"v<={wire.frame_version(wire.KV_TRANSFER_CHUNK)})")
                    log.warning("rejecting transfer frame from %s for "
                                "request %s: %s", peer, rid, err)
                    self.streams_failed += 1
                    self._fail_waiter(rid, err)
                    st = self._ingests.get(rid)
                    if st is not None and rid in conn_rids:
                        st.queue.put_nowait(None)  # tear down mid-stream
                    nack = wire.checked(wire.KV_TRANSFER_ACK, {
                        "ok": False, "request_id": rid or "",
                        "error": str(err)})
                    async with wlock:
                        writer.write(codec.encode(
                            TwoPartMessage(header=nack)))
                        # frame atomicity needs the lock across the
                        # (bounded) drain
                        await asyncio.wait_for(  # dynalint: disable=lock-across-blocking
                            writer.drain(), _io_timeout())
                    continue
                if kind == "abort":
                    st = self._ingests.get(rid)
                    if st is not None and rid in conn_rids:
                        st.queue.put_nowait(None)  # sentinel → teardown
                    else:
                        self._fail_waiter(rid, RuntimeError(
                            "sender aborted transfer"))
                    continue
                st = self._ingests.get(rid)
                if st is None or rid not in conn_rids:
                    st = _IngestState()
                    self._ingests[rid] = st
                    conn_rids.add(rid)
                    st.task = asyncio.ensure_future(
                        self._ingest_worker(rid, st, writer, wlock))
                st.queue.put_nowait(msg)
        finally:
            # connection dropped mid-stream: fail every uncommitted stream
            # it owned so decode falls back immediately; the worker's
            # cancel handler releases the partial state
            for rid in conn_rids:
                st = self._ingests.get(rid)
                if st is not None and st.task is not None and not st.committed:
                    st.task.cancel()
            self._conns.discard(writer)
            writer.close()
            log.debug("transfer conn from %s closed", peer)

    async def _ingest_worker(self, request_id: str, st: _IngestState,
                             writer: asyncio.StreamWriter,
                             wlock: asyncio.Lock) -> None:
        """Drain one request's frames: inject each chunk, ack it, resolve
        the waiter on the commit (final) chunk. Interleaved requests on
        the same connection each get their own worker, so one slow inject
        no longer serializes the whole transfer plane."""
        try:
            while True:
                # bounded by the connection: _on_conn cancels this task
                # the moment the conn drops, so the wait cannot outlive it
                msg = await st.queue.get()  # dynalint: disable=unbounded-await
                if msg is None:  # sender abort
                    self.streams_failed += 1
                    # proto: kv_transfer.stream streaming->aborted
                    self._fail_waiter(request_id, RuntimeError(
                        "sender aborted transfer mid-stream"))
                    return
                h = wire.decoded(_KV_FRAMES, msg.header)
                legacy = "kind" not in h
                chunk_idx = 0 if legacy else int(h["chunk_idx"])
                n_chunks = 1 if legacy else int(h["n_chunks"])
                final = chunk_idx >= n_chunks - 1
                ack = wire.checked(wire.KV_TRANSFER_ACK, {
                    "ok": True, "request_id": request_id,
                    "chunk_idx": chunk_idx})
                if st.failed:
                    ack.update(ok=False, error=st.error or "stream failed")
                elif request_id not in self._waiters:
                    # per-chunk late-write guard: the decode side may have
                    # timed out and released these pages — they can belong
                    # to another request now, so drop the payload
                    st.failed = True  # proto: kv_transfer.stream streaming->failed
                    st.error = "unknown/cancelled request"
                    log.warning("dropping KV chunk %d for unknown/cancelled "
                                "request %s", chunk_idx, request_id)
                    ack.update(ok=False, error=st.error)
                else:
                    try:
                        await self._inject_chunk(h, msg.body, st)
                    except Exception as exc:  # noqa: BLE001 — report + fail fast
                        log.exception("KV ingest failed for %s chunk %d",
                                      request_id, chunk_idx)
                        st.failed = True  # proto: kv_transfer.stream streaming->failed
                        st.error = str(exc)
                        self.streams_failed += 1
                        self._fail_waiter(request_id, exc)
                        ack.update(ok=False, error=st.error)
                if not st.failed and final:
                    if st.received == n_chunks:
                        fut = self._waiters.pop(request_id, None)
                        if fut is not None and not fut.done():
                            fut.set_result(int(h["first_token"]))
                        st.committed = True  # proto: kv_transfer.stream streaming->committed
                        ack["committed"] = True
                        if h.get("trace"):
                            # receiver-side stage span, joined to the
                            # sender's trace via the frame-header ctx
                            tracing.get_tracer().record_span(
                                "kv_transfer.inject", st.inject_seconds,
                                parent=h["trace"],
                                attributes={"request_id": request_id,
                                            "pages": len(st.injected),
                                            "bytes": st.bytes,
                                            "chunks": st.received})
                    else:
                        st.failed = True  # proto: kv_transfer.stream streaming->failed
                        st.error = (f"incomplete stream: {st.received}"
                                    f"/{n_chunks} chunks")
                        self.streams_failed += 1
                        self._fail_waiter(request_id,
                                          RuntimeError(st.error))
                        ack.update(ok=False, error=st.error)
                async with wlock:
                    writer.write(codec.encode(TwoPartMessage(header=ack)))
                    # frame atomicity needs the lock across the (bounded)
                    # drain
                    await asyncio.wait_for(  # dynalint: disable=lock-across-blocking
                        writer.drain(), _io_timeout())
                if final:
                    return
        except asyncio.CancelledError:
            if not st.committed:
                self.streams_failed += 1
                # proto: kv_transfer.stream streaming->failed
                self._fail_waiter(request_id, ConnectionError(
                    "KV transfer connection dropped mid-stream"))
            raise
        except Exception as exc:  # noqa: BLE001 — ack write failure etc.
            if not st.committed:
                self.streams_failed += 1
            self._fail_waiter(request_id, exc)
        finally:
            if self._ingests.get(request_id) is st:
                del self._ingests[request_id]

    async def _inject_chunk(self, h: dict, body: bytes,
                            st: _IngestState) -> None:
        h = wire.decoded(_KV_FRAMES, h)
        page_ids = list(h["page_ids"])
        if page_ids:
            t0 = time.monotonic()
            k, v = _decode_body(h, body)
            await self.engine.inject_pages(page_ids, k, v)
            dt = time.monotonic() - t0
            self.bytes_ingested += len(body)
            self.pages_ingested += len(page_ids)
            self.ingest_seconds += dt
            st.inject_seconds += dt
            st.bytes += len(body)
            st.injected.extend(page_ids)
        self.chunks_ingested += 1
        st.received += 1


def _bulk_frame(request_id: str, page_ids, k: np.ndarray, v: np.ndarray,
                first_token: int, compress: bool) -> Tuple[dict, list]:
    """Legacy single-frame encoding: header + zero-copy body parts."""
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    header = wire.checked(wire.KV_TRANSFER_BULK, {
        "request_id": request_id,
        "page_ids": list(int(p) for p in page_ids),
        "shape": list(k.shape),
        "dtype": str(k.dtype),
        "k_len": k.nbytes,
        "first_token": int(first_token),
        "v": wire.frame_version(wire.KV_TRANSFER_BULK),
    })
    if compress:
        from ...engine.kv_compress import quantize_pages_np

        kq, ks = quantize_pages_np(k)
        vq, vs = quantize_pages_np(v)
        header["quant"] = "int8"
        header["k_len"] = kq.nbytes
        parts = [kq, vq, ks, vs]
    else:
        parts = [k, v]
    return header, parts


class KvTransferClient:
    """Prefill-side sender: one persistent connection per decode engine.

    A background ack loop demultiplexes replies by request_id, so any
    number of sends — bulk or chunked streams — share the connection
    concurrently; nothing holds a lock across a remote ack wait (the seed
    serialized all in-flight jobs to one decode engine here). Frames are
    written atomically (synchronous ``writelines`` of zero-copy parts), so
    interleaving between awaits never splits a frame."""

    def __init__(self, host: str, port: int,
                 stats: Optional[TransferStats] = None):
        self.host = host
        self.port = port
        # the connection triple is written by _ensure (reconnect) and
        # nulled by the ack loop on connection loss — both under the
        # lock; senders hold the writer _ensure returned, never re-read
        # self._writer across their awaits
        self._reader: Optional[asyncio.StreamReader] = None  # guarded-by: self._conn_lock
        self._writer: Optional[asyncio.StreamWriter] = None  # guarded-by: self._conn_lock
        self._ack_task: Optional[asyncio.Task] = None  # guarded-by: self._conn_lock
        self._conn_lock = asyncio.Lock()  # held for connect only, never acks
        # ack demux table: single-statement register/pop/get only
        self._pending: Dict[str, asyncio.Queue] = {}  # guarded-by: loop
        self.stats = stats if stats is not None else TransferStats()

    @classmethod
    async def lookup(cls, dcp: DcpClient, namespace: str, engine_id: int,
                     stats: Optional[TransferStats] = None
                     ) -> "KvTransferClient":
        raw = await dcp.kv_get(metadata_key(namespace, engine_id))
        if raw is None:
            raise RuntimeError(
                f"no KV transfer endpoint registered for engine "
                f"{engine_id:x} (decode worker down?)")
        meta = json.loads(raw)
        return cls(meta["host"], meta["port"], stats=stats)

    async def _ensure(self) -> asyncio.StreamWriter:
        """(Re)connect if needed; returns the live writer. Senders keep
        this local reference across their awaits — re-reading
        ``self._writer`` mid-send races the ack loop nulling it on
        connection loss (the demux would yank the writer out from under
        an in-flight frame)."""
        async with self._conn_lock:
            if self._writer is None or self._writer.is_closing():
                await guard.chaos_point("kv.connect")
                # the connect lock only guards (re)connection, never an
                # ack wait; the connect itself is bounded
                self._reader, self._writer = await asyncio.wait_for(  # dynalint: disable=lock-across-blocking
                    asyncio.open_connection(self.host, self.port),
                    _io_timeout())
                self._ack_task = asyncio.ensure_future(
                    self._ack_loop(self._reader, self._writer))
            return self._writer

    async def _ack_loop(self, reader: asyncio.StreamReader,
                        writer: asyncio.StreamWriter) -> None:
        """Demux acks to per-request queues; on connection loss fail every
        pending send so none of them idles out its timeout."""
        try:
            while True:
                # idle demux read: senders bound their own ack waits; this
                # loop lives exactly as long as the connection
                msg = await codec.decode(reader)  # dynalint: disable=unbounded-await
                ack = wire.decoded(wire.KV_TRANSFER_ACK, msg.header)
                q = self._pending.get(ack.get("request_id"))
                if q is not None:
                    q.put_nowait(ack)
                else:
                    log.debug("dropping unroutable transfer ack: %r", ack)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — conn loss/desync
            err = {"ok": False, "conn_lost": True,
                   "error": f"transfer connection lost: {exc}"}
            for q in self._pending.values():
                q.put_nowait(err)
            async with self._conn_lock:
                if self._writer is writer:
                    self._writer = None
            writer.close()

    def _register(self, request_id: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._pending[request_id] = q
        return q

    @staticmethod
    def _check_ack(ack: dict) -> None:
        ack = wire.decoded(wire.KV_TRANSFER_ACK, ack)
        if int(ack.get("v", 1)) > wire.frame_version(wire.KV_TRANSFER_ACK):
            raise wire.WireVersionMismatch(
                f"decode side acked with unsupported schema "
                f"v={ack.get('v')}")
        if not ack.get("ok"):
            if ack.get("conn_lost"):
                raise ConnectionError(ack.get("error"))
            raise RuntimeError(
                f"decode-side KV ingest failed: {ack.get('error')}")

    async def send_kv(self, request_id: str, page_ids, k: np.ndarray,
                      v: np.ndarray, first_token: int,
                      timeout: Optional[float] = None,
                      compress: bool = False,
                      stats: Optional[TransferStats] = None) -> None:
        """Bulk mode (``chunk_pages=0``): ship all pages
        [L, n, KV, ps, hd] + the first token in one frame; returns once
        the decode side has injected them (raises on remote failure).
        ``compress=True`` quantizes each (token, head) row to int8 +
        f32 scale before framing — ~half the DCN bytes, lossy (see
        engine/kv_compress.py); the header's dtype stays the ORIGINAL
        so the receiver restores into its pool dtype. ``stats`` overrides
        the accumulator (per-send accounting for trace spans)."""
        st = stats if stats is not None else self.stats
        timeout = _ack_timeout(timeout)
        header, parts = _bulk_frame(request_id, page_ids, k, v,
                                    first_token, compress)
        tc = tracing.get_tracer().current_trace_ctx()
        if tc is not None:
            header["trace"] = tc
        q = self._register(request_id)
        t_wall = time.monotonic()
        try:
            writer = await self._ensure()
            await guard.chaos_point("kv.send", writer)
            t0 = time.monotonic()
            writer.writelines(codec.encode_parts(header, parts))
            await asyncio.wait_for(writer.drain(), _io_timeout())
            now = time.monotonic()
            st.wire_seconds += now - t0
            st.bytes_sent += sum(p.nbytes for p in parts)
            ack = await asyncio.wait_for(q.get(), timeout)
            st.ack_wait_seconds += time.monotonic() - now
        finally:
            self._pending.pop(request_id, None)
            st.wall_seconds += time.monotonic() - t_wall
            st.sends += 1
        self._check_ack(ack)

    async def send_kv_chunked(self, request_id: str, n_chunks: int, frames,
                              first_token: int,
                              timeout: Optional[float] = None,
                              stats: Optional[TransferStats] = None) -> None:
        """Streamed mode: consume ``frames`` — an async iterator yielding
        ``(dst_page_ids, header_extra, body_parts, nbytes)`` per chunk —
        one chunk ahead, so producing chunk i+1 (device→host extract +
        optional compression) overlaps the socket write of chunk i. The
        final chunk carries the first token and acts as the commit; the
        call returns once the decode side acks that commit. On any
        failure an abort frame tears down the receiver's partial state
        (which fails the decode-side waiter → immediate local fallback).
        ``stats`` overrides the accumulator (per-send accounting)."""
        st = stats if stats is not None else self.stats
        timeout = _ack_timeout(timeout)
        tc = tracing.get_tracer().current_trace_ctx()
        q = self._register(request_id)
        t_wall = time.monotonic()
        nxt: Optional[asyncio.Future] = None
        committed = False
        try:
            writer = await self._ensure()
            nxt = asyncio.ensure_future(frames.__anext__())
            idx = 0
            while True:
                try:
                    dst, extra, parts, nbytes = await nxt
                    nxt = None
                except StopAsyncIteration:
                    nxt = None
                    break
                if idx + 1 < n_chunks:
                    # pipeline: start producing chunk i+1 before writing i
                    nxt = asyncio.ensure_future(frames.__anext__())
                header = wire.checked(wire.KV_TRANSFER_CHUNK, {
                    "kind": "chunk", "request_id": request_id,
                    "chunk_idx": idx, "n_chunks": n_chunks,
                    "page_ids": [int(p) for p in dst],
                    "v": wire.frame_version(wire.KV_TRANSFER_CHUNK),
                    **extra})
                if idx == n_chunks - 1:
                    header["first_token"] = int(first_token)
                    if tc is not None:  # commit chunk carries the trace ctx
                        header["trace"] = tc
                await guard.chaos_point("kv.send", writer)
                t0 = time.monotonic()
                writer.writelines(codec.encode_parts(header, parts))
                await asyncio.wait_for(writer.drain(), _io_timeout())
                st.wire_seconds += time.monotonic() - t0
                st.bytes_sent += nbytes
                st.chunks_sent += 1
                idx += 1
                # early-failure check: abort the remaining extract/send
                # work the moment the receiver reports a chunk failure
                while not q.empty():
                    ack = q.get_nowait()
                    self._check_ack(ack)
                    committed = committed or bool(ack.get("committed"))
                if idx >= n_chunks:
                    break
            if idx != n_chunks:
                raise RuntimeError(
                    f"chunk producer yielded {idx}/{n_chunks} chunks")
            t1 = time.monotonic()
            while not committed:
                ack = await asyncio.wait_for(q.get(), timeout)
                self._check_ack(ack)
                committed = bool(ack.get("committed"))
            st.ack_wait_seconds += time.monotonic() - t1
        except BaseException:
            if nxt is not None:
                nxt.cancel()
            await self._abort(request_id)
            raise
        finally:
            if hasattr(frames, "aclose"):
                try:
                    await frames.aclose()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            self._pending.pop(request_id, None)
            st.wall_seconds += time.monotonic() - t_wall
            st.sends += 1

    async def _abort(self, request_id: str) -> None:
        """Best-effort abort frame: lets the receiver drop partial state
        and fail the waiter now, without closing the shared connection
        under other in-flight requests."""
        try:
            async with self._conn_lock:
                writer = self._writer  # snapshot: the ack loop may null it
            if writer is not None and not writer.is_closing():
                writer.writelines(codec.encode_parts(
                    wire.checked(wire.KV_TRANSFER_ABORT, {
                        "kind": "abort", "request_id": request_id})))
                await asyncio.wait_for(writer.drain(), _io_timeout())
        except Exception:  # noqa: BLE001 — the conn may be the failure
            pass

    def close(self) -> None:
        if self._ack_task is not None:
            self._ack_task.cancel()
            self._ack_task = None
        if self._writer:
            self._writer.close()
            self._writer = None


def _local_ip() -> str:
    from ...runtime.tcp import _local_ip as impl

    return impl()
