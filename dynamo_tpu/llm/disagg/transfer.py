"""KV page transfer plane — the TPU-native NIXL replacement.

Reference: the vLLM patch's ``DynamoNixlConnector`` (patch:811-1216) RDMA-reads/
writes KV blocks directly between GPU VRAM of prefill and decode engines,
with agent metadata exchanged through etcd (``utils/nixl.py``
NixlMetadataStore:56-105). TPUs expose no peer-to-peer RDMA API to user
code, so the idiomatic equivalent is the reference's *cross-slice* path
made primary: device→host gather (one XLA op), raw bytes over a dedicated
TCP side channel framed by the TwoPartCodec, host→device donated scatter on
the receiver (DCN host-staged transfer, SURVEY §5 "Distributed
communication backend"). Endpoint metadata lives in the DCP KV store under
the decode worker's lease, exactly like NIXL metadata in etcd.

Layout conversion between prefill TP and decode TP (the Triton
``kv_rearrange`` kernel, patch:743) is unnecessary here: pages travel in
the logical host layout ``[L, n, KV, page_size, hd]`` and each side's
sharded pool scatter applies its own GSPMD sharding on ingest.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, Optional, Tuple

import numpy as np

from ...runtime import codec
from ...runtime.codec import TwoPartMessage
from ...runtime.dcp_client import DcpClient

log = logging.getLogger("dynamo_tpu.llm.disagg")


def metadata_key(namespace: str, engine_id: int) -> str:
    return f"{namespace}/disagg/transfer/{engine_id:x}"


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bundled with jax

        return np.dtype(getattr(ml_dtypes, name))


class KvTransferServer:
    """Decode-side ingest listener.

    Accepts KV page payloads, scatters them into the engine's pool, and
    resolves the waiter registered under the request id with the remotely
    sampled first token. One message per request:
    header {request_id, page_ids, shape, dtype, first_token, k_len} with
    shape = [L, n, KV, page_size, hd] (kv-head-major pool layout),
    body = k_bytes || v_bytes; replies {ok, request_id} once injection
    completes (the NIXL completion-notification analog).
    """

    def __init__(self, engine):
        self.engine = engine
        self._server: Optional[asyncio.AbstractServer] = None
        self._waiters: Dict[str, asyncio.Future] = {}
        self.host: str = ""
        self.port: int = 0
        # transfer-plane accounting (disagg bench breakdown)
        self.bytes_ingested = 0
        self.pages_ingested = 0
        self.ingest_seconds = 0.0

    async def start(self, host: str = "0.0.0.0") -> None:
        self._server = await asyncio.start_server(self._on_conn, host, 0)
        self.port = self._server.sockets[0].getsockname()[1]
        self.host = _local_ip()

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for fut in self._waiters.values():
            if not fut.done():
                fut.cancel()
        self._waiters.clear()

    async def register(self, dcp: DcpClient, namespace: str, engine_id: int,
                       lease: int = 0) -> None:
        """Publish this listener for prefill workers (NixlMetadataStore
        analog — dies with the worker's lease)."""
        meta = {"host": self.host, "port": self.port}
        await dcp.kv_put(metadata_key(namespace, engine_id),
                         json.dumps(meta).encode(), lease=lease)

    def expect(self, request_id: str) -> asyncio.Future:
        """Future resolving to the first sampled token once the KV for
        request_id has been injected."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters[request_id] = fut
        return fut

    def cancel(self, request_id: str) -> None:
        fut = self._waiters.pop(request_id, None)
        if fut and not fut.done():
            fut.cancel()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    msg = await codec.decode(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                try:
                    await self._ingest(msg)
                    writer.write(codec.encode(TwoPartMessage(
                        header={"ok": True,
                                "request_id": msg.header["request_id"]})))
                except Exception as exc:  # noqa: BLE001 — report to sender
                    log.exception("KV ingest failed")
                    writer.write(codec.encode(TwoPartMessage(
                        header={"ok": False, "error": str(exc),
                                "request_id": msg.header.get("request_id")})))
                await writer.drain()
        finally:
            writer.close()
            log.debug("transfer conn from %s closed", peer)

    async def _ingest(self, msg: TwoPartMessage) -> None:
        h = msg.header
        request_id = h["request_id"]
        # claim the waiter FIRST: if the decode side already timed out and
        # released the pages, they may belong to another request now — a
        # late write would corrupt it, so drop the payload instead
        fut = self._waiters.pop(request_id, None)
        if fut is None:
            log.warning("dropping KV for unknown/cancelled request %s",
                        request_id)
            return
        page_ids = list(h["page_ids"])
        if page_ids:
            import time as _time

            t0 = _time.monotonic()
            shape = tuple(h["shape"])  # [L, n, KV, ps, hd]
            dtype = _np_dtype(h["dtype"])
            k_len = h["k_len"]
            if h.get("quant") == "int8":
                # compressed frame (sender opted in — see
                # engine/kv_compress.py): body = k_q‖v_q‖k_s‖v_s; the
                # header dtype is the ORIGINAL pool dtype to restore to
                from ...engine.kv_compress import dequantize_pages_np

                sshape = shape[:-1] + (1,)
                s_len = int(np.prod(sshape)) * 4
                kq = np.frombuffer(msg.body[:k_len],
                                   np.int8).reshape(shape)
                vq = np.frombuffer(msg.body[k_len:2 * k_len],
                                   np.int8).reshape(shape)
                ks = np.frombuffer(msg.body[2 * k_len:2 * k_len + s_len],
                                   np.float32).reshape(sshape)
                vs = np.frombuffer(msg.body[2 * k_len + s_len:],
                                   np.float32).reshape(sshape)
                k = dequantize_pages_np(kq, ks, dtype)
                v = dequantize_pages_np(vq, vs, dtype)
            else:
                k = np.frombuffer(msg.body[:k_len], dtype).reshape(shape)
                v = np.frombuffer(msg.body[k_len:], dtype).reshape(shape)
            await self.engine.inject_pages(page_ids, k, v)
            self.bytes_ingested += len(msg.body)
            self.pages_ingested += len(page_ids)
            self.ingest_seconds += _time.monotonic() - t0
        if not fut.done():
            fut.set_result(int(h["first_token"]))


class KvTransferClient:
    """Prefill-side sender: one persistent connection per decode engine."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    @classmethod
    async def lookup(cls, dcp: DcpClient, namespace: str,
                     engine_id: int) -> "KvTransferClient":
        raw = await dcp.kv_get(metadata_key(namespace, engine_id))
        if raw is None:
            raise RuntimeError(
                f"no KV transfer endpoint registered for engine "
                f"{engine_id:x} (decode worker down?)")
        meta = json.loads(raw)
        return cls(meta["host"], meta["port"])

    async def _ensure(self) -> None:
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port)

    async def send_kv(self, request_id: str, page_ids, k: np.ndarray,
                      v: np.ndarray, first_token: int,
                      timeout: float = 60.0,
                      compress: bool = False) -> None:
        """Ship pages [L, n, KV, ps, hd] + first token; returns once the
        decode side has injected them (raises on remote failure).
        ``compress=True`` quantizes each (token, head) row to int8 +
        f32 scale before framing — ~half the DCN bytes, lossy (see
        engine/kv_compress.py); the header's dtype stays the ORIGINAL
        so the receiver restores into its pool dtype."""
        k = np.ascontiguousarray(k)
        v = np.ascontiguousarray(v)
        header = {
            "request_id": request_id,
            "page_ids": list(int(p) for p in page_ids),
            "shape": list(k.shape),
            "dtype": str(k.dtype),
            "k_len": k.nbytes,
            "first_token": int(first_token),
        }
        if compress:
            from ...engine.kv_compress import quantize_pages_np

            kq, ks = quantize_pages_np(k)
            vq, vs = quantize_pages_np(v)
            header["quant"] = "int8"
            header["k_len"] = kq.nbytes
            body = (kq.tobytes() + vq.tobytes()
                    + ks.tobytes() + vs.tobytes())
        else:
            body = k.tobytes() + v.tobytes()
        async with self._lock:  # frame-atomic per request
            try:
                await self._ensure()
                self._writer.write(codec.encode(TwoPartMessage(
                    header=header, body=body)))
                await self._writer.drain()
                ack = await asyncio.wait_for(codec.decode(self._reader),
                                             timeout)
            except Exception:
                # a timed-out/aborted read leaves the stream mid-frame —
                # drop the connection so the next send starts clean
                self.close()
                raise
            if ack.header.get("request_id") != request_id:
                self.close()  # desynced: stale ack from an earlier request
                raise RuntimeError(
                    f"KV transfer ack mismatch: sent {request_id}, "
                    f"got {ack.header.get('request_id')}")
        if not ack.header.get("ok"):
            raise RuntimeError(
                f"decode-side KV ingest failed: {ack.header.get('error')}")

    def close(self) -> None:
        if self._writer:
            self._writer.close()
            self._writer = None


def _local_ip() -> str:
    from ...runtime.tcp import _local_ip as impl

    return impl()
