"""The shared prefill work queue.

Reference: NATS JetStream pull queue (examples/llm/utils/prefill_queue.py +
utils/nats_queue.py) — elastic xPyD semantics: decode workers push, any
prefill worker pulls; workers join/leave freely (docs/disagg_serving.md:93-100).
Here the DCP server's durable FIFO work queue provides the same contract.
"""

from __future__ import annotations

import json
from typing import Optional

from ...runtime.dcp_client import DcpClient
from .protocols import RemotePrefillRequest


class PrefillQueue:
    def __init__(self, dcp: DcpClient, namespace: str = "dynamo",
                 name: str = "prefill_queue"):
        self.dcp = dcp
        self.queue = f"{namespace}.{name}"

    async def put(self, req: RemotePrefillRequest) -> None:
        await self.dcp.queue_put(self.queue,
                                 json.dumps(req.to_dict()).encode())

    async def pull(self, timeout: float = 0.0
                   ) -> Optional[RemotePrefillRequest]:
        raw = await self.dcp.queue_pull(self.queue, timeout=timeout)
        if raw is None:
            return None
        return RemotePrefillRequest.from_dict(json.loads(raw))

    async def depth(self) -> int:
        return await self.dcp.queue_len(self.queue)
