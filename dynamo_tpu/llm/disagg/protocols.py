"""Disaggregation wire types (reference vllm/remote_prefill.py
RemotePrefillRequest — patch:3584 — carried over the JetStream prefill
queue in the reference, over the DCP work queue here)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ...runtime import wire


@dataclass
class RemotePrefillRequest:
    """One queued remote-prefill job.

    ``page_ids`` are DECODE-side pool pages, reserved before enqueueing
    (reference: vLLM allocates decode blocks first, then enqueues with
    ``block_ids`` so the prefill side can write straight into them).
    ``skip_pages`` leading pages are already valid on the decode side
    (prefix-cache hits) and are not transferred.
    """

    request_id: str
    token_ids: List[int]
    sampling: dict = field(default_factory=dict)
    eos_token_ids: List[int] = field(default_factory=list)
    page_ids: List[int] = field(default_factory=list)
    skip_pages: int = 0
    engine_id: int = 0          # decode engine instance (transfer lookup key)
    # dyntrace context of the decode-side request, so the prefill worker's
    # spans join the same trace. Absent on the wire = no parent (old
    # peers interoperate unchanged).
    trace_ctx: Optional[dict] = None
    # remaining request budget (ms) at enqueue time: the prefill worker
    # drops jobs whose budget is spent and caps its ack waits by what is
    # left. Absent on the wire = no deadline (legacy peers unchanged).
    deadline_ms: Optional[int] = None

    def to_dict(self) -> dict:
        d = {
            "request_id": self.request_id,
            "token_ids": list(self.token_ids),
            "sampling": self.sampling,
            "eos_token_ids": list(self.eos_token_ids),
            "page_ids": list(self.page_ids),
            "skip_pages": self.skip_pages,
            "engine_id": self.engine_id,
        }
        if self.trace_ctx is not None:
            d["trace_ctx"] = self.trace_ctx
        if self.deadline_ms is not None:
            d["deadline_ms"] = int(self.deadline_ms)
        return wire.checked(wire.PREFILL_REMOTE_REQUEST, d)

    @classmethod
    def from_dict(cls, d: dict) -> "RemotePrefillRequest":
        d = wire.decoded(wire.PREFILL_REMOTE_REQUEST, d)
        return cls(request_id=d["request_id"],
                   token_ids=list(d["token_ids"]),
                   sampling=d.get("sampling", {}),
                   eos_token_ids=list(d.get("eos_token_ids", [])),
                   page_ids=list(d.get("page_ids", [])),
                   skip_pages=int(d.get("skip_pages", 0)),
                   engine_id=int(d.get("engine_id", 0)),
                   trace_ctx=d.get("trace_ctx"),
                   deadline_ms=d.get("deadline_ms"))
