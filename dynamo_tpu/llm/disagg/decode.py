"""Decode-side disaggregation orchestration.

Reference examples/llm/components/worker.py:37-189 (VllmWorker): per
request, consult the disagg router with (prefill_length, prefix_hit);
remote → allocate decode-side KV blocks, enqueue a RemotePrefillRequest,
wait for the prefill worker's block write + completion notification, then
continue decoding locally. Falls back to fully local prefill whenever the
pool is exhausted, the queue is saturated, or the remote path errors.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Optional

from ...runtime.engine import Context
from ..protocols.common import (FINISH_CANCELLED, FINISH_ERROR, EngineOutput,
                                PreprocessedRequest)
from .protocols import RemotePrefillRequest
from .queue import PrefillQueue
from .router import DisaggRouter
from .transfer import KvTransferServer

log = logging.getLogger("dynamo_tpu.llm.disagg")


class DisaggDecodeEngine:
    """AsyncEngine wrapper adding conditional remote prefill to a JaxEngine.

    Serves the same token-level protocol, so it drops into serve_token_model
    / the Backend pipeline unchanged.
    """

    def __init__(self, engine, queue: PrefillQueue, transfer: KvTransferServer,
                 router: DisaggRouter, engine_id: int,
                 prefill_timeout: float = 120.0):
        self.engine = engine
        self.queue = queue
        self.transfer = transfer
        self.router = router
        self.engine_id = engine_id
        self.prefill_timeout = prefill_timeout
        # observability
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_fallbacks = 0
        # decode-side view of the remote leg: enqueue → KV landed + first
        # token (queue wait + prefill compute + page transfer), the
        # disagg-vs-agg transfer-overhead breakdown the reference's
        # "+30%/GPU" claim hides (docs/architecture.md:57-61)
        self.remote_wait_total_s = 0.0

    def stats(self) -> dict:
        s = dict(self.engine.stats())
        s.update(remote_prefills=self.remote_prefills,
                 local_prefills=self.local_prefills,
                 remote_fallbacks=self.remote_fallbacks,
                 remote_wait_total_s=round(self.remote_wait_total_s, 3),
                 remote_prefill_wait_seconds_total=round(
                     self.remote_wait_total_s, 3))
        # transfer-plane ingest counters (streaming chunk pipeline) — fed
        # into ForwardPassMetrics for the Prometheus gauges
        s.update(self.transfer.stats())
        return s

    async def generate(self, request, context: Context
                       ) -> AsyncIterator[EngineOutput]:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.from_dict(request)
        tokens = request.token_ids

        # short prompts can never go remote (prefill_len - hit <= prefill_len
        # <= threshold), so skip the reservation churn on the hot path
        res = None
        if (self.router.enabled
                and len(tokens) > self.router.max_local_prefill_length):
            res = await self.engine.reserve_remote(tokens)

        seq = None
        try:
            remote = False
            if res is not None:
                depth = await self.queue.depth()
                remote = self.router.prefill_remote(len(tokens),
                                                    res.cached_tokens, depth)
            if not remote:
                if res is not None:
                    # drop ownership before awaiting: a cancellation landing
                    # at the await must not re-release in the finally block
                    pages, res = res.pages, None
                    await self.engine.release_pages(pages)
                self.local_prefills += 1
                async for out in self.engine.generate(request, context):
                    yield out
                return

            self.remote_prefills += 1
            first = await self._remote_prefill(request, context, res)
            if first is None:  # remote failed/timed out → local fallback
                self.remote_fallbacks += 1
                pages, res = res.pages, None
                await self.engine.release_pages(pages)
                if context.stopped:
                    yield EngineOutput(finish_reason=FINISH_CANCELLED)
                    return
                log.warning("remote prefill fell back to local for %s",
                            context.id)
                async for out in self.engine.generate(request, context):
                    yield out
                return

            seq = await self.engine.submit_prefilled(request, context,
                                                     res.pages, first)
            res = None  # ownership passed to the sequence
        finally:
            if res is not None and seq is None:
                # a failure between reserve and handoff must not leak pages
                await self.engine.release_pages(res.pages)

        while True:
            out: EngineOutput = await seq.out.get()
            yield out
            if out.finish_reason is not None:
                return

    async def _remote_prefill(self, request: PreprocessedRequest,
                              context: Context, res) -> Optional[int]:
        """Enqueue + await the KV arrival; returns the first token or None."""
        import time as _time

        t0 = _time.monotonic()
        fut = self.transfer.expect(context.id)
        await self.queue.put(RemotePrefillRequest(
            request_id=context.id,
            token_ids=list(request.token_ids),
            sampling=request.sampling.to_dict(),
            eos_token_ids=list(request.eos_token_ids),
            page_ids=list(res.pages),
            skip_pages=res.skip_pages,
            engine_id=self.engine_id,
        ))
        try:
            first = await asyncio.wait_for(fut, self.prefill_timeout)
            self.remote_wait_total_s += _time.monotonic() - t0
            return first
        except asyncio.TimeoutError:
            self.transfer.cancel(context.id)
            return None
        except asyncio.CancelledError:
            # handler task cancelled — cancel the waiter and propagate;
            # generate()'s finally releases the reserved pages
            self.transfer.cancel(context.id)
            raise
        except Exception as exc:  # noqa: BLE001
            # a failed stream sets this exception on the waiter the moment
            # the transfer plane knows (ingest error, sender abort, conn
            # drop) — falling back NOW instead of idling out the full
            # prefill_timeout
            log.warning("remote prefill failed for %s (%s); falling back "
                        "to local", context.id, exc)
            self.transfer.cancel(context.id)
            return None


async def build_disagg_decode(drt, engine, *, namespace: str = "dynamo",
                              model: str = "default",
                              router: Optional[DisaggRouter] = None,
                              watch_config: bool = True
                              ) -> DisaggDecodeEngine:
    """Wire the decode side: transfer listener (registered under the
    worker's lease), prefill queue handle, router with live config watch."""
    router = router or DisaggRouter()
    if watch_config:
        await router.start_watch(drt.dcp, namespace, model)
    transfer = KvTransferServer(engine)
    await transfer.start()
    await transfer.register(drt.dcp, namespace, drt.instance_id,
                            lease=drt.primary_lease)
    queue = PrefillQueue(drt.dcp, namespace)
    return DisaggDecodeEngine(engine, queue, transfer, router,
                              drt.instance_id)
