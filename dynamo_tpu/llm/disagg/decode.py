"""Decode-side disaggregation orchestration.

Reference examples/llm/components/worker.py:37-189 (VllmWorker): per
request, consult the disagg router with (prefill_length, prefix_hit);
remote → allocate decode-side KV blocks, enqueue a RemotePrefillRequest,
wait for the prefill worker's block write + completion notification, then
continue decoding locally. Falls back to fully local prefill whenever the
pool is exhausted, the queue is saturated, or the remote path errors.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Optional

from ...runtime import guard, tracing
from ...runtime.config import env_float, env_int
from ...runtime.engine import Context
from ..protocols.common import (FINISH_CANCELLED, FINISH_ERROR, EngineOutput,
                                PreprocessedRequest)
from .protocols import RemotePrefillRequest
from .queue import PrefillQueue
from .router import DisaggRouter
from .transfer import KvTransferServer

log = logging.getLogger("dynamo_tpu.llm.disagg")


async def _drain_seq(seq) -> AsyncIterator[EngineOutput]:
    """Engine-sequence queue → chunk stream (remote-prefill decode leg)."""
    while True:
        out: EngineOutput = await seq.out.get()
        yield out
        if out.finish_reason is not None:
            return


class DisaggDecodeEngine:
    """AsyncEngine wrapper adding conditional remote prefill to a JaxEngine.

    Serves the same token-level protocol, so it drops into serve_token_model
    / the Backend pipeline unchanged.
    """

    def __init__(self, engine, queue: PrefillQueue, transfer: KvTransferServer,
                 router: DisaggRouter, engine_id: int,
                 prefill_timeout: Optional[float] = None,
                 max_dispatches: Optional[int] = None):
        self.engine = engine
        if hasattr(engine, "set_role"):
            # dynaslo: the wrapped engine serves the decode side of the
            # disagg split — its TTFT/ITL histograms merge under
            # role="decode" fleet-wide
            engine.set_role("decode")
        self.queue = queue
        self.transfer = transfer
        self.router = router
        self.engine_id = engine_id
        self.prefill_timeout = prefill_timeout if prefill_timeout is not None \
            else (env_float("DYN_PREFILL_TIMEOUT", 120.0) or 120.0)
        # hedged re-dispatch: when the transfer plane fails FAST (prefill
        # worker died mid-transfer, severed conn) and budget remains, the
        # job is re-enqueued to the shared queue — another worker picks it
        # up — before giving up and falling back to local prefill. A slow
        # timeout never re-dispatches (the budget is already spent).
        self.max_dispatches = max(1, max_dispatches if max_dispatches
                                  is not None
                                  else (env_int("DYN_REDISPATCH_MAX", 2)
                                        or 1))
        # observability
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_fallbacks = 0
        self.redispatches = 0
        # decode-side view of the remote leg: enqueue → KV landed + first
        # token (queue wait + prefill compute + page transfer), the
        # disagg-vs-agg transfer-overhead breakdown the reference's
        # "+30%/GPU" claim hides (docs/architecture.md:57-61)
        self.remote_wait_total_s = 0.0

    def stats(self) -> dict:
        s = dict(self.engine.stats())
        s.update(remote_prefills=self.remote_prefills,
                 local_prefills=self.local_prefills,
                 remote_fallbacks=self.remote_fallbacks,
                 remote_redispatches=self.redispatches,
                 remote_wait_total_s=round(self.remote_wait_total_s, 3),
                 remote_prefill_wait_seconds_total=round(
                     self.remote_wait_total_s, 3))
        # transfer-plane ingest counters (streaming chunk pipeline) — fed
        # into ForwardPassMetrics for the Prometheus gauges
        s.update(self.transfer.stats())
        return s

    async def generate(self, request, context: Context
                       ) -> AsyncIterator[EngineOutput]:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.from_dict(request)
        tokens = request.token_ids
        tracer = tracing.get_tracer()

        # short prompts can never go remote (prefill_len - hit <= prefill_len
        # <= threshold), so skip the reservation churn on the hot path
        res = None
        if (self.router.enabled
                and len(tokens) > self.router.max_local_prefill_length):
            res = await self.engine.reserve_remote(tokens)

        seq = None
        try:
            remote = False
            depth = None
            with tracer.start_span("route.disagg", attributes={
                    "prefill_len": len(tokens)}) as rsp:
                if res is not None:
                    depth = await self.queue.depth()
                    remote = self.router.prefill_remote(
                        len(tokens), res.cached_tokens, depth)
                    rsp.set_attribute("cached_tokens", res.cached_tokens)
                    rsp.set_attribute("queue_depth", depth)
                rsp.set_attribute("remote", remote)
            if not remote:
                if res is not None:
                    # drop ownership before awaiting: a cancellation landing
                    # at the await must not re-release in the finally block
                    pages, res = res.pages, None
                    await self.engine.release_pages(pages)
                self.local_prefills += 1
                dsp = tracer.start_span("decode",
                                        attributes={"mode": "local"})
                async for out in self._traced(
                        dsp, self.engine.generate(request, context),
                        request.stop.max_tokens):
                    yield out
                return

            self.remote_prefills += 1
            with tracer.start_span("prefill.remote", attributes={
                    "queue_depth": depth,
                    "skip_pages": res.skip_pages}) as psp:
                first = await self._remote_prefill(request, context, res)
                psp.set_attribute("ok", first is not None)
            if first is None:  # remote failed/timed out → local fallback
                self.remote_fallbacks += 1
                pages, res = res.pages, None
                await self.engine.release_pages(pages)
                if context.stopped:
                    # deadline expiry surfaces as "timeout", caller
                    # cancellation as "cancelled"
                    yield EngineOutput(
                        finish_reason=context.cancel_reason())
                    return
                log.warning("remote prefill fell back to local for %s",
                            context.id)
                dsp = tracer.start_span("decode", attributes={
                    "mode": "local_fallback"})
                async for out in self._traced(
                        dsp, self.engine.generate(request, context),
                        request.stop.max_tokens):
                    yield out
                return

            seq = await self.engine.submit_prefilled(request, context,
                                                     res.pages, first)
            res = None  # ownership passed to the sequence
        finally:
            if res is not None and seq is None:
                # a failure between reserve and handoff must not leak pages
                await self.engine.release_pages(res.pages)

        dsp = tracer.start_span("decode", attributes={
            "mode": "remote_prefill"})
        async for out in self._traced(dsp, _drain_seq(seq),
                                      request.stop.max_tokens):
            yield out

    async def _traced(self, dsp, stream, max_tokens):
        """Relay ``stream`` under the decode span ``dsp``, ending the span
        the moment the request is observably finished — a finish chunk OR
        the token budget reached. The budget mirror matters: downstream
        (Backend) stamps max_tokens itself and abandons this generator
        right after the last token chunk, so a span ended only by the
        engine's finish chunk would linger until GC-time aclose."""
        n_out = 0
        try:
            async for out in stream:
                n_out += len(out.token_ids)
                if out.finish_reason is not None or (
                        max_tokens is not None and n_out >= max_tokens):
                    dsp.set_attribute("tokens", n_out)
                    if out.finish_reason is not None:
                        dsp.set_attribute("finish", out.finish_reason)
                    dsp.end()  # idempotent; before the abandonable yield
                yield out
        finally:
            dsp.end()

    async def _remote_prefill(self, request: PreprocessedRequest,
                              context: Context, res) -> Optional[int]:
        """Enqueue + await the KV arrival; returns the first token or None.

        The wait is bounded by ``min(prefill_timeout, request deadline)``.
        A FAST failure (the transfer plane fails the waiter: prefill
        worker died mid-transfer, severed connection, ingest error) is
        hedged: while dispatches and budget remain, the job is re-enqueued
        to the shared queue for another worker. A timeout — budget already
        burned — falls straight back to local prefill."""
        import time as _time

        t0 = _time.monotonic()
        deadline = context.deadline
        for dispatch in range(self.max_dispatches):
            fut = self.transfer.expect(context.id)
            await self.queue.put(RemotePrefillRequest(
                request_id=context.id,
                token_ids=list(request.token_ids),
                sampling=request.sampling.to_dict(),
                eos_token_ids=list(request.eos_token_ids),
                page_ids=list(res.pages),
                skip_pages=res.skip_pages,
                engine_id=self.engine_id,
                # join the prefill worker's spans to this request's trace
                # (None when not sampled → field absent on the wire)
                trace_ctx=tracing.get_tracer().current_trace_ctx(),
                # remaining budget travels with the job (absent = none)
                deadline_ms=(deadline.to_wire_ms()
                             if deadline is not None else None),
            ))
            try:
                first = await guard.bound(fut, timeout=self.prefill_timeout,
                                          deadline=deadline,
                                          what="remote prefill")
                self.remote_wait_total_s += _time.monotonic() - t0
                return first
            except asyncio.TimeoutError:
                # covers DeadlineExceeded too: the budget is spent (or
                # the prefill pool is too slow) — no hedge, fall back
                self.transfer.cancel(context.id)
                return None
            except asyncio.CancelledError:
                # handler task cancelled — cancel the waiter and propagate;
                # generate()'s finally releases the reserved pages
                self.transfer.cancel(context.id)
                raise
            except Exception as exc:  # noqa: BLE001
                # fail-fast signal from the transfer plane: hedge if a
                # dispatch remains and the budget can still cover work
                self.transfer.cancel(context.id)
                if dispatch + 1 < self.max_dispatches and \
                        not (deadline is not None and deadline.expired):
                    self.redispatches += 1
                    guard.counter_inc("dyn_guard_hedged_redispatch_total")
                    log.warning("remote prefill for %s failed fast (%s); "
                                "re-enqueueing (dispatch %d/%d)",
                                context.id, exc, dispatch + 2,
                                self.max_dispatches)
                    continue
                log.warning("remote prefill failed for %s (%s); falling "
                            "back to local", context.id, exc)
                return None
        return None


async def build_disagg_decode(drt, engine, *, namespace: str = "dynamo",
                              model: str = "default",
                              router: Optional[DisaggRouter] = None,
                              watch_config: bool = True
                              ) -> DisaggDecodeEngine:
    """Wire the decode side: transfer listener (registered under the
    worker's lease), prefill queue handle, router with live config watch."""
    router = router or DisaggRouter()
    if watch_config:
        await router.start_watch(drt.dcp, namespace, model)
    transfer = KvTransferServer(engine)
    await transfer.start()
    await transfer.register(drt.dcp, namespace, drt.instance_id,
                            lease=drt.primary_lease)
    queue = PrefillQueue(drt.dcp, namespace)
    return DisaggDecodeEngine(engine, queue, transfer, router,
                              drt.instance_id)
