"""Conditional-disaggregation decision + live reconfiguration.

Reference lib/llm/src/disagg_router.rs: remote prefill iff
``prefill_length - prefix_hit_length > max_local_prefill_length`` (decision
:239-249), with the threshold live-reconfigurable via an etcd watch on
``public/components/disagg_router/models/chat/<model>`` (:38-141). Here the
watch runs against the DCP KV store.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from ...runtime.dcp_client import DcpClient

log = logging.getLogger("dynamo_tpu.llm.disagg")


def config_key(namespace: str, model: str) -> str:
    return f"{namespace}/disagg_router/models/{model}"


class DisaggRouter:
    def __init__(self, max_local_prefill_length: int = 512,
                 max_prefill_queue_size: Optional[int] = None,
                 enabled: bool = True):
        self.max_local_prefill_length = max_local_prefill_length
        self.max_prefill_queue_size = max_prefill_queue_size
        self.enabled = enabled
        self._watch_task: Optional[asyncio.Task] = None

    def prefill_remote(self, prefill_length: int, prefix_hit_length: int,
                       queue_depth: int = 0) -> bool:
        """True → enqueue a remote prefill; False → prefill locally."""
        if not self.enabled:
            return False
        if (self.max_prefill_queue_size is not None
                and queue_depth >= self.max_prefill_queue_size):
            return False  # queue saturated: keep it local (backpressure)
        return (prefill_length - prefix_hit_length
                > self.max_local_prefill_length)

    # ------------------------------------------------------- live reconfig

    async def start_watch(self, dcp: DcpClient, namespace: str,
                          model: str) -> None:
        """Apply + follow threshold updates published at config_key()."""
        key = config_key(namespace, model)
        items, watch = await dcp.kv_watch_prefix(key)
        for item in items:
            self._apply(item.value)

        async def _loop():
            async for ev in watch:
                if ev.event == "put" and ev.value is not None:
                    self._apply(ev.value)

        self._watch_task = asyncio.ensure_future(_loop())

    def _apply(self, raw: bytes) -> None:
        try:
            cfg = json.loads(raw)
        except (ValueError, TypeError):
            log.warning("ignoring malformed disagg config: %r", raw[:100])
            return
        if "max_local_prefill_length" in cfg:
            self.max_local_prefill_length = int(cfg["max_local_prefill_length"])
        if "max_prefill_queue_size" in cfg:
            v = cfg["max_prefill_queue_size"]
            self.max_prefill_queue_size = None if v is None else int(v)
        if "enabled" in cfg:
            self.enabled = bool(cfg["enabled"])
        log.info("disagg router reconfigured: threshold=%d queue_max=%s "
                 "enabled=%s", self.max_local_prefill_length,
                 self.max_prefill_queue_size, self.enabled)

    def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
            self._watch_task = None


async def publish_config(dcp: DcpClient, namespace: str, model: str,
                         **cfg) -> None:
    """Operator-side helper: update the live disagg config (the llmctl-style
    write the reference does via etcd)."""
    await dcp.kv_put(config_key(namespace, model), json.dumps(cfg).encode())
