"""The prefill worker: pull queue → prefill → stream KV pages.

Reference examples/llm/components/prefill_worker.py:37-141: pulls the
JetStream prefill queue, lazily fetches the decode engine's NIXL metadata
from etcd on first contact, runs a max_tokens=1 generate, and RDMA-writes
the computed blocks into decode VRAM. Here: DCP work queue, DCP-stored TCP
endpoints, engine.prefill_only + a chunked extract→compress→send pipeline
(transfer.py streaming protocol) so the device→host extract of chunk i+1
overlaps the socket write of chunk i — decode-side TTFT stops being the
sum of prefill + extract + wire + inject.

Elastic xPyD: any number of prefill workers pull the one shared queue;
joining/leaving needs no coordination (docs/disagg_serving.md:93-100).
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from typing import Dict, List, Optional, Set

import numpy as np

from ...runtime import guard, tracing
from ...runtime.engine import Context
from ..protocols.common import (PreprocessedRequest, SamplingOptions,
                                StopConditions)
from .protocols import RemotePrefillRequest
from .queue import PrefillQueue
from .transfer import KvTransferClient, TransferStats

log = logging.getLogger("dynamo_tpu.llm.disagg")

DEFAULT_CHUNK_PAGES = 4


class PrefillWorker:
    def __init__(self, drt, engine, *, namespace: str = "dynamo",
                 max_inflight: int = 4,
                 compress_kv: Optional[bool] = None,
                 chunk_pages: Optional[int] = None):
        from ...runtime.config import env_bool, env_int

        self.drt = drt
        self.engine = engine
        if hasattr(engine, "set_role"):
            # dynaslo: this engine serves prefill-only — its latency
            # histograms (queue wait of pulled jobs, prefill-side
            # timings) merge under role="prefill" fleet-wide
            engine.set_role("prefill")
        self.namespace = namespace
        # int8-compress shipped pages (~half the DCN bytes; lossy —
        # engine/kv_compress.py). Opt-in: arg, else DYN_KV_TRANSFER_INT8
        self.compress_kv = (compress_kv if compress_kv is not None
                            else env_bool("DYN_KV_TRANSFER_INT8"))
        # pages per streamed chunk frame; 0 = legacy single bulk frame.
        # Arg, else DYN_KV_TRANSFER_CHUNK_PAGES, else the default.
        if chunk_pages is None:
            chunk_pages = env_int("DYN_KV_TRANSFER_CHUNK_PAGES",
                                  DEFAULT_CHUNK_PAGES)
        self.chunk_pages = max(int(chunk_pages), 0)
        self.queue = PrefillQueue(drt.dcp, namespace)
        self.max_inflight = max_inflight
        self._clients: Dict[int, KvTransferClient] = {}
        self._tasks: Set[asyncio.Task] = set()
        self._run_task: Optional[asyncio.Task] = None
        self._stopped = False
        self.completed = 0
        self.failed = 0
        self.expired = 0            # jobs dropped: budget spent in-queue
        self.client_evictions = 0
        # shared retry/breaker plane (replaces the PR 2 ad-hoc
        # evict-and-retry-once): sends to a decode engine run under the
        # RetryPolicy (budget-aware), and a per-engine circuit breaker
        # fails jobs fast while an engine's transfer endpoint stays dead
        self.retry = guard.RetryPolicy.from_env()
        self.breakers = guard.BreakerBoard(f"prefill-worker:{namespace}")
        # per-stage transfer-pipeline accounting, shared by all clients
        self.xfer = TransferStats()

    def start(self) -> None:
        if self._run_task is None:
            self._run_task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._run_task:
            self._run_task.cancel()
            try:
                await self._run_task
            except asyncio.CancelledError:
                pass
        for t in list(self._tasks):
            t.cancel()
        for c in self._clients.values():
            c.close()

    async def _run(self) -> None:
        while not self._stopped:
            try:
                if len(self._tasks) >= self.max_inflight:
                    await asyncio.wait(self._tasks,
                                       return_when=asyncio.FIRST_COMPLETED)
                    continue
                req = await self.queue.pull(timeout=0.5)
                if req is None:
                    continue
                task = asyncio.ensure_future(self._handle(req))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — a DCP hiccup must not
                log.exception("prefill pull loop error; retrying")  # kill us
                await asyncio.sleep(1.0)

    async def _handle(self, req: RemotePrefillRequest) -> None:
        """One remote prefill: compute, extract the non-cached pages, ship."""
        pages = None
        tracing.bind_request_id(req.request_id)
        tracer = tracing.get_tracer()
        # rebuild the job's deadline against this host's clock (absent on
        # the wire = no deadline); a job whose budget died in the queue is
        # dropped outright — the decode side has already fallen back
        deadline = guard.Deadline.from_wire_ms(req.deadline_ms)
        if deadline is not None and deadline.expired:
            self.expired += 1
            log.warning("dropping expired remote prefill job %s "
                        "(budget spent in queue)", req.request_id)
            return
        try:
            pre = PreprocessedRequest(
                token_ids=list(req.token_ids),
                sampling=SamplingOptions.from_dict(req.sampling),
                stop=StopConditions(max_tokens=1),
                eos_token_ids=list(req.eos_token_ids),
            )
            ctx = Context(req.request_id)
            # parent = the decode-side request's trace (trace_ctx rides the
            # queue); None roots a worker-local trace instead
            with tracer.start_span(
                    "prefill.forward", parent=req.trace_ctx,
                    attributes={"tokens": len(req.token_ids)},
                    request_id=req.request_id) as fsp:
                first, pages = await self.engine.prefill_only(pre, ctx)
                fsp.set_attribute("pages", len(pages))

            if deadline is not None and deadline.expired:
                # budget died during the prefill compute: shipping now
                # cannot beat the decode side's (already fired) fallback —
                # drop instead of racing a doomed transfer
                self.expired += 1
                log.warning("dropping remote prefill job %s after compute "
                            "(budget spent)", req.request_id)
                return
            ps = self.engine.ecfg.page_size
            n_prompt_pages = math.ceil(len(req.token_ids) / ps)
            local_send = pages[req.skip_pages:n_prompt_pages]
            remote_dst = req.page_ids[req.skip_pages:n_prompt_pages]
            await self._send(req, local_send, remote_dst, first, deadline)
            self.completed += 1
        except Exception:  # noqa: BLE001 — a bad job must not kill the loop
            self.failed += 1
            log.exception("remote prefill job %s failed (decode side will "
                          "fall back)", req.request_id)
        finally:
            if pages is not None:
                await self.engine.release_pages(pages)

    async def _send(self, req: RemotePrefillRequest, local_send: List[int],
                    remote_dst: List[int], first: int,
                    deadline: Optional[guard.Deadline] = None) -> None:
        """Ship the pages, surviving a decode-worker restart: the cached
        client may point at a dead host:port, so each failed attempt
        evicts it, re-resolves the endpoint from DCP, and retries with a
        fresh connection under the shared RetryPolicy (budget-aware —
        never past the job's deadline). A per-engine circuit breaker
        fails jobs fast while an engine's endpoint stays dead. Stage
        times accumulate into a per-send TransferStats (exact per-request
        trace spans) and fold into the shared ``self.xfer`` totals
        afterwards."""
        tracer = tracing.get_tracer()
        per = TransferStats()
        br = self.breakers.get("transfer", req.engine_id)
        span = tracer.start_span(
            "kv_transfer.send", parent=req.trace_ctx,
            attributes={"engine_id": f"{req.engine_id:x}",
                        "pages": len(local_send),
                        "chunk_pages": self.chunk_pages})
        try:
            with span:
                if not br.allow():
                    raise guard.NoCapacity(
                        f"transfer endpoint for engine {req.engine_id:x} "
                        f"is circuit-broken")
                last: Optional[BaseException] = None
                sent = False
                async for _attempt in self.retry.attempts(deadline):
                    client = await self._client(req.engine_id)
                    try:
                        await self._send_once(client, req, local_send,
                                              remote_dst, first, per,
                                              deadline)
                        br.record_success()
                        sent = True
                        break
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:  # noqa: BLE001 — retry fresh
                        self._evict(req.engine_id, client)
                        self.client_evictions += 1
                        last = exc
                        log.warning("KV send for %s to engine %x failed "
                                    "(%s); re-resolving endpoint and "
                                    "retrying within budget",
                                    req.request_id, req.engine_id, exc)
                if not sent:
                    br.record_failure()
                    raise last if last is not None else \
                        guard.DeadlineExceeded(
                            f"no budget left to send KV for "
                            f"{req.request_id}")
                span.set_attribute("bytes", per.bytes_sent)
                span.set_attribute("chunks", per.chunks_sent)
                # adopt the measured stage accumulators as child spans
                # (stages overlap, so siblings legitimately sum past the
                # parent's wall — that inequality IS the pipelining)
                for stage, secs in (("extract", per.extract_seconds),
                                    ("compress", per.compress_seconds),
                                    ("wire", per.wire_seconds),
                                    ("ack_wait", per.ack_wait_seconds)):
                    if secs > 0:
                        tracer.record_span(f"kv_transfer.{stage}", secs,
                                           parent=span)
        finally:
            self.xfer.merge(per)

    async def _send_once(self, client: KvTransferClient,
                         req: RemotePrefillRequest, local_send: List[int],
                         remote_dst: List[int], first: int,
                         stats: TransferStats,
                         deadline: Optional[guard.Deadline] = None) -> None:
        # the decode side's commit-ack wait is capped by the remaining
        # request budget (None → the registered default)
        timeout = None if deadline is None else max(deadline.cap(None), 0.05)
        cp = self.chunk_pages
        if cp and local_send:
            n_chunks = math.ceil(len(local_send) / cp)
            frames = self._frames(local_send, remote_dst, cp, stats)
            await client.send_kv_chunked(req.request_id, n_chunks, frames,
                                         first, timeout=timeout, stats=stats)
        else:
            t0 = time.monotonic()
            k, v = await self.engine.extract_pages(local_send)
            dt = time.monotonic() - t0
            stats.extract_seconds += dt
            # bulk runs extract BEFORE the send; count it into the wall so
            # the stage-sum-vs-wall overlap comparison is apples-to-apples
            # with the chunked pipeline (whose wall covers extraction)
            stats.wall_seconds += dt
            await client.send_kv(req.request_id, remote_dst, k, v, first,
                                 timeout=timeout,
                                 compress=self.compress_kv, stats=stats)

    async def _frames(self, local_send: List[int], remote_dst: List[int],
                      cp: int, stats: TransferStats):
        """Chunk producer for the streaming protocol: ranged device→host
        extract (pipelined inside the engine) + optional int8 compression
        off the event loop. The client consumes this one chunk ahead, so
        this body runs under the previous chunk's socket write."""
        loop = asyncio.get_running_loop()
        async for off, k, v, dt in self.engine.extract_pages_chunked(
                local_send, cp):
            stats.extract_seconds += dt
            dst = remote_dst[off:off + cp]
            k = np.ascontiguousarray(k)
            v = np.ascontiguousarray(v)
            extra = {"shape": list(k.shape), "dtype": str(k.dtype),
                     "k_len": k.nbytes}
            if self.compress_kv:
                from ...engine.kv_compress import quantize_pages_np

                t0 = time.monotonic()
                kq, ks = await loop.run_in_executor(None, quantize_pages_np,
                                                    k)
                vq, vs = await loop.run_in_executor(None, quantize_pages_np,
                                                    v)
                stats.compress_seconds += time.monotonic() - t0
                extra.update(quant="int8", k_len=kq.nbytes)
                yield dst, extra, [kq, vq, ks, vs], (kq.nbytes + vq.nbytes
                                                     + ks.nbytes + vs.nbytes)
            else:
                yield dst, extra, [k, v], k.nbytes + v.nbytes

    async def _client(self, engine_id: int) -> KvTransferClient:
        client = self._clients.get(engine_id)
        if client is not None:
            return client
        client = await KvTransferClient.lookup(self.drt.dcp,
                                               self.namespace, engine_id,
                                               stats=self.xfer)
        # re-check after the lookup await: a concurrent job for the same
        # engine may have resolved it first — without this, the loser
        # clobbers the cache and the winner's connection leaks
        cached = self._clients.get(engine_id)
        if cached is not None:
            client.close()
            return cached
        self._clients[engine_id] = client
        return client

    def _evict(self, engine_id: int, client: Optional[KvTransferClient]
               ) -> None:
        cached = self._clients.get(engine_id)
        if cached is not None and (client is None or cached is client):
            del self._clients[engine_id]
        if client is not None:
            client.close()

    def stats(self) -> dict:
        return {"inflight": len(self._tasks), "completed": self.completed,
                "failed": self.failed, "expired_jobs": self.expired,
                "client_evictions": self.client_evictions,
                "transfer_breakers_open":
                    len(self.breakers.not_closed("transfer")),
                "chunk_pages": self.chunk_pages,
                **{f"kv_send_{k}": v for k, v in self.xfer.to_dict().items()}}
