"""The prefill worker: pull queue → prefill → push KV pages.

Reference examples/llm/components/prefill_worker.py:37-141: pulls the
JetStream prefill queue, lazily fetches the decode engine's NIXL metadata
from etcd on first contact, runs a max_tokens=1 generate, and RDMA-writes
the computed blocks into decode VRAM. Here: DCP work queue, DCP-stored TCP
endpoints, engine.prefill_only + extract_pages, TwoPartCodec page push.

Elastic xPyD: any number of prefill workers pull the one shared queue;
joining/leaving needs no coordination (docs/disagg_serving.md:93-100).
"""

from __future__ import annotations

import asyncio
import logging
import math
from typing import Dict, Optional, Set

from ...runtime.engine import Context
from ..protocols.common import (PreprocessedRequest, SamplingOptions,
                                StopConditions)
from .protocols import RemotePrefillRequest
from .queue import PrefillQueue
from .transfer import KvTransferClient

log = logging.getLogger("dynamo_tpu.llm.disagg")


class PrefillWorker:
    def __init__(self, drt, engine, *, namespace: str = "dynamo",
                 max_inflight: int = 4,
                 compress_kv: Optional[bool] = None):
        import os

        self.drt = drt
        self.engine = engine
        self.namespace = namespace
        # int8-compress shipped pages (~half the DCN bytes; lossy —
        # engine/kv_compress.py). Opt-in: arg, else DYN_KV_TRANSFER_INT8
        self.compress_kv = (compress_kv if compress_kv is not None
                            else os.environ.get("DYN_KV_TRANSFER_INT8",
                                                "") == "1")
        self.queue = PrefillQueue(drt.dcp, namespace)
        self.max_inflight = max_inflight
        self._clients: Dict[int, KvTransferClient] = {}
        self._tasks: Set[asyncio.Task] = set()
        self._run_task: Optional[asyncio.Task] = None
        self._stopped = False
        self.completed = 0
        self.failed = 0

    def start(self) -> None:
        if self._run_task is None:
            self._run_task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._run_task:
            self._run_task.cancel()
            try:
                await self._run_task
            except asyncio.CancelledError:
                pass
        for t in list(self._tasks):
            t.cancel()
        for c in self._clients.values():
            c.close()

    async def _run(self) -> None:
        while not self._stopped:
            try:
                if len(self._tasks) >= self.max_inflight:
                    await asyncio.wait(self._tasks,
                                       return_when=asyncio.FIRST_COMPLETED)
                    continue
                req = await self.queue.pull(timeout=0.5)
                if req is None:
                    continue
                task = asyncio.ensure_future(self._handle(req))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — a DCP hiccup must not
                log.exception("prefill pull loop error; retrying")  # kill us
                await asyncio.sleep(1.0)

    async def _handle(self, req: RemotePrefillRequest) -> None:
        """One remote prefill: compute, extract the non-cached pages, ship."""
        pages = None
        try:
            pre = PreprocessedRequest(
                token_ids=list(req.token_ids),
                sampling=SamplingOptions.from_dict(req.sampling),
                stop=StopConditions(max_tokens=1),
                eos_token_ids=list(req.eos_token_ids),
            )
            ctx = Context(req.request_id)
            first, pages = await self.engine.prefill_only(pre, ctx)

            ps = self.engine.ecfg.page_size
            n_prompt_pages = math.ceil(len(req.token_ids) / ps)
            local_send = pages[req.skip_pages:n_prompt_pages]
            remote_dst = req.page_ids[req.skip_pages:n_prompt_pages]
            k, v = await self.engine.extract_pages(local_send)

            client = await self._client(req.engine_id)
            await client.send_kv(req.request_id, remote_dst, k, v, first,
                                 compress=self.compress_kv)
            self.completed += 1
        except Exception:  # noqa: BLE001 — a bad job must not kill the loop
            self.failed += 1
            log.exception("remote prefill job %s failed (decode side will "
                          "fall back on timeout)", req.request_id)
        finally:
            if pages is not None:
                await self.engine.release_pages(pages)

    async def _client(self, engine_id: int) -> KvTransferClient:
        client = self._clients.get(engine_id)
        if client is None:
            client = await KvTransferClient.lookup(self.drt.dcp,
                                                   self.namespace, engine_id)
            self._clients[engine_id] = client
        return client

    def stats(self) -> dict:
        return {"inflight": len(self._tasks), "completed": self.completed,
                "failed": self.failed}
