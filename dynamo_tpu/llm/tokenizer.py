"""Tokenizer abstraction + incremental detokenization.

Reference lib/llm/src/tokenizers.rs: ``Tokenizer`` trait over the HF
tokenizers crate with ``Encoding``, ``DecodeStream::step`` (incremental,
UTF-8-safe detokenization) and ``Sequence`` append. Here:

- ``HFTokenizer`` — wraps ``transformers.AutoTokenizer`` loaded from a LOCAL
  path (offline; the serving path never hits the network).
- ``ByteTokenizer`` — deterministic 256-byte-vocab tokenizer with BOS/EOS/PAD
  specials. The framework's analog of the reference's GPU-free test plan
  (echo engines, SURVEY §4): fully functional encode/decode for CI and
  benches with no tokenizer artifacts.
- ``DecodeStream`` — incremental decoding that withholds bytes until they
  form complete UTF-8 (the \\ufffd-guard technique).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ..runtime.config import env_set_default

env_set_default("HF_HUB_OFFLINE", "1")
env_set_default("TRANSFORMERS_OFFLINE", "1")

DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message.role }}|>\n{{ message.content }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


class Tokenizer:
    """Base interface. ``encode``/``decode`` plus chat templating."""

    eos_token_ids: List[int] = []
    bos_token_id: Optional[int] = None
    vocab_size: int = 0

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        raise NotImplementedError

    def apply_chat_template(self, messages: List[dict],
                            add_generation_prompt: bool = True) -> str:
        import jinja2

        tpl = jinja2.Environment(keep_trailing_newline=True).from_string(
            self.chat_template())
        return tpl.render(messages=messages,
                          add_generation_prompt=add_generation_prompt,
                          bos_token="", eos_token="")

    def chat_template(self) -> str:
        return DEFAULT_CHAT_TEMPLATE

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens)


class ByteTokenizer(Tokenizer):
    """Bytes 0..255 are tokens 0..255; PAD=256, BOS=257, EOS=258.

    vocab_size is padded to 512 so test models get TPU-friendly shapes.
    """

    PAD, BOS, EOS = 256, 257, 258

    def __init__(self, vocab_size: int = 512):
        self.vocab_size = vocab_size
        self.eos_token_ids = [self.EOS]
        self.bos_token_id = self.BOS
        self.pad_token_id = self.PAD

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        if add_special_tokens:
            ids = [self.BOS] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        raw = bytes(i for i in ids if i < 256)
        return raw.decode("utf-8", errors="replace")

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        return bytes(i for i in ids if i < 256)


class HFTokenizer(Tokenizer):
    """HuggingFace tokenizer from a local directory (tokenizer.json et al.).

    Reference TokenizerKind::HfTokenizerJson (model_card/model.rs).
    """

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self.path = path
        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        eos = self._tok.eos_token_id
        self.eos_token_ids = ([eos] if isinstance(eos, int) else list(eos or []))
        # generation_config may add more eos ids (e.g. Llama-3 eot_id)
        gen_cfg = os.path.join(path, "generation_config.json")
        if os.path.exists(gen_cfg):
            import json

            with open(gen_cfg) as f:
                g = json.load(f)
            extra = g.get("eos_token_id")
            if isinstance(extra, int):
                extra = [extra]
            for e in extra or []:
                if e not in self.eos_token_ids:
                    self.eos_token_ids.append(e)
        self.bos_token_id = self._tok.bos_token_id

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def chat_template(self) -> str:
        return getattr(self._tok, "chat_template", None) or DEFAULT_CHAT_TEMPLATE

    def apply_chat_template(self, messages: List[dict],
                            add_generation_prompt: bool = True) -> str:
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False,
                add_generation_prompt=add_generation_prompt)
        except Exception:
            return super().apply_chat_template(messages, add_generation_prompt)


class DecodeStream:
    """Incremental, UTF-8-safe detokenization (reference
    tokenizers.rs DecodeStream::step:211).

    Decodes a sliding window and only emits text once it no longer ends in a
    partial multi-byte sequence (detected via the replacement character).
    """

    def __init__(self, tokenizer: Tokenizer, skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: List[int] = []
        self._prefix_offset = 0  # start of the decode window
        self._read_offset = 0    # how much of the window is already emitted

    def step(self, token_id: int) -> str:
        """Feed one token; returns newly-finalized text ('' if held back)."""
        self._ids.append(token_id)
        window = self._ids[self._prefix_offset:]
        text = self._tok.decode(window, self._skip_special)
        if text.endswith("�"):
            return ""  # mid-codepoint; wait for more tokens
        emitted = self._tok.decode(
            self._ids[self._prefix_offset:self._read_offset], self._skip_special)
        new_text = text[len(emitted):]
        # slide the window: keep a small suffix for tokenizers whose decode
        # depends on preceding context (byte-level BPE space handling)
        if len(window) > 16:
            self._prefix_offset = len(self._ids) - 8
        self._read_offset = len(self._ids)
        return new_text

    def flush(self) -> str:
        """Emit anything still held (e.g. trailing partial UTF-8 as U+FFFD)."""
        window = self._ids[self._prefix_offset:]
        text = self._tok.decode(window, self._skip_special)
        emitted = self._tok.decode(
            self._ids[self._prefix_offset:self._read_offset], self._skip_special)
        self._read_offset = len(self._ids)
        return text[len(emitted):]


def load_tokenizer(kind: str, path: Optional[str] = None) -> Tokenizer:
    if kind == "byte":
        return ByteTokenizer()
    if kind == "hf":
        assert path, "hf tokenizer requires a local path"
        return HFTokenizer(path)
    raise ValueError(f"unknown tokenizer kind {kind!r}")
