"""Model Deployment Card (MDC).

Reference lib/llm/src/model_card/model.rs:55-190: the card bundles
everything a frontend/preprocessor needs to serve a model — display name,
tokenizer artifact, prompt/chat template, context length, KV block size —
plus a content checksum (``mdcsum``) so workers and frontends can verify
they agree on preprocessing. Cards are published to the control-plane KV
store (reference stores them in etcd with expiry/refresh).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import List, Optional

import xxhash

from ..runtime.dcp_client import DcpClient, pack, unpack
from .tokenizer import Tokenizer, load_tokenizer

MDC_PREFIX = "mdc/"


@dataclass
class ModelDeploymentCard:
    name: str
    model_path: Optional[str] = None      # local dir with config/weights
    tokenizer_kind: str = "byte"          # "byte" | "hf"
    tokenizer_path: Optional[str] = None
    context_length: int = 8192
    kv_block_size: int = 64               # tokens per KV block/page
    model_type: str = "chat"              # "chat" | "completions" | "both"
    extra: dict = field(default_factory=dict)

    def mdcsum(self) -> str:
        blob = json.dumps(self.to_dict(), sort_keys=True).encode()
        return f"{xxhash.xxh3_64_intdigest(blob):016x}"

    def to_dict(self) -> dict:
        return {
            "name": self.name, "model_path": self.model_path,
            "tokenizer_kind": self.tokenizer_kind,
            "tokenizer_path": self.tokenizer_path,
            "context_length": self.context_length,
            "kv_block_size": self.kv_block_size,
            "model_type": self.model_type, "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ModelDeploymentCard":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})  # type: ignore[attr-defined]

    @classmethod
    def from_local_path(cls, path: str, name: Optional[str] = None,
                        **overrides) -> "ModelDeploymentCard":
        """Build a card from a local HF-style model directory (reference
        model_card/create.rs from_local_path)."""
        name = name or os.path.basename(path.rstrip("/"))
        card = cls(name=name, model_path=path)
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            card.context_length = int(
                cfg.get("max_position_embeddings", card.context_length))
        if os.path.exists(os.path.join(path, "tokenizer.json")) or \
                os.path.exists(os.path.join(path, "tokenizer_config.json")):
            card.tokenizer_kind = "hf"
            card.tokenizer_path = path
        for k, v in overrides.items():
            setattr(card, k, v)
        return card

    def load_tokenizer(self) -> Tokenizer:
        return load_tokenizer(self.tokenizer_kind, self.tokenizer_path)

    # ---------------------------------------------------------- KV publish

    def kv_key(self) -> str:
        return f"{MDC_PREFIX}{self.name}"

    async def publish(self, dcp: DcpClient, lease: int = 0) -> None:
        await dcp.kv_put(self.kv_key(), pack(self.to_dict()), lease=lease)

    @classmethod
    async def load(cls, dcp: DcpClient, name: str) -> Optional["ModelDeploymentCard"]:
        raw = await dcp.kv_get(f"{MDC_PREFIX}{name}")
        return cls.from_dict(unpack(raw)) if raw else None
