"""Model registration entries (the ``llmctl`` plane).

Reference launch/llmctl/src/main.rs + lib/llm/src/http/service/discovery.rs:
a ``ModelEntry {name, endpoint, model_type}`` written to the KV store under
``models/<type>/<name>``; the frontend's model watcher reacts to Put/Delete
by (un)registering engines. ``register_model``/``remove_model`` are the
llmctl verbs (``llmctl http add chat-models <name> <endpoint>``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..runtime.component import EndpointAddress
from ..runtime.dcp_client import DcpClient, pack, unpack

MODEL_PREFIX = "models/"


@dataclass
class ModelEntry:
    name: str
    endpoint: str           # dyn://namespace.component.endpoint
    model_type: str = "chat"  # "chat" | "completions" | "both"

    def kv_key(self) -> str:
        return f"{MODEL_PREFIX}{self.model_type}/{self.name}"

    def to_dict(self) -> dict:
        return {"name": self.name, "endpoint": self.endpoint,
                "model_type": self.model_type}

    @classmethod
    def from_dict(cls, d: dict) -> "ModelEntry":
        return cls(name=d["name"], endpoint=d["endpoint"],
                   model_type=d.get("model_type", "chat"))

    @property
    def address(self) -> EndpointAddress:
        return EndpointAddress.parse(self.endpoint)


async def register_model(dcp: DcpClient, entry: ModelEntry,
                         lease: int = 0) -> None:
    await dcp.kv_put(entry.kv_key(), pack(entry.to_dict()), lease=lease)


async def remove_model(dcp: DcpClient, name: str,
                       model_type: str = "chat") -> bool:
    return await dcp.kv_delete(f"{MODEL_PREFIX}{model_type}/{name}")


async def list_models(dcp: DcpClient) -> List[ModelEntry]:
    items = await dcp.kv_get_prefix(MODEL_PREFIX)
    return [ModelEntry.from_dict(unpack(i.value)) for i in items]
