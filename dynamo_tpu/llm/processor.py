"""Frontend-side processor: tokenize → KV-route → worker → detokenize.

Reference examples/llm/components/processor.py:41-208 (the Processor of the
``agg_router`` graph): lowers the OpenAI request with the model card's
tokenizer, asks the Router for a worker, calls the worker's token-level
endpoint with ``direct()`` routing, and maps the token stream back to
OpenAI chunks through the detokenizing Backend.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import AsyncIterator, Optional

from ..runtime import guard, revive
from ..runtime.component import Client
from ..runtime.dcp_client import NoRespondersError
from ..runtime.engine import Context
from .backend import Backend
from .kv_router.router import KvRouter
from .model_card import ModelDeploymentCard
from .preprocessor import completion_logprobs, OpenAIPreprocessor
from .protocols.common import EngineOutput, PreprocessedRequest
from .protocols.openai import (ChatCompletionRequest, CompletionRequest,
                               _finish_reason_openai)

log = logging.getLogger("dynamo_tpu.processor")


class _RemoteTokenEngine:
    """Adapts a worker's token-level endpoint to the local AsyncEngine
    shape so the Backend can detokenize the remote stream.

    dynarevive: the adapter journals every token it forwards and, when
    the upstream dies before a finish chunk (worker crash, connection
    drop, breaker churn), re-dispatches ``prompt + emitted_tokens`` to a
    sibling worker — ``reroute`` lets the KV router pick the replica
    with the warmest prefix, excluding the dead one — and splices the
    continuation into the SAME stream. Greedy requests resume
    token-identical; no single worker failure becomes a client-visible
    error while siblings are alive and budget remains.
    """

    def __init__(self, client: Client, worker_id: Optional[int],
                 reroute=None):
        self.client = client
        self.worker_id = worker_id
        # async (token_ids, exclude) -> Optional[worker_id]; None falls
        # back to the policy-equipped round-robin path
        self.reroute = reroute

    async def _dispatch(self, request: PreprocessedRequest,
                        context: Context, worker_id: Optional[int]):
        """Route the request: the KV-routed direct pick first, then the
        shared RetryPolicy's round-robin path (``Client.generate``
        retries under the policy, budget-aware, with per-instance
        breakers). The fallback is counted — not silent — as
        ``dyn_llm_route_fallback_total``."""
        if worker_id is not None:
            try:
                return await self.client.direct(request.to_dict(),
                                                worker_id,
                                                context=context)
            except guard.DeadlineExceeded:
                raise
            except (RuntimeError, NoRespondersError) as e:
                # the routed worker vanished between the router's scrape
                # and the direct call (drain/crash churn), or its breaker
                # is open: any live worker beats a 500 — the
                # prefix-overlap win is gone, correctness is not
                guard.counter_inc("dyn_llm_route_fallback_total",
                                  reason=type(e).__name__)
                log.warning("direct route to %x failed (%s); falling "
                            "back to round-robin", worker_id, e)
        return await self.client.round_robin(request.to_dict(),
                                             context=context)

    async def _run_attempt(self, request: PreprocessedRequest,
                           context: Context, session: revive.ReviveSession,
                           worker_id: Optional[int]):
        """One upstream dispatch: journal + forward every chunk. Raises
        the upstream failure for the failover loop to judge."""
        stream = await self._dispatch(request, context, worker_id)
        # the moment the caller kills this request (SSE client dropped,
        # deadline path), sever the call-home conn synchronously — the
        # worker's ctrl loop maps the drop to ctx.kill(), so the engine
        # cancels and frees pages without waiting for this (possibly
        # abandoned) generator to be finalized
        context.on_kill(stream.close)
        killed_sync = False
        try:
            async for env in stream:
                if env.is_error:
                    raise RuntimeError(env.error_message())
                if env.data is not None:
                    out = EngineOutput.from_dict(env.data)
                    session.observe(out)
                    if session.resumes and out.cost is not None:
                        # the finish cost block names the resume so
                        # /v1/traces/{rid} and usage show the failover
                        out.cost.setdefault("resumed_attempts",
                                            session.resumes)
                    yield out
        except (asyncio.CancelledError, GeneratorExit):
            # the caller vanished mid-stream (SSE client disconnect →
            # aiohttp cancels the handler task, which unwinds this
            # generator). Closing the call-home stream is the reliable
            # SYNCHRONOUS kill signal: the worker's ctrl loop maps the
            # conn drop to ctx.kill(), the engine cancels the sequence
            # on its normal path (pages free, attribution records
            # "cancelled"). Awaiting a ctrl frame here would race our
            # own cancellation.
            context.kill()
            stream.close()
            killed_sync = True
            raise
        finally:
            if killed_sync:
                pass  # conn already dropped; never await mid-cancel
            elif context.killed:
                await stream.kill()
            elif context.stopped:
                await stream.stop_generating()

    async def generate(self, request: PreprocessedRequest, context: Context):
        session = revive.ReviveSession(request, context)
        # a killed (abandoned) request must not leak its journal entry
        # until the generator finalizer runs
        # proto: revive.journal open->closed
        context.on_kill(session.close)
        attempt_req = request
        target = self.worker_id
        try:
            while True:
                try:
                    async for out in self._run_attempt(attempt_req, context,
                                                       session, target):
                        yield out
                    if session.finished:
                        return
                    # stream ended without a finish chunk (legacy peer /
                    # truncated): downstream stamps the terminal reason
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — judged below
                    if not session.should_resume(e):
                        raise
                    if session.budget_spent():
                        # every budgeted token already streamed; only the
                        # finish chunk died with the worker — synthesize it
                        yield session.synthetic_finish()
                        return
                    session.mark_resume()
                    # proto: request.lifecycle resumed->prefill
                    attempt_req = session.resume_request()
                    target = await self._pick_resume_target(
                        attempt_req, context, target)
                    log.warning(
                        "revive: upstream for %s died after %d tokens "
                        "(%s); resuming on %s (attempt %d)",
                        context.id, len(session.emitted), e,
                        f"{target:x}" if target is not None
                        else "round-robin", session.resumes)
        finally:
            session.close()  # proto: revive.journal open->closed

    async def _pick_resume_target(self, request: PreprocessedRequest,
                                  context: Context,
                                  failed: Optional[int]) -> Optional[int]:
        """Re-route the resume: overlap scoring over ``prompt + emitted``
        lands it on the sibling with the warmest prefix; the failed
        worker is excluded (its discovery record may outlive it)."""
        if self.reroute is None:
            return None
        exclude = {failed} if failed is not None else set()
        try:
            return await self.reroute(request.token_ids, exclude,
                                      context.id)
        except Exception:  # noqa: BLE001 — routing is best-effort here;
            # the round-robin fallback still carries the resume
            log.debug("revive reroute failed for %s", context.id,
                      exc_info=True)
            return None


class Processor:
    """KV-routed OpenAI engine (chat + completions callables for the
    ModelManager)."""

    def __init__(self, mdc: ModelDeploymentCard, client: Client,
                 router: Optional[KvRouter] = None):
        self.mdc = mdc
        self.client = client
        self.router = router
        self.preprocessor = OpenAIPreprocessor(mdc)

    async def _route(self, pre: PreprocessedRequest,
                     context: Context) -> Optional[int]:
        if self.router is None:
            return None
        # the request id keys the router's predicted-vs-realized
        # calibration entry (matched when the finish cost block returns)
        try:
            worker_id = await self.router.schedule(pre.token_ids,
                                                   request_id=context.id)
        except NoRespondersError:
            raise  # empty pool: typed 503 + Retry-After, not a fallback
        except RuntimeError as e:
            # every candidate saturated (or optimistic slot accounting
            # thinks so between scrapes): dispatch round-robin instead of
            # 500ing — the engines' own admission queues absorb the wave
            # and the frontend's admission controller bounds how deep it
            # gets (dynarevive). Counted, never silent.
            guard.counter_inc("dyn_llm_route_fallback_total",
                              reason="SchedulerSaturated")
            log.warning("kv scheduler saturated (%s); dispatching "
                        "round-robin", e)
            return None
        return worker_id

    async def _reroute(self, token_ids, exclude, request_id):
        """dynarevive resume routing: schedule ``prompt + emitted`` with
        the dead worker excluded — overlap scoring lands the retry on
        the replica with the warmest prefix (and re-keys the calibration
        entry to the resume's prediction)."""
        if self.router is None:
            return None
        return await self.router.schedule(token_ids,
                                          request_id=request_id,
                                          exclude=exclude)

    def chat(self, request: ChatCompletionRequest,
             context: Context) -> AsyncIterator:
        return self._chat(request, context)

    async def _chat(self, request: ChatCompletionRequest, context: Context):
        pre, annotations = self.preprocessor.preprocess_chat(request)
        for ann in annotations:
            yield ann
        worker_id = await self._route(pre, context)
        engine = _RemoteTokenEngine(self.client, worker_id,
                                    reroute=self._reroute)
        backend = Backend(engine, self.preprocessor.tokenizer)
        async for chunk in self.preprocessor.chat_stream(
                request, backend.generate(pre, context), context,
                len(pre.token_ids)):
            yield chunk

    def completion(self, request: CompletionRequest,
                   context: Context) -> AsyncIterator:
        return self._completion(request, context)

    async def _completion(self, request: CompletionRequest, context: Context):
        pre, annotations = self.preprocessor.preprocess_completion(request)
        for ann in annotations:
            yield ann
        worker_id = await self._route(pre, context)
        engine = _RemoteTokenEngine(self.client, worker_id,
                                    reroute=self._reroute)
        backend = Backend(engine, self.preprocessor.tokenizer)
        rid = f"cmpl-{context.id or uuid.uuid4().hex}"
        created = int(time.time())
        n_out = 0
        text_off = 0
        if pre.output.echo_prompt:
            # OpenAI completions echo=true (same contract as the local
            # chain, llm/engines.py); offsets start after the prompt
            echo_text = self.preprocessor.tokenizer.decode(
                list(pre.token_ids))
            text_off = len(echo_text)
            yield {"id": rid, "object": "text_completion",
                   "created": created, "model": request.model,
                   "choices": [{
                       "index": 0, "text": echo_text,
                       "finish_reason": None}]}
        async for out in backend.generate(pre, context):
            n_out += len(out.token_ids)
            if out.text or out.finish_reason or out.logprobs:
                choice = {"index": 0, "text": out.text or "",
                          "finish_reason":
                              _finish_reason_openai(out.finish_reason)}
                lp = completion_logprobs(out, self.preprocessor.tokenizer, text_off)
                if lp:
                    choice["logprobs"] = lp
                text_off += len(out.text or "")
                yield {"id": rid, "object": "text_completion",
                       "created": created, "model": request.model,
                       "choices": [choice]}
            if out.finish_reason:
                if request.stream_options and \
                        request.stream_options.include_usage:
                    yield {"id": rid, "object": "text_completion",
                           "created": created, "model": request.model,
                           "choices": [],
                           "usage": {"prompt_tokens": len(pre.token_ids),
                                     "completion_tokens": n_out,
                                     "total_tokens":
                                         len(pre.token_ids) + n_out}}
                return
