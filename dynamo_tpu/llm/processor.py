"""Frontend-side processor: tokenize → KV-route → worker → detokenize.

Reference examples/llm/components/processor.py:41-208 (the Processor of the
``agg_router`` graph): lowers the OpenAI request with the model card's
tokenizer, asks the Router for a worker, calls the worker's token-level
endpoint with ``direct()`` routing, and maps the token stream back to
OpenAI chunks through the detokenizing Backend.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import AsyncIterator, Optional

from ..runtime import guard
from ..runtime.component import Client
from ..runtime.dcp_client import NoRespondersError
from ..runtime.engine import Context
from .backend import Backend
from .kv_router.router import KvRouter
from .model_card import ModelDeploymentCard
from .preprocessor import completion_logprobs, OpenAIPreprocessor
from .protocols.common import EngineOutput, PreprocessedRequest
from .protocols.openai import (ChatCompletionRequest, CompletionRequest,
                               _finish_reason_openai)

log = logging.getLogger("dynamo_tpu.processor")


class _RemoteTokenEngine:
    """Adapts a worker's token-level endpoint to the local AsyncEngine
    shape so the Backend can detokenize the remote stream."""

    def __init__(self, client: Client, worker_id: Optional[int]):
        self.client = client
        self.worker_id = worker_id

    async def _dispatch(self, request: PreprocessedRequest,
                        context: Context):
        """Route the request: the KV-routed direct pick first, then the
        shared RetryPolicy's round-robin path (``Client.generate``
        retries under the policy, budget-aware, with per-instance
        breakers). The fallback is counted — not silent — as
        ``dyn_llm_route_fallback_total``."""
        if self.worker_id is not None:
            try:
                return await self.client.direct(request.to_dict(),
                                                self.worker_id,
                                                context=context)
            except guard.DeadlineExceeded:
                raise
            except (RuntimeError, NoRespondersError) as e:
                # the routed worker vanished between the router's scrape
                # and the direct call (drain/crash churn), or its breaker
                # is open: any live worker beats a 500 — the
                # prefix-overlap win is gone, correctness is not
                guard.counter_inc("dyn_llm_route_fallback_total",
                                  reason=type(e).__name__)
                log.warning("direct route to %x failed (%s); falling "
                            "back to round-robin", self.worker_id, e)
        return await self.client.round_robin(request.to_dict(),
                                             context=context)

    async def generate(self, request: PreprocessedRequest, context: Context):
        stream = await self._dispatch(request, context)
        try:
            async for env in stream:
                if env.is_error:
                    raise RuntimeError(env.error_message())
                if env.data is not None:
                    yield EngineOutput.from_dict(env.data)
        finally:
            if context.killed:
                await stream.kill()
            elif context.stopped:
                await stream.stop_generating()


class Processor:
    """KV-routed OpenAI engine (chat + completions callables for the
    ModelManager)."""

    def __init__(self, mdc: ModelDeploymentCard, client: Client,
                 router: Optional[KvRouter] = None):
        self.mdc = mdc
        self.client = client
        self.router = router
        self.preprocessor = OpenAIPreprocessor(mdc)

    async def _route(self, pre: PreprocessedRequest,
                     context: Context) -> Optional[int]:
        if self.router is None:
            return None
        # the request id keys the router's predicted-vs-realized
        # calibration entry (matched when the finish cost block returns)
        worker_id = await self.router.schedule(pre.token_ids,
                                               request_id=context.id)
        return worker_id

    def chat(self, request: ChatCompletionRequest,
             context: Context) -> AsyncIterator:
        return self._chat(request, context)

    async def _chat(self, request: ChatCompletionRequest, context: Context):
        pre, annotations = self.preprocessor.preprocess_chat(request)
        for ann in annotations:
            yield ann
        worker_id = await self._route(pre, context)
        engine = _RemoteTokenEngine(self.client, worker_id)
        backend = Backend(engine, self.preprocessor.tokenizer)
        async for chunk in self.preprocessor.chat_stream(
                request, backend.generate(pre, context), context,
                len(pre.token_ids)):
            yield chunk

    def completion(self, request: CompletionRequest,
                   context: Context) -> AsyncIterator:
        return self._completion(request, context)

    async def _completion(self, request: CompletionRequest, context: Context):
        pre, annotations = self.preprocessor.preprocess_completion(request)
        for ann in annotations:
            yield ann
        worker_id = await self._route(pre, context)
        engine = _RemoteTokenEngine(self.client, worker_id)
        backend = Backend(engine, self.preprocessor.tokenizer)
        rid = f"cmpl-{context.id or uuid.uuid4().hex}"
        created = int(time.time())
        n_out = 0
        text_off = 0
        if pre.output.echo_prompt:
            # OpenAI completions echo=true (same contract as the local
            # chain, llm/engines.py); offsets start after the prompt
            echo_text = self.preprocessor.tokenizer.decode(
                list(pre.token_ids))
            text_off = len(echo_text)
            yield {"id": rid, "object": "text_completion",
                   "created": created, "model": request.model,
                   "choices": [{
                       "index": 0, "text": echo_text,
                       "finish_reason": None}]}
        async for out in backend.generate(pre, context):
            n_out += len(out.token_ids)
            if out.text or out.finish_reason or out.logprobs:
                choice = {"index": 0, "text": out.text or "",
                          "finish_reason":
                              _finish_reason_openai(out.finish_reason)}
                lp = completion_logprobs(out, self.preprocessor.tokenizer, text_off)
                if lp:
                    choice["logprobs"] = lp
                text_off += len(out.text or "")
                yield {"id": rid, "object": "text_completion",
                       "created": created, "model": request.model,
                       "choices": [choice]}
            if out.finish_reason:
                if request.stream_options and \
                        request.stream_options.include_usage:
                    yield {"id": rid, "object": "text_completion",
                           "created": created, "model": request.model,
                           "choices": [],
                           "usage": {"prompt_tokens": len(pre.token_ids),
                                     "completion_tokens": n_out,
                                     "total_tokens":
                                         len(pre.token_ids) + n_out}}
                return
