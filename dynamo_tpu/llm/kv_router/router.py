"""The KV router: event-fed index + metrics-fed scheduler.

Reference lib/llm/src/kv_router.rs:45-143: subscribes the component's
``kv_events`` subject into the ``KvIndexer``, polls worker stats into the
scheduler's ``ProcessedEndpoints`` (metrics_aggregator.rs:27-109), and
answers ``schedule(token_ids) → worker_id``. Also prunes dead workers on
discovery Delete events and publishes per-decision KVHitRateEvents for the
metrics component.
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence

from ...runtime import guard, profiling, tracing, wire
from ...runtime.component import Client
from ...runtime.dcp_client import (DcpClient, NoRespondersError, pack,
                                   unpack)
from ...runtime.runtime import DistributedRuntime
from ...runtime.tasks import backoff_interval, cancel_join, spawn_tracked
from .indexer import KvIndexer, OverlapScores
from .protocols import (KV_EVENT_SUBJECT, KV_HIT_RATE_SUBJECT,
                        ForwardPassMetrics, KvCacheEventWire)
from .scheduler import KvScheduler

log = logging.getLogger("dynamo_tpu.kv_router")


class KvRouter:
    """Routes requests onto the workers of one component using the global
    prefix index + load cost function."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 component: str, *, block_size: int = 64,
                 load_balance_weight: float = 0.3,
                 scrape_interval: float = 1.0,
                 seed: Optional[int] = None):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        # event-loop-affine: the event subscription, the scrape loop and
        # every schedule() call share these; each touch is one atomic
        # sync call (reference indexer.rs single-writer discipline —
        # the asyncio loop provides it without thread hops), and
        # dynarace rejects any access pattern that straddles an await
        self.indexer = KvIndexer(block_size)  # guarded-by: loop
        # seed: deterministic tie-breaking for simulated / replayed runs
        self.scheduler = KvScheduler(  # guarded-by: loop
            block_size=block_size, load_balance_weight=load_balance_weight,
            on_hit_rate_event=self._on_hit_rate,
            rng=random.Random(seed) if seed is not None else random.Random())
        self.scrape_interval = scrape_interval
        self.client: Optional[Client] = None
        self._sid: Optional[int] = None
        self._scrape_task: Optional[asyncio.Task] = None
        self._hit_events = 0
        self._overlap_blocks_total = 0
        self._isl_blocks_total = 0
        # dynacache calibration: per-request predicted overlap parked at
        # schedule() time, compared against the engine's REALIZED prefix
        # split when the finish cost block passes the attribution
        # listener — the first direct measurement of whether overlap
        # routing is right. The listener fires on the engine's executor
        # thread in-process, so this state takes a real lock (not the
        # loop-affinity discipline the indexer/scheduler use).
        self._calib_lock = threading.Lock()
        self._pending_pred: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: self._calib_lock
        self._pending_cap = 2048
        self.calib_compared = 0  # guarded-by: self._calib_lock
        self.calib_predicted_blocks = 0  # guarded-by: self._calib_lock
        self.calib_realized_blocks = 0  # guarded-by: self._calib_lock
        self.calib_abs_error_blocks = 0  # guarded-by: self._calib_lock

    async def start(self, endpoint: str = "generate_tokens",
                    *, run_loop: bool = True) -> None:
        """``run_loop=False`` skips the periodic scrape task; drivers that
        step time themselves (the fleet simulator) call ``scrape_once``
        directly."""
        drt = self.drt
        self.client = await drt.namespace(self.namespace) \
            .component(self.component).endpoint(endpoint).client()
        self._sid = await drt.dcp.subscribe(
            f"{self.namespace}.{self.component}.{KV_EVENT_SUBJECT}",
            self._on_events)
        if run_loop:
            self._scrape_task = spawn_tracked(self._scrape_loop(),
                                              name="kv-router-scrape")
        # calibration feed: finish cost blocks (engine-local or re-registered
        # from a remote worker's finish chunk by the Backend) flow past here
        profiling.add_attribution_listener(self._on_attribution)
        profiling.register_cache(f"kv-router-{id(self):x}", self)

    async def stop(self) -> None:
        profiling.remove_attribution_listener(self._on_attribution)
        if self._sid is not None:
            try:
                await self.drt.dcp.unsubscribe(self._sid)
            except Exception:
                log.debug("unsubscribe failed during stop", exc_info=True)
        await cancel_join(self._scrape_task)
        if self.client:
            await self.client.close()

    # ------------------------------------------------------------- inputs

    async def _on_events(self, msg) -> None:
        try:
            for raw in unpack(msg.payload):
                self.indexer.apply_event(KvCacheEventWire.from_dict(raw))
        except Exception:
            log.exception("bad kv event payload")

    async def _scrape_loop(self) -> None:
        failures = 0
        while True:
            try:
                await self.scrape_once()
                failures = 0
            except Exception:
                # bounded backoff: a worker pool that stays unreachable
                # gets probed gently, and every failure is on the record
                failures += 1
                log.exception("stats scrape failed "
                              "(%d consecutive failures)", failures)
            await asyncio.sleep(
                backoff_interval(self.scrape_interval, failures))

    async def scrape_once(self) -> None:
        """Scrape worker stats + reconcile live instances (reference
        collect_endpoints_task)."""
        stats = await self.client.collect_stats(timeout=self.scrape_interval)
        metrics: Dict[int, ForwardPassMetrics] = {}
        for wid, payload in stats.items():
            payload = wire.decoded(wire.DCP_STATS_REPLY, payload)
            metrics[wid] = ForwardPassMetrics.from_dict(payload.get("data", {}))
        self.scheduler.update_metrics(metrics)
        # prune index entries of workers that disappeared from discovery
        live = set(self.client.instance_ids())
        for wid in self.indexer.workers():
            if wid not in live:
                log.info("pruning dead worker %x from KV index", wid)
                self.indexer.remove_worker(wid)

    # ------------------------------------------------------------ routing

    async def schedule(self, token_ids: Sequence[int],
                       request_id: Optional[str] = None,
                       exclude=None) -> int:
        """token_ids → worker instance id. ``request_id`` keys the
        predicted-vs-realized calibration entry for this decision.
        ``exclude`` (dynarevive failover) drops candidate workers — the
        dead worker a resume must avoid even while its discovery record
        and warm prefix index entries linger."""
        with tracing.get_tracer().start_span("route", attributes={
                "tokens": len(token_ids)}) as span:
            if not self.scheduler.workers:
                await self.scrape_once()
            if not self.scheduler.workers:
                # no stats yet: fall back to any live instance. An EMPTY
                # pool is typed NoResponders (HTTP 503 + Retry-After) —
                # found live by the dynarevive drain drive: draining the
                # last worker turned new requests into raw TimeoutError
                # 500s here instead of the retryable no-capacity shape
                try:
                    ids = await self.client.wait_for_instances(timeout=10)
                except asyncio.TimeoutError:
                    raise NoRespondersError(
                        f"no live instances of {self.namespace}."
                        f"{self.component}") from None
                if not self.scheduler.workers:
                    # re-check after the wait: a scrape may have landed
                    # real occupancy during it, and zeroed fallback
                    # metrics must not clobber that view (the router
                    # would dogpile the busiest worker)
                    self.scheduler.update_metrics(
                        {wid: ForwardPassMetrics() for wid in ids})
            overlaps = self.indexer.find_matches_for_request(token_ids)
            # only consider overlaps from live workers
            wid = self.scheduler.schedule(len(token_ids), overlaps,
                                          request_id=request_id,
                                          exclude=exclude)
            if request_id:
                bs = self.scheduler.block_size
                isl_blocks = max((len(token_ids) + bs - 1) // bs, 1)
                with self._calib_lock:
                    self._pending_pred[request_id] = {
                        "worker": wid,
                        "overlap_blocks": min(
                            overlaps.scores.get(wid, 0), isl_blocks),
                        "isl_blocks": isl_blocks,
                        "compared": False,
                    }
                    while len(self._pending_pred) > self._pending_cap:
                        self._pending_pred.popitem(last=False)
            span.set_attribute("worker_id", f"{wid:x}")
            span.set_attribute("overlap_blocks",
                               overlaps.scores.get(wid, 0))
            return wid

    def overlap_for(self, token_ids: Sequence[int], worker_id: int) -> int:
        """Matched prefix BLOCKS on the chosen worker (feeds the disagg
        router's prefix_hit_length)."""
        scores = self.indexer.find_matches_for_request(token_ids).scores
        return scores.get(worker_id, 0)

    # -------------------------------------------------------- observability

    def _on_attribution(self, request_id: str, cost: dict) -> None:
        """Attribution listener (dynacache calibration): when a routed
        request's finish cost block arrives, merge this router's predicted
        overlap into the block (so /v1/traces/{rid} shows
        router_overlap_blocks next to the engine's realized split) and
        accumulate predicted-vs-realized counters. Sync, idempotent per
        request (the engine-local record and the Backend's re-register of
        the same finish both pass through here), and callable from any
        thread."""
        if "device_hit_blocks" not in cost:
            return  # not an engine prefix-split cost block
        with self._calib_lock:
            ent = self._pending_pred.get(request_id)
            if ent is None:
                return
            cost.setdefault("router_overlap_blocks", ent["overlap_blocks"])
            if ent["compared"]:
                return
            ent["compared"] = True
            realized = (int(cost.get("device_hit_blocks", 0))
                        + int(cost.get("host_restored_blocks", 0)))
            predicted = ent["overlap_blocks"]
            self.calib_compared += 1
            self.calib_predicted_blocks += predicted
            self.calib_realized_blocks += realized
            self.calib_abs_error_blocks += abs(predicted - realized)
            # dynaheat: feed the scheduler's load_balance_weight
            # autotuner (no-op unless enabled; bounded adjustment once
            # per calibration window)
            self.scheduler.observe_calibration(predicted, realized,
                                               ent["isl_blocks"])
        guard.counter_inc("dyn_kv_router_predicted_vs_realized_blocks",
                          float(predicted), view="predicted")
        guard.counter_inc("dyn_kv_router_predicted_vs_realized_blocks",
                          float(realized), view="realized")

    def _on_hit_rate(self, ev) -> None:
        self._hit_events += 1
        self._overlap_blocks_total += ev.overlap_blocks
        self._isl_blocks_total += ev.isl_blocks
        spawn_tracked(self._publish_hit_rate(ev), name="kv-hit-rate-pub")

    async def _publish_hit_rate(self, ev) -> None:
        try:
            await self.drt.dcp.publish(
                f"{self.namespace}.{KV_HIT_RATE_SUBJECT}",
                pack(ev.to_dict()))
        except Exception:
            log.debug("hit-rate publish failed", exc_info=True)

    def stats(self) -> dict:
        with self._calib_lock:
            calib = {
                "compared": self.calib_compared,
                "predicted_blocks_total": self.calib_predicted_blocks,
                "realized_blocks_total": self.calib_realized_blocks,
                "abs_error_blocks_total": self.calib_abs_error_blocks,
                "mean_abs_error_blocks": (
                    self.calib_abs_error_blocks
                    / max(self.calib_compared, 1)),
            }
        return {
            "decisions": self._hit_events,
            "avg_hit_rate": (self._overlap_blocks_total /
                             max(self._isl_blocks_total, 1)),
            "indexed_blocks": self.indexer.tree.block_count(),
            "workers": len(self.scheduler.workers),
            # predicted (overlap scoring) vs realized (engine prefix
            # split) blocks over requests whose cost block came back
            "calibration": calib,
            # dynaheat autotune: the live (possibly self-tuned) cost
            # weight and how often calibration bias actually moved it
            "load_balance_weight": round(
                self.scheduler.load_balance_weight, 4),
            "autotune": {
                "enabled": bool(self.scheduler.autotune),
                "adjustments": self.scheduler.autotune_adjustments,
            },
        }

    def cache_snapshot(self) -> dict:
        """dynacache /debug/cache view of the routing side: index size,
        hit-rate aggregates, and the calibration counters."""
        return {"kind": "kv_router", **self.stats()}
