"""The KV router: event-fed index + metrics-fed scheduler.

Reference lib/llm/src/kv_router.rs:45-143: subscribes the component's
``kv_events`` subject into the ``KvIndexer``, polls worker stats into the
scheduler's ``ProcessedEndpoints`` (metrics_aggregator.rs:27-109), and
answers ``schedule(token_ids) → worker_id``. Also prunes dead workers on
discovery Delete events and publishes per-decision KVHitRateEvents for the
metrics component.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Dict, Optional, Sequence

from ...runtime import tracing, wire
from ...runtime.component import Client
from ...runtime.dcp_client import DcpClient, pack, unpack
from ...runtime.runtime import DistributedRuntime
from ...runtime.tasks import backoff_interval, cancel_join, spawn_tracked
from .indexer import KvIndexer, OverlapScores
from .protocols import (KV_EVENT_SUBJECT, KV_HIT_RATE_SUBJECT,
                        ForwardPassMetrics, KvCacheEventWire)
from .scheduler import KvScheduler

log = logging.getLogger("dynamo_tpu.kv_router")


class KvRouter:
    """Routes requests onto the workers of one component using the global
    prefix index + load cost function."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 component: str, *, block_size: int = 64,
                 load_balance_weight: float = 0.3,
                 scrape_interval: float = 1.0,
                 seed: Optional[int] = None):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        # event-loop-affine: the event subscription, the scrape loop and
        # every schedule() call share these; each touch is one atomic
        # sync call (reference indexer.rs single-writer discipline —
        # the asyncio loop provides it without thread hops), and
        # dynarace rejects any access pattern that straddles an await
        self.indexer = KvIndexer(block_size)  # guarded-by: loop
        # seed: deterministic tie-breaking for simulated / replayed runs
        self.scheduler = KvScheduler(  # guarded-by: loop
            block_size=block_size, load_balance_weight=load_balance_weight,
            on_hit_rate_event=self._on_hit_rate,
            rng=random.Random(seed) if seed is not None else random.Random())
        self.scrape_interval = scrape_interval
        self.client: Optional[Client] = None
        self._sid: Optional[int] = None
        self._scrape_task: Optional[asyncio.Task] = None
        self._hit_events = 0
        self._overlap_blocks_total = 0
        self._isl_blocks_total = 0

    async def start(self, endpoint: str = "generate_tokens",
                    *, run_loop: bool = True) -> None:
        """``run_loop=False`` skips the periodic scrape task; drivers that
        step time themselves (the fleet simulator) call ``scrape_once``
        directly."""
        drt = self.drt
        self.client = await drt.namespace(self.namespace) \
            .component(self.component).endpoint(endpoint).client()
        self._sid = await drt.dcp.subscribe(
            f"{self.namespace}.{self.component}.{KV_EVENT_SUBJECT}",
            self._on_events)
        if run_loop:
            self._scrape_task = spawn_tracked(self._scrape_loop(),
                                              name="kv-router-scrape")

    async def stop(self) -> None:
        if self._sid is not None:
            try:
                await self.drt.dcp.unsubscribe(self._sid)
            except Exception:
                log.debug("unsubscribe failed during stop", exc_info=True)
        await cancel_join(self._scrape_task)
        if self.client:
            await self.client.close()

    # ------------------------------------------------------------- inputs

    async def _on_events(self, msg) -> None:
        try:
            for raw in unpack(msg.payload):
                self.indexer.apply_event(KvCacheEventWire.from_dict(raw))
        except Exception:
            log.exception("bad kv event payload")

    async def _scrape_loop(self) -> None:
        failures = 0
        while True:
            try:
                await self.scrape_once()
                failures = 0
            except Exception:
                # bounded backoff: a worker pool that stays unreachable
                # gets probed gently, and every failure is on the record
                failures += 1
                log.exception("stats scrape failed "
                              "(%d consecutive failures)", failures)
            await asyncio.sleep(
                backoff_interval(self.scrape_interval, failures))

    async def scrape_once(self) -> None:
        """Scrape worker stats + reconcile live instances (reference
        collect_endpoints_task)."""
        stats = await self.client.collect_stats(timeout=self.scrape_interval)
        metrics: Dict[int, ForwardPassMetrics] = {}
        for wid, payload in stats.items():
            payload = wire.decoded(wire.DCP_STATS_REPLY, payload)
            metrics[wid] = ForwardPassMetrics.from_dict(payload.get("data", {}))
        self.scheduler.update_metrics(metrics)
        # prune index entries of workers that disappeared from discovery
        live = set(self.client.instance_ids())
        for wid in self.indexer.workers():
            if wid not in live:
                log.info("pruning dead worker %x from KV index", wid)
                self.indexer.remove_worker(wid)

    # ------------------------------------------------------------ routing

    async def schedule(self, token_ids: Sequence[int]) -> int:
        """token_ids → worker instance id."""
        with tracing.get_tracer().start_span("route", attributes={
                "tokens": len(token_ids)}) as span:
            if not self.scheduler.workers:
                await self.scrape_once()
            if not self.scheduler.workers:
                # no stats yet: fall back to any live instance
                ids = await self.client.wait_for_instances(timeout=10)
                if not self.scheduler.workers:
                    # re-check after the wait: a scrape may have landed
                    # real occupancy during it, and zeroed fallback
                    # metrics must not clobber that view (the router
                    # would dogpile the busiest worker)
                    self.scheduler.update_metrics(
                        {wid: ForwardPassMetrics() for wid in ids})
            overlaps = self.indexer.find_matches_for_request(token_ids)
            # only consider overlaps from live workers
            wid = self.scheduler.schedule(len(token_ids), overlaps)
            span.set_attribute("worker_id", f"{wid:x}")
            span.set_attribute("overlap_blocks",
                               overlaps.scores.get(wid, 0))
            return wid

    def overlap_for(self, token_ids: Sequence[int], worker_id: int) -> int:
        """Matched prefix BLOCKS on the chosen worker (feeds the disagg
        router's prefix_hit_length)."""
        scores = self.indexer.find_matches_for_request(token_ids).scores
        return scores.get(worker_id, 0)

    # -------------------------------------------------------- observability

    def _on_hit_rate(self, ev) -> None:
        self._hit_events += 1
        self._overlap_blocks_total += ev.overlap_blocks
        self._isl_blocks_total += ev.isl_blocks
        spawn_tracked(self._publish_hit_rate(ev), name="kv-hit-rate-pub")

    async def _publish_hit_rate(self, ev) -> None:
        try:
            await self.drt.dcp.publish(
                f"{self.namespace}.{KV_HIT_RATE_SUBJECT}",
                pack(ev.to_dict()))
        except Exception:
            log.debug("hit-rate publish failed", exc_info=True)

    def stats(self) -> dict:
        return {
            "decisions": self._hit_events,
            "avg_hit_rate": (self._overlap_blocks_total /
                             max(self._isl_blocks_total, 1)),
            "indexed_blocks": self.indexer.tree.block_count(),
            "workers": len(self.scheduler.workers),
        }
