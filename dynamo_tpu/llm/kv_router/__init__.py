"""KV-cache-aware routing (reference lib/llm/src/kv_router/)."""

from .indexer import KvIndexer, OverlapScores, RadixTree
from .protocols import (ForwardPassMetrics, KVHitRateEvent, KvCacheEventWire,
                        KV_EVENT_SUBJECT, KV_HIT_RATE_SUBJECT)
from .publisher import KvEventPublisher
from .router import KvRouter
from .scheduler import KvScheduler, WorkerState
