"""ctypes wrapper over the C++ radix index (native/radix_index.cpp) —
drop-in for RadixTree (reference indexer.rs in Rust; SURVEY §7 hard part
(d) calls for the indexer hot path in native code)."""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

from ...utils import native
from .indexer import OverlapScores
from .protocols import KvCacheEventWire

_MAX_WORKERS = 4096  # find_matches out-buffer capacity


class CppRadixTree:
    """Same interface as indexer.RadixTree, backed by the C++ index."""

    def __init__(self) -> None:
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._ptr = self._lib.dyn_radix_create()
        self._ow = (ctypes.c_uint64 * _MAX_WORKERS)()
        self._os = (ctypes.c_uint32 * _MAX_WORKERS)()

    def __del__(self):
        try:
            if getattr(self, "_ptr", None):
                self._lib.dyn_radix_destroy(self._ptr)
                self._ptr = None
        except Exception:
            pass

    @staticmethod
    def _arr(hashes: Sequence[int]):
        import numpy as np

        # numpy marshals the int list in C, ~10x faster than a ctypes
        # array constructor per call on long chains
        a = np.asarray(hashes, dtype=np.uint64)
        return a, a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(a)

    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        keep, ptr, n = self._arr(block_hashes)
        cnt = self._lib.dyn_radix_find_matches(
            self._ptr, ptr, n, self._ow, self._os, _MAX_WORKERS)
        return OverlapScores({int(self._ow[i]): int(self._os[i])
                              for i in range(cnt)})

    def apply_event(self, ev: KvCacheEventWire) -> None:
        keep, ptr, n = self._arr(ev.block_hashes)
        if ev.kind == "stored":
            parent = ev.parent_hash
            self._lib.dyn_radix_apply_stored(
                self._ptr, ev.worker_id & (2**64 - 1),
                (parent or 0) & (2**64 - 1), 1 if parent is not None else 0,
                ptr, n)
        elif ev.kind == "removed":
            self._lib.dyn_radix_apply_removed(
                self._ptr, ev.worker_id & (2**64 - 1), ptr, n)

    def remove_worker(self, worker_id: int) -> None:
        self._lib.dyn_radix_remove_worker(self._ptr, worker_id & (2**64 - 1))

    def block_count(self) -> int:
        return int(self._lib.dyn_radix_block_count(self._ptr))


def make_radix_tree(prefer_native: bool = True):
    """RadixTree factory: C++ when buildable, Python otherwise."""
    if prefer_native and native.available():
        return CppRadixTree()
    from .indexer import RadixTree

    return RadixTree()
