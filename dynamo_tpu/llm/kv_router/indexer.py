"""Global KV-cache radix index.

Reference lib/llm/src/kv_router/indexer.rs (1,409 LoC): a prefix tree over
chained block hashes recording WHICH workers hold WHICH cached blocks.
``find_matches`` walks a request's block-hash chain and returns per-worker
overlap scores; ``apply_event`` ingests worker Stored/Removed events. The
reference confines the tree to a dedicated single-threaded runtime fed by
channels (indexer.rs:37,499+); here the asyncio event loop provides the
same single-writer discipline without thread hops.

Block hashes are the engine's chained xxh3 hashes (engine/kv_manager.py,
same construction as reference tokens.rs / indexer.rs:64,123-135), so the
index is consistent across engine, events, and router without re-hashing.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from ...engine.kv_manager import chain_hashes
from .protocols import KvCacheEventWire


@dataclass
class OverlapScores:
    """worker id → number of matched prefix blocks (reference
    indexer.rs OverlapScores)."""

    scores: Dict[int, int] = field(default_factory=dict)

    def best(self) -> int:
        return max(self.scores.values(), default=0)


class _Node:
    __slots__ = ("block_hash", "parent", "children", "workers")

    def __init__(self, block_hash: int, parent: Optional["_Node"]):
        self.block_hash = block_hash
        self.parent = parent
        self.children: Dict[int, _Node] = {}
        self.workers: Set[int] = set()


class RadixTree:
    """Prefix tree keyed by block hash; each node records the workers that
    hold that block. A per-worker hash→node lookup makes Removed events and
    worker eviction O(1) per block (reference indexer.rs:187-203)."""

    def __init__(self) -> None:
        self.root = _Node(0, None)
        self.lookup: Dict[int, Dict[int, _Node]] = defaultdict(dict)

    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        """Walk the chain from the root; count per-worker contiguous
        matches (reference indexer.rs find_matches, :239+)."""
        scores: Dict[int, int] = {}
        node = self.root
        for h in block_hashes:
            nxt = node.children.get(h)
            if nxt is None:
                break
            for w in nxt.workers:
                scores[w] = scores.get(w, 0) + 1
            node = nxt
        return OverlapScores(scores)

    def apply_event(self, ev: KvCacheEventWire) -> None:
        if ev.kind == "stored":
            self._apply_stored(ev)
        elif ev.kind == "removed":
            self._apply_removed(ev)

    def _apply_stored(self, ev: KvCacheEventWire) -> None:
        wl = self.lookup[ev.worker_id]
        # anchor at the parent node if known, else the root (reference
        # attaches Stored{parent_hash, blocks} chains)
        if ev.parent_hash is not None and ev.parent_hash in wl:
            node = wl[ev.parent_hash]
        else:
            node = self.root
        for h in ev.block_hashes:
            existing = wl.get(h)
            if existing is not None:
                node = existing
                continue
            child = node.children.get(h)
            if child is None:
                child = _Node(h, node)
                node.children[h] = child
            child.workers.add(ev.worker_id)
            wl[h] = child
            node = child

    def _apply_removed(self, ev: KvCacheEventWire) -> None:
        wl = self.lookup[ev.worker_id]
        for h in ev.block_hashes:
            node = wl.pop(h, None)
            if node is None:
                continue
            node.workers.discard(ev.worker_id)
            self._maybe_prune(node)

    def remove_worker(self, worker_id: int) -> None:
        """Drop every block of a dead worker (lease expiry → stale index
        entries must go, reference kv_router.rs worker removal)."""
        wl = self.lookup.pop(worker_id, {})
        for node in wl.values():
            node.workers.discard(worker_id)
            self._maybe_prune(node)

    def _maybe_prune(self, node: "_Node") -> None:
        while (node is not self.root and not node.workers
               and not node.children and node.parent is not None):
            parent = node.parent
            parent.children.pop(node.block_hash, None)
            node.parent = None
            node = parent

    def block_count(self) -> int:
        n = 0
        stack = [self.root]
        while stack:
            cur = stack.pop()
            n += len(cur.children)
            stack.extend(cur.children.values())
        return n


class KvIndexer:
    """Tokens-in, scores-out façade over the RadixTree. Uses the C++
    index (native/radix_index.cpp via ctypes) when it builds, else this
    module's Python tree (``backend='python'`` forces the fallback)."""

    def __init__(self, block_size: int, backend: str = "auto"):
        self.block_size = block_size
        # backend-agnostic record of workers with indexed blocks: the C++
        # tree has no worker-enumeration API, and the router's dead-worker
        # prune needs one (reading the Python tree's ``lookup`` dict broke
        # every scrape pass under the native backend)
        self._workers: set = set()  # guarded-by: loop
        if backend == "python":
            self.tree = RadixTree()  # guarded-by: loop
        else:
            from .native_indexer import make_radix_tree

            self.tree = make_radix_tree(prefer_native=(backend != "python"))

    def find_matches_for_request(self, token_ids: Sequence[int]
                                 ) -> OverlapScores:
        hashes = chain_hashes(token_ids, self.block_size)
        return self.tree.find_matches(hashes)

    def apply_event(self, ev: KvCacheEventWire) -> None:
        self._workers.add(ev.worker_id)
        self.tree.apply_event(ev)

    def remove_worker(self, worker_id: int) -> None:
        self._workers.discard(worker_id)
        self.tree.remove_worker(worker_id)

    def workers(self) -> List[int]:
        """Workers that have contributed indexed blocks (sorted)."""
        return sorted(self._workers)
