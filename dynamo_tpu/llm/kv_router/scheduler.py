"""KV-aware worker selection (cost scheduler).

Reference lib/llm/src/kv_router/scheduler.rs:84-316: pick the worker
minimizing

    cost = alpha * load_deviation            (KV usage vs fleet mean)
         + (1 - alpha) * normalized_new_tokens (1 - prefix overlap ratio)
         + gamma * request_load_ratio          (active / total slots)

with alpha 0.7 when load-balancing is prioritized and 0.3 when cache reuse
is (scheduler.rs cost fn); saturated workers (no free request slots or no
free KV blocks) are skipped; optimistic local accounting bumps the chosen
worker's slots/blocks so a burst of schedules between metric scrapes
doesn't pile onto one worker; every decision emits a KVHitRateEvent.
"""

from __future__ import annotations

import logging
import random
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...runtime import guard
from ...runtime.config import env_bool, env_float
from .indexer import OverlapScores
from .protocols import ForwardPassMetrics, KVHitRateEvent

log = logging.getLogger("dynamo_tpu.kv_router.scheduler")


@dataclass
class WorkerState:
    metrics: ForwardPassMetrics
    # optimistic deltas applied since the last scrape
    extra_requests: int = 0
    extra_blocks: int = 0

    @property
    def active_slots(self) -> int:
        return self.metrics.request_active_slots + self.extra_requests

    @property
    def active_blocks(self) -> int:
        return self.metrics.kv_active_blocks + self.extra_blocks

    @property
    def usage(self) -> float:
        total = max(self.metrics.kv_total_blocks, 1)
        return self.active_blocks / total

    def saturated(self) -> bool:
        m = self.metrics
        slots_full = (m.request_total_slots > 0
                      and self.active_slots >= m.request_total_slots)
        blocks_full = (m.kv_total_blocks > 0
                       and self.active_blocks >= m.kv_total_blocks)
        return slots_full or blocks_full


@dataclass
class KvScheduler:
    block_size: int
    load_balance_weight: float = 0.3   # alpha: 0.3 favors cache reuse,
    #                                     0.7 favors load balancing
    slot_weight: float = 0.25          # gamma
    on_hit_rate_event: Optional[Callable[[KVHitRateEvent], None]] = None
    workers: Dict[int, WorkerState] = field(default_factory=dict)
    # tie-breaking entropy: injectable so deterministic drivers (the fleet
    # simulator) can seed routing; default keeps process-level randomness
    rng: random.Random = field(default_factory=random.Random)
    # dynacache calibration feed: the last N routing decisions with every
    # candidate's (capped) overlap score and the chosen worker, so the
    # router can compare its prediction against the engine's realized
    # prefix hit when the finish cost block comes back
    decisions: deque = field(default_factory=lambda: deque(maxlen=256))
    # ── dynaheat autotune: load_balance_weight self-adjusts from the
    # predicted-vs-realized overlap calibration error the router feeds
    # back via observe_calibration(). Systematic OVER-prediction (the
    # index promises overlap the engines no longer hold — evicted or
    # stale blocks) means the overlap term is over-trusted, so weight
    # shifts toward load; under-prediction shifts it back. None reads
    # DYN_ROUTER_AUTOTUNE / DYN_ROUTER_AUTOTUNE_GAIN.
    autotune: Optional[bool] = None
    autotune_gain: Optional[float] = None
    autotune_window: int = 64          # compared requests per adjustment
    alpha_min: float = 0.1             # hard bounds on the tuned weight
    alpha_max: float = 0.9
    autotune_adjustments: int = 0      # times the weight actually moved
    _tune_pred: int = 0                # window accumulators
    _tune_real: int = 0
    _tune_isl: int = 0
    _tune_seen: int = 0

    def __post_init__(self) -> None:
        if self.autotune is None:
            self.autotune = env_bool("DYN_ROUTER_AUTOTUNE", True)
        if self.autotune_gain is None:
            self.autotune_gain = env_float("DYN_ROUTER_AUTOTUNE_GAIN",
                                           0.05) or 0.0

    def observe_calibration(self, predicted: int, realized: int,
                            isl_blocks: int) -> None:
        """One compared request's predicted vs realized overlap blocks
        (called by KvRouter._on_attribution under its calibration lock).
        Every ``autotune_window`` observations the window bias
        ``(pred − real) / isl`` nudges ``load_balance_weight`` by
        ``gain · bias · range``, clamped to [alpha_min, alpha_max]; zero
        bias (perfect calibration) moves nothing. The current weight is
        exported as the ``dyn_kv_router_load_balance_weight`` gauge."""
        if not self.autotune:
            return
        self._tune_pred += predicted
        self._tune_real += realized
        self._tune_isl += isl_blocks
        self._tune_seen += 1
        if self._tune_seen < self.autotune_window:
            return
        bias = (self._tune_pred - self._tune_real) / max(self._tune_isl, 1)
        self._tune_pred = self._tune_real = self._tune_isl = 0
        self._tune_seen = 0
        step = self.autotune_gain * bias * (self.alpha_max - self.alpha_min)
        if step == 0.0:
            return
        new_w = min(max(self.load_balance_weight + step, self.alpha_min),
                    self.alpha_max)
        if new_w != self.load_balance_weight:
            self.load_balance_weight = new_w
            self.autotune_adjustments += 1
        # gauge semantics over the counter store: set-by-delta so the
        # exposition always shows the CURRENT weight
        guard.counter_inc(
            "dyn_kv_router_load_balance_weight",
            new_w - guard.counter_value("dyn_kv_router_load_balance_weight"))

    def update_metrics(self, metrics: Dict[int, ForwardPassMetrics]) -> None:
        """Replace worker snapshots (periodic scrape) and reset the
        optimistic deltas (reference ProcessedEndpoints refresh)."""
        self.workers = {wid: WorkerState(m) for wid, m in metrics.items()}

    def schedule(self, num_tokens: int, overlaps: OverlapScores,
                 request_id: Optional[str] = None,
                 exclude=None) -> int:
        """Pick a worker for a request of ``num_tokens`` prompt tokens.
        Raises RuntimeError when no worker is available. ``exclude``
        drops candidates outright (dynarevive failover: the dead worker
        a resume must avoid); draining workers are skipped like
        saturated ones (draining ≠ dead, but it admits nothing new)."""
        if not self.workers:
            raise RuntimeError("no workers registered with the KV scheduler")
        isl_blocks = max((num_tokens + self.block_size - 1) // self.block_size, 1)
        usages = [w.usage for w in self.workers.values()]
        mean_usage = sum(usages) / len(usages)

        alpha = self.load_balance_weight
        excluded = set(exclude) if exclude else ()
        best_cost = None
        best: List[int] = []
        for wid, w in self.workers.items():
            if wid in excluded:
                continue
            if getattr(w.metrics, "draining", 0):
                continue
            if getattr(w.metrics, "role", "") == "prefill":
                # dynaslo P/D roles: a prefill-role worker takes its work
                # from the shared prefill queue, never routed decode
                # requests (the fleet P/D rebalance flips roles live —
                # the next scrape moves it out of the candidate set)
                continue
            if w.saturated():
                continue
            overlap = min(overlaps.scores.get(wid, 0), isl_blocks)
            new_ratio = 1.0 - overlap / isl_blocks
            load_dev = w.usage - mean_usage
            slots = w.active_slots / max(w.metrics.request_total_slots, 1)
            cost = alpha * load_dev + (1 - alpha) * new_ratio \
                + self.slot_weight * slots
            if best_cost is None or cost < best_cost - 1e-9:
                best_cost, best = cost, [wid]
            elif abs(cost - best_cost) <= 1e-9:
                best.append(wid)
        if not best:
            raise RuntimeError("all workers saturated")
        chosen = self.rng.choice(best)
        # per-decision record: every live candidate's capped overlap plus
        # the pick (bounded ring; feeds predicted-vs-realized calibration).
        # The chosen worker's capped overlap is read once (dynahot DL022:
        # the same min(scores.get(...)) was resolved three more times
        # below for the accounting and the hit-rate event).
        scores = overlaps.scores
        chosen_overlap = min(scores.get(chosen, 0), isl_blocks)
        self.decisions.append({
            "request_id": request_id,
            "chosen": chosen,
            "isl_blocks": isl_blocks,
            "overlap_blocks": chosen_overlap,
            "candidates": {wid: min(scores.get(wid, 0), isl_blocks)
                           for wid in self.workers},
        })
        # optimistic accounting until the next scrape
        w = self.workers[chosen]
        w.extra_requests += 1
        w.extra_blocks += isl_blocks - chosen_overlap
        if self.on_hit_rate_event:
            self.on_hit_rate_event(KVHitRateEvent(
                worker_id=chosen, isl_blocks=isl_blocks,
                overlap_blocks=chosen_overlap))
        return chosen
