"""Worker-side KV event + metrics publishing.

Reference lib/llm/src/kv_router/publisher.rs:33-137 (KvEventPublisher →
NATS ``kv_events``; KvMetricsPublisher → ``load_metrics`` endpoint + stats)
and the vLLM-patch ``event_manager.py`` → C FFI path the reference needs to
get events OUT of the engine process. Here the engine is in-process, so the
publisher drains ``PageManager.drain_events()`` directly — no FFI shim.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ...engine.jax_engine import JaxEngine
from ...runtime.dcp_client import DcpClient, pack
from ...runtime.tasks import cancel_join, spawn_tracked
from .protocols import KV_EVENT_SUBJECT, KvCacheEventWire

log = logging.getLogger("dynamo_tpu.kv_router.publisher")


class KvEventPublisher:
    """Periodically drains engine KV events onto the bus subject
    ``<namespace>.<component>.kv_events``."""

    def __init__(self, dcp: DcpClient, namespace: str, component: str,
                 worker_id: int, engine: JaxEngine,
                 interval: float = 0.25):
        self.dcp = dcp
        self.subject = f"{namespace}.{component}.{KV_EVENT_SUBJECT}"
        self.worker_id = worker_id
        self.engine = engine
        self.interval = interval
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = spawn_tracked(self._loop(), name="kv-event-pub")

    async def stop(self) -> None:
        # claim the task before the await: a concurrent stop() must not
        # double-cancel (and a start() during the join must not be
        # clobbered by our late `= None`)
        task, self._task = self._task, None
        await cancel_join(task)
        await self.flush()

    async def flush(self) -> None:
        events = self.engine.pm.drain_events()
        if not events:
            return
        payload = pack([
            KvCacheEventWire(worker_id=self.worker_id, kind=e.kind,
                             block_hashes=e.block_hashes,
                             parent_hash=e.parent_hash).to_dict()
            for e in events])
        try:
            await self.dcp.publish(self.subject, payload)
        except Exception:
            log.exception("kv event publish failed")

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            await self.flush()


class NativeEventBridge:
    """Drains the C ABI KV-event shim (native/kv_event_shim.cpp — the
    reference lib/bindings/c surface loaded by external native engines via
    dlopen/ctypes) and republishes onto the bus subject. One bridge per
    worker process hosting a native engine."""

    RECORD_HEADER = 21  # kind u8 + event_id u64 + parent u64 + nblocks u32
    NO_PARENT = 2**64 - 1

    def __init__(self, dcp: DcpClient, namespace: str, component: str,
                 worker_id: int, interval: float = 0.25,
                 buf_size: int = 1 << 20):
        import ctypes

        from ...utils import native

        lib = native.load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._buf = (ctypes.c_uint8 * buf_size)()
        self._buf_size = buf_size
        self.dcp = dcp
        self.subject = f"{namespace}.{component}.{KV_EVENT_SUBJECT}"
        self.worker_id = worker_id
        self.interval = interval
        self._task: Optional[asyncio.Task] = None

    def init_shim(self, namespace: str, component: str,
                  kv_block_size: int) -> None:
        self._lib.dynamo_llm_init(namespace.encode(), component.encode(),
                                  self.worker_id, kv_block_size)

    def drain(self) -> list:
        """Parse drained shim bytes into KvCacheEventWire records."""
        import struct

        n = self._lib.dynamo_kv_events_drain(self._buf, self._buf_size)
        events, off = [], 0
        raw = bytes(self._buf[:n])
        while off + self.RECORD_HEADER <= n:
            kind_b, event_id, parent, nb = struct.unpack_from(
                "<BQQI", raw, off)
            off += self.RECORD_HEADER
            hashes = list(struct.unpack_from(f"<{nb}Q", raw, off))
            off += 8 * nb
            events.append(KvCacheEventWire(
                worker_id=self.worker_id,
                kind="stored" if kind_b == 1 else "removed",
                block_hashes=hashes,
                parent_hash=None if parent == self.NO_PARENT else parent))
        return events

    async def flush(self) -> None:
        events = self.drain()
        if not events:
            return
        try:
            await self.dcp.publish(self.subject,
                                   pack([e.to_dict() for e in events]))
        except Exception:
            log.exception("native kv event publish failed")

    def start(self) -> None:
        if self._task is None:
            self._task = spawn_tracked(self._loop(),
                                       name="native-kv-event-bridge")

    async def stop(self) -> None:
        task, self._task = self._task, None  # claim before the await
        await cancel_join(task)
        await self.flush()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            await self.flush()
