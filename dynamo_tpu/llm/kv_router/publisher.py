"""Worker-side KV event + metrics publishing.

Reference lib/llm/src/kv_router/publisher.rs:33-137 (KvEventPublisher →
NATS ``kv_events``; KvMetricsPublisher → ``load_metrics`` endpoint + stats)
and the vLLM-patch ``event_manager.py`` → C FFI path the reference needs to
get events OUT of the engine process. Here the engine is in-process, so the
publisher drains ``PageManager.drain_events()`` directly — no FFI shim.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ...engine.jax_engine import JaxEngine
from ...runtime.dcp_client import DcpClient, pack
from .protocols import KV_EVENT_SUBJECT, KvCacheEventWire

log = logging.getLogger("dynamo_tpu.kv_router.publisher")


class KvEventPublisher:
    """Periodically drains engine KV events onto the bus subject
    ``<namespace>.<component>.kv_events``."""

    def __init__(self, dcp: DcpClient, namespace: str, component: str,
                 worker_id: int, engine: JaxEngine,
                 interval: float = 0.25):
        self.dcp = dcp
        self.subject = f"{namespace}.{component}.{KV_EVENT_SUBJECT}"
        self.worker_id = worker_id
        self.engine = engine
        self.interval = interval
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None
        await self.flush()

    async def flush(self) -> None:
        events = self.engine.pm.drain_events()
        if not events:
            return
        payload = pack([
            KvCacheEventWire(worker_id=self.worker_id, kind=e.kind,
                             block_hashes=e.block_hashes,
                             parent_hash=e.parent_hash).to_dict()
            for e in events])
        try:
            await self.dcp.publish(self.subject, payload)
        except Exception:
            log.exception("kv event publish failed")

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            await self.flush()
