"""KV-router wire protocols.

Reference lib/llm/src/kv_router/protocols.rs:18-97: ``ForwardPassMetrics``
(worker load snapshot), ``KvCacheEvent`` (Stored/Removed block updates),
and the hit-rate event emitted per routing decision (scheduler.rs:27-32).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

KV_EVENT_SUBJECT = "kv_events"       # published under <ns>.<component>.
KV_HIT_RATE_SUBJECT = "kv-hit-rate"  # router observability events


@dataclass
class ForwardPassMetrics:
    """Per-worker load snapshot (reference protocols.rs:18-30)."""

    # dynashard replica identity: the engine's stable per-replica label
    # (e.g. "r0") and submesh geometry. The label becomes the `replica`
    # Prometheus label when set — instance ids (lease hex) are unique
    # but change on every restart, so N-replicas-in-one-process dashboards
    # key on this instead (ISSUE 12 satellite: metric identity).
    worker_label: str = ""
    mesh_shape: str = ""
    mesh_devices: int = 1
    # dynaslo: the worker's serving role (prefill|decode|unified). The
    # KV scheduler never routes token requests to a prefill-role worker
    # (disagg prefill capacity is fed from the shared queue, not the
    # router), the planner's P/D rebalance policy counts roles, and the
    # aggregator labels every merged latency histogram with it.
    role: str = "unified"
    # dynaslo: per-role mergeable latency histograms
    # ({role: {ttft|itl|queue_wait|e2e: wire histogram}}) recorded by
    # the worker and MERGED by the metrics aggregator into the first
    # fleet-wide latency quantiles (runtime/slo.py fixed bucket grid:
    # lossless merge, nearest-bucket quantiles).
    latency_hist: dict = field(default_factory=dict)
    # dynarevive graceful drain: 1 while the worker is finishing its
    # in-flight sequences after withdrawing from discovery. Draining ≠
    # dead — the stats plane keeps answering (no breaker opens) and the
    # scheduler simply stops offering this worker new requests.
    draining: int = 0
    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    # dynacache: the headline hit rate is WINDOWED (recent admissions);
    # the lifetime ratio and the raw token totals ride alongside
    gpu_prefix_cache_hit_rate: float = 0.0
    gpu_prefix_cache_hit_rate_lifetime: float = 0.0
    prefix_hit_tokens_total: int = 0
    prompt_tokens_total: int = 0
    # dynacache lifecycle counters (engine PageManager.cache_stats()):
    # allocation prefix split, eviction fates + block age, host-tier
    # evictions, restore-queue depth and drain latency
    cache_device_hit_blocks_total: int = 0
    cache_host_restored_blocks_total: int = 0
    cache_fresh_blocks_total: int = 0
    cache_evict_offloaded_total: int = 0
    cache_evict_dropped_total: int = 0
    cache_evict_age_seconds_total: float = 0.0
    cache_host_evictions_total: int = 0
    cache_restore_queue_depth: int = 0
    cache_restores_drained_total: int = 0
    cache_restore_wait_seconds_total: float = 0.0
    # dynaheat restore batching: drained batches + pages per batch (mean
    # batch size = pages/batches — the coalescing win)
    cache_restore_batches_total: int = 0
    cache_restore_batch_pages_total: int = 0
    # self-speculative decoding observability (engine/spec_decode.py):
    # accepted/drafted tokens, and accepted drafts per verify step
    spec_decode_acceptance_rate: float = 0.0
    spec_decode_mean_accepted_len: float = 0.0
    # compile fence (engine/jit_fence.py): XLA compiles observed after
    # warmup() — any nonzero value means a worker broke the zero-compile
    # serving invariant and stalled its in-flight requests
    post_warmup_compiles_total: int = 0
    # disaggregation transfer plane (llm/disagg/transfer.py streaming
    # chunk pipeline): decode-side ingest volume/time + the remote-prefill
    # wait the decode engine accumulates (enqueue → KV committed)
    kv_transfer_bytes_total: int = 0
    kv_transfer_chunks_total: int = 0
    kv_transfer_inject_seconds_total: float = 0.0
    kv_transfer_streams_failed_total: int = 0
    remote_prefill_wait_seconds_total: float = 0.0
    # engine internals that existed in stats() but never reached
    # Prometheus before dynaprof: admission-queue wait, free/cached HBM
    # pages, the host offload tier, long-context prefills
    queue_wait_seconds_total: float = 0.0
    kv_free_blocks: int = 0
    kv_cached_blocks: int = 0
    host_free_blocks: int = 0
    host_cache_usage_perc: float = 0.0
    host_offload_pages_total: int = 0
    host_restore_pages_total: int = 0
    long_prefills_total: int = 0
    # dynaprof (engine/profiler.py + runtime/profiling.py): event-loop
    # lag percentiles, sampled device/host split, per-bucket program
    # cost table ("kind:BxP..." -> {samples, dispatch_us, device_us,
    # tokens_per_s}), and the attribution conservation counter
    loop_lag_p50_seconds: float = 0.0
    loop_lag_p99_seconds: float = 0.0
    device_time_fraction: float = 0.0
    profiled_steps_total: int = 0
    batch_dispatches_total: int = 0
    bucket_cost: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict) -> "ForwardPassMetrics":
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**{k: v for k, v in d.items() if k in known})


# Engine ``stats()`` keys that deliberately do NOT ride ForwardPassMetrics
# into a Prometheus gauge, with the reason. The dynacache sync-gate test
# (tests/test_cache_obs.py) asserts every numeric stats() key is either an
# FPM field (and rendered by the aggregator) or listed here — so a new
# stats counter can never silently stop at the stats plane again (the
# drift class PR 10 found by hand).
STATS_PROMETHEUS_SKIP = {
    "spec_decode_steps":
        "raw counter folded into spec_decode_mean_accepted_len",
    "spec_decode_draft_tokens_total":
        "raw counter folded into spec_decode_acceptance_rate",
    "spec_decode_accepted_tokens_total":
        "raw counter folded into spec_decode_acceptance_rate",
}


@dataclass
class KvCacheEventWire:
    """Stored/Removed event as published on the bus (reference
    protocols.rs KvCacheEvent + the worker id tag added on receive)."""

    worker_id: int
    kind: str                        # "stored" | "removed"
    block_hashes: List[int]
    parent_hash: Optional[int] = None

    def to_dict(self) -> dict:
        return {"worker_id": self.worker_id, "kind": self.kind,
                "block_hashes": self.block_hashes,
                "parent_hash": self.parent_hash}

    @classmethod
    def from_dict(cls, d: dict) -> "KvCacheEventWire":
        return cls(worker_id=d["worker_id"], kind=d["kind"],
                   block_hashes=list(d["block_hashes"]),
                   parent_hash=d.get("parent_hash"))


@dataclass
class KVHitRateEvent:
    """Per-decision observability event (reference scheduler.rs:27-32)."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int

    def to_dict(self) -> dict:
        return dict(self.__dict__)
