"""`llmctl` — model registration CLI.

Reference launch/llmctl/src/main.rs:26-80: writes/removes ``ModelEntry``
records in the KV store; the frontend's model watcher reacts by
(un)registering engines.

    python -m dynamo_tpu llmctl http add chat-models <name> <dyn://endpoint>
    python -m dynamo_tpu llmctl http remove chat-models <name>
    python -m dynamo_tpu llmctl http list
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from typing import List, Optional

from ..runtime.config import env_str
from ..runtime.dcp_client import DcpClient
from .entry import ModelEntry, list_models, register_model, remove_model

_KIND_TO_TYPE = {"chat-models": "chat", "completion-models": "completions",
                 "completions-models": "completions", "models": "both"}


async def amain(args) -> int:
    address = args.dcp or env_str("DYN_DCP_ADDRESS", "127.0.0.1:6650")
    dcp = await DcpClient.connect(address)
    try:
        if args.verb == "add":
            mtype = _KIND_TO_TYPE.get(args.kind, "chat")
            await register_model(dcp, ModelEntry(
                name=args.name, endpoint=args.endpoint, model_type=mtype))
            print(f"added {mtype} model {args.name!r} -> {args.endpoint}")
        elif args.verb == "remove":
            mtype = _KIND_TO_TYPE.get(args.kind, "chat")
            ok = await remove_model(dcp, args.name, mtype)
            print(f"{'removed' if ok else 'not found:'} {args.name!r}")
            return 0 if ok else 1
        elif args.verb == "list":
            for e in await list_models(dcp):
                print(f"{e.model_type:12s} {e.name:40s} {e.endpoint}")
    finally:
        await dcp.close()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="llmctl")
    ap.add_argument("--dcp", default=None, help="control-plane address")
    sub = ap.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http")
    vsub = http.add_subparsers(dest="verb", required=True)
    add = vsub.add_parser("add")
    add.add_argument("kind", choices=list(_KIND_TO_TYPE))
    add.add_argument("name")
    add.add_argument("endpoint")
    rm = vsub.add_parser("remove")
    rm.add_argument("kind", choices=list(_KIND_TO_TYPE))
    rm.add_argument("name")
    vsub.add_parser("list")
    args = ap.parse_args(argv)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
