"""Engine adapters: local chains and remote forwarding.

Reference lib/llm/src/engines.rs + the pipeline links in
launch/dynamo-run/src/input/http.rs: a "full" engine speaks OpenAI types
directly; a "core" engine speaks token-level types and is wrapped by
``OpenAIPreprocessor`` + ``Backend``. ``RemoteOpenAIEngine`` is the analog
of the frontend's remote client engine (http/service/discovery.rs:36-56):
it forwards OpenAI requests over the distributed runtime to a worker.
"""

from __future__ import annotations

import logging
from typing import AsyncIterator, Optional

from ..runtime.component import Client
from ..runtime.engine import Annotated, Context
from .backend import Backend
from .model_card import ModelDeploymentCard
from .preprocessor import completion_logprobs, OpenAIPreprocessor
from .protocols.openai import (ChatCompletionRequest, CompletionRequest,
                               _finish_reason_openai)

log = logging.getLogger("dynamo_tpu.engines")


def usage_cost(context: Context):
    """dynaprof usage extension: the request's cost-attribution block,
    when DYN_PROF_USAGE is on and the engine (local or remote via the
    Backend relay) recorded one — else None, and the usage payload
    stays byte-for-byte OpenAI-shaped."""
    from ..runtime.config import env_bool
    from ..runtime import profiling

    if not env_bool("DYN_PROF_USAGE"):
        return None
    return profiling.request_attribution(context.id)


class LocalChatChain:
    """preprocessor → backend → core engine, in-process (reference
    EngineConfig::StaticCore pipeline: ServiceFrontend → OpenAIPreprocessor →
    Backend → ExecutionContext)."""

    def __init__(self, mdc: ModelDeploymentCard, core_engine,
                 preprocessor: Optional[OpenAIPreprocessor] = None):
        self.mdc = mdc
        self.preprocessor = preprocessor or OpenAIPreprocessor(mdc)
        self.backend = Backend(core_engine, self.preprocessor.tokenizer)

    def __call__(self, request: ChatCompletionRequest,
                 context: Context) -> AsyncIterator:
        return self._run(request, context)

    async def _run(self, request: ChatCompletionRequest, context: Context):
        pre, annotations = self.preprocessor.preprocess_chat(request)
        for ann in annotations:
            yield ann
        engine_stream = self.backend.generate(pre, context)
        async for chunk in self.preprocessor.chat_stream(
                request, engine_stream, context, len(pre.token_ids)):
            yield chunk


class LocalCompletionChain:
    """Same chain for the /v1/completions endpoint."""

    def __init__(self, mdc: ModelDeploymentCard, core_engine,
                 preprocessor: Optional[OpenAIPreprocessor] = None):
        self.mdc = mdc
        self.preprocessor = preprocessor or OpenAIPreprocessor(mdc)
        self.backend = Backend(core_engine, self.preprocessor.tokenizer)

    def __call__(self, request: CompletionRequest,
                 context: Context) -> AsyncIterator:
        return self._run(request, context)

    async def _run(self, request: CompletionRequest, context: Context):
        import time as _time
        import uuid as _uuid

        pre, annotations = self.preprocessor.preprocess_completion(request)
        for ann in annotations:
            yield ann
        rid = f"cmpl-{context.id or _uuid.uuid4().hex}"
        created = int(_time.time())
        completion_tokens = 0
        text_off = 0
        if pre.output.echo_prompt:
            # OpenAI completions echo=true: the response text starts with
            # the prompt (reconstructed from the request token ids so
            # pre-tokenized prompts echo too); generated-token offsets
            # then start AFTER it
            echo_text = self.preprocessor.tokenizer.decode(
                list(pre.token_ids))
            text_off = len(echo_text)
            yield {
                "id": rid, "object": "text_completion", "created": created,
                "model": request.model,
                "choices": [{"index": 0, "text": echo_text,
                             "finish_reason": None}],
            }
        async for out in self.backend.generate(pre, context):
            completion_tokens += len(out.token_ids)
            if out.text or out.finish_reason or out.logprobs:
                choice = {"index": 0, "text": out.text or "",
                          "finish_reason":
                              _finish_reason_openai(out.finish_reason)}
                lp = completion_logprobs(out, self.preprocessor.tokenizer, text_off)
                if lp:
                    choice["logprobs"] = lp
                text_off += len(out.text or "")
                yield {"id": rid, "object": "text_completion",
                       "created": created, "model": request.model,
                       "choices": [choice]}
            if out.finish_reason:
                if request.stream_options and request.stream_options.include_usage:
                    usage = {
                        "prompt_tokens": len(pre.token_ids),
                        "completion_tokens": completion_tokens,
                        "total_tokens":
                            len(pre.token_ids) + completion_tokens}
                    cost = usage_cost(context)
                    if cost is not None:
                        usage["cost"] = cost
                    yield {"id": rid, "object": "text_completion",
                           "created": created, "model": request.model,
                           "choices": [], "usage": usage}
                return


class RemoteOpenAIEngine:
    """Forwards OpenAI-level requests to a worker endpoint over the
    distributed runtime; the worker streams chunk dicts back in Annotated
    envelopes. ``mode``/``instance_id`` select routing."""

    def __init__(self, client: Client, mode: str = "round_robin"):
        self.client = client
        self.mode = mode

    def __call__(self, request, context: Context) -> AsyncIterator:
        return self._run(request, context)

    async def _run(self, request, context: Context):
        payload = request.model_dump(exclude_none=True) \
            if hasattr(request, "model_dump") else request
        stream = await self.client.generate(
            payload, mode=self.mode, context=context)
        try:
            async for env in stream:
                yield env
        finally:
            if context.killed:
                await stream.kill()
            elif context.stopped:
                await stream.stop_generating()
