"""LLM layer: OpenAI protocol, HTTP frontend, preprocessing, detokenizing
backend, model cards, KV routing. Reference: lib/llm/src/."""

from .backend import Backend
from .engines import LocalChatChain, LocalCompletionChain, RemoteOpenAIEngine
from .entry import ModelEntry, list_models, register_model, remove_model
from .model_card import ModelDeploymentCard
from .preprocessor import OpenAIPreprocessor
from .tokenizer import ByteTokenizer, DecodeStream, HFTokenizer, Tokenizer
from .worker import serve_openai_model
