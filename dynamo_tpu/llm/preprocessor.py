"""OpenAI → internal translation + response post-processing.

Reference lib/llm/src/preprocessor.rs:63-356 (``OpenAIPreprocessor``):
renders the chat template, tokenizes, maps sampling/stop options into the
internal ``PreprocessedRequest``, emits request annotations
(``formatted_prompt``, ``token_ids``), and on the way back transforms the
token-level engine stream into OpenAI SSE deltas / full responses.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional, Tuple

from ..runtime import tracing
from ..runtime.engine import Annotated, Context
from .model_card import ModelDeploymentCard
from .protocols.common import (EngineOutput, OutputOptions, PreprocessedRequest,
                               SamplingOptions, StopConditions)
from .protocols.openai import (ChatCompletionChunk, ChatCompletionRequest,
                               ChatDeltaGenerator, CompletionRequest, Usage)
from .tokenizer import Tokenizer

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


class OpenAIPreprocessor:
    """Stateless translator bound to one model card + tokenizer."""

    def __init__(self, mdc: ModelDeploymentCard, tokenizer: Optional[Tokenizer] = None):
        self.mdc = mdc
        self.tokenizer = tokenizer or mdc.load_tokenizer()
        self._mdcsum = mdc.mdcsum()

    # ------------------------------------------------------------ requests

    def preprocess_chat(
        self, request: ChatCompletionRequest
    ) -> Tuple[PreprocessedRequest, List[Annotated]]:
        with tracing.get_tracer().start_span("preprocess") as span:
            ext = request.extension()
            if ext.use_raw_prompt and request.messages:
                prompt = "".join(m.text() for m in request.messages)
            else:
                prompt = self.tokenizer.apply_chat_template(
                    [{"role": m.role, "content": m.text()}
                     for m in request.messages],
                    add_generation_prompt=True)
            token_ids = self.tokenizer.encode(prompt)
            span.set_attribute("tokens", len(token_ids))
            pre = self._build(request, token_ids, request.max_output_tokens())
            annotations = self._annotations(ext.annotations or [], prompt,
                                            token_ids)
            return pre, annotations

    def preprocess_completion(
        self, request: CompletionRequest
    ) -> Tuple[PreprocessedRequest, List[Annotated]]:
        with tracing.get_tracer().start_span("preprocess") as span:
            return self._preprocess_completion(request, span)

    def _preprocess_completion(
        self, request: CompletionRequest, span
    ) -> Tuple[PreprocessedRequest, List[Annotated]]:
        ext = request.extension()
        prompt = request.prompt
        prompt_text: Optional[str] = None
        if isinstance(prompt, str):
            prompt_text = prompt
            token_ids = self.tokenizer.encode(prompt_text)
        elif isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)  # pre-tokenized prompt
        elif isinstance(prompt, list) and len(prompt) == 1:
            inner = prompt[0]
            if isinstance(inner, str):
                prompt_text = inner
                token_ids = self.tokenizer.encode(prompt_text)
            else:
                token_ids = list(inner)
        elif isinstance(prompt, list) and len(prompt) > 1:
            raise ValueError(
                "batch prompts (multiple prompts per request) are not "
                "supported; send one request per prompt")
        else:
            raise ValueError("prompt must be a non-empty string or token list")
        span.set_attribute("tokens", len(token_ids))
        pre = self._build(request, token_ids, request.max_tokens)
        annotations = self._annotations(
            ext.annotations or [], prompt_text or "", token_ids)
        return pre, annotations

    def _build(self, request, token_ids: List[int],
               max_tokens: Optional[int]) -> PreprocessedRequest:
        ext = request.extension()
        budget = self.mdc.context_length - len(token_ids)
        if budget <= 0:
            raise ValueError(
                f"prompt ({len(token_ids)} tokens) exceeds the model context "
                f"length ({self.mdc.context_length})")
        sampling = SamplingOptions(
            temperature=request.temperature, top_p=request.top_p,
            top_k=getattr(request, "top_k", None),
            frequency_penalty=request.frequency_penalty,
            presence_penalty=request.presence_penalty,
            repetition_penalty=getattr(request, "repetition_penalty", None),
            logit_bias=({int(k): float(v)
                         for k, v in request.logit_bias.items()}
                        if getattr(request, "logit_bias", None) else None),
            seed=request.seed, n=request.n or 1)
        if ext.greedy_sampling:
            sampling.temperature = 0.0
        if max_tokens is not None and max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        stop = StopConditions(
            max_tokens=min(max_tokens, budget) if max_tokens is not None else budget,
            stop=request.stop_list(),
            min_tokens=getattr(request, "min_tokens", None),
            ignore_eos=bool(ext.ignore_eos))
        raw_logprobs = getattr(request, "logprobs", None)
        top_lp: Optional[int] = getattr(request, "top_logprobs", None)
        if top_lp is not None and raw_logprobs is not True:
            # OpenAI: top_logprobs requires logprobs=true (400 otherwise)
            raise ValueError("top_logprobs requires logprobs to be true")
        logprobs: Optional[int] = top_lp
        if logprobs is None:
            if raw_logprobs is True:
                logprobs = 0  # sampled-token logprob only
            elif isinstance(raw_logprobs, int) and not isinstance(raw_logprobs, bool):
                logprobs = raw_logprobs  # completions-style integer
        if logprobs is not None and not 0 <= logprobs <= 20:
            raise ValueError("logprobs/top_logprobs must be between 0 "
                             "and 20")
        output = OutputOptions(
            logprobs=logprobs, echo_prompt=bool(getattr(request, "echo", False)))
        return PreprocessedRequest(
            token_ids=token_ids, sampling=sampling, stop=stop, output=output,
            eos_token_ids=list(self.tokenizer.eos_token_ids),
            mdc_sum=self._mdcsum,
            annotations=list(ext.annotations or []))

    def _annotations(self, requested: List[str], prompt: str,
                     token_ids: List[int]) -> List[Annotated]:
        out = []
        if ANNOTATION_FORMATTED_PROMPT in requested:
            out.append(Annotated.from_annotation(ANNOTATION_FORMATTED_PROMPT, prompt))
        if ANNOTATION_TOKEN_IDS in requested:
            out.append(Annotated.from_annotation(ANNOTATION_TOKEN_IDS, token_ids))
        return out

    # ----------------------------------------------------------- responses

    async def chat_stream(
        self,
        request: ChatCompletionRequest,
        engine_stream: AsyncIterator[EngineOutput],
        context: Context,
        prompt_tokens: int,
    ) -> AsyncIterator[ChatCompletionChunk]:
        """Map the backend's EngineOutput stream to OpenAI chat chunks
        (reference preprocessor.rs transform_postprocessor_stream:176-243)."""
        gen = ChatDeltaGenerator(request.model, context.id)
        yield gen.role_chunk()
        completion_tokens = 0
        finish: Optional[str] = None
        async for out in engine_stream:
            completion_tokens += len(out.token_ids)
            if out.completion_tokens is not None:
                completion_tokens = out.completion_tokens
            lp = chat_logprobs_content(out, self.tokenizer)
            if out.text or out.finish_reason or lp:
                yield gen.content_chunk(out.text or "", out.finish_reason,
                                        logprobs=lp)
            if out.finish_reason:
                finish = out.finish_reason
                break
        if finish is None:
            yield gen.content_chunk("", "stop")
        if request.stream_options and request.stream_options.include_usage:
            from .engines import usage_cost

            yield gen.usage_chunk(Usage(
                prompt_tokens=prompt_tokens,
                completion_tokens=completion_tokens,
                total_tokens=prompt_tokens + completion_tokens,
                cost=usage_cost(context)))

def chat_logprobs_content(out, tokenizer) -> Optional[dict]:
    """EngineOutput logprob fields → the OpenAI chat ``logprobs`` object
    ({"content": [{token, logprob, bytes, top_logprobs}]}). None when
    the request didn't ask (the engine attaches fields only then).
    Logprobs describe the RAW model distribution (docs: sampling
    penalties/temperature are not reflected)."""
    if not out.logprobs or not out.token_ids:
        return None

    # "bytes" derives from the DECODED string: byte-fallback tokens that
    # split a multi-byte character decode to U+FFFD, so their bytes show
    # the replacement character, not the raw token bytes — a documented
    # fidelity limit of this surface
    def entry(tid: int, lp: float, tops: dict) -> dict:
        s = tokenizer.decode([int(tid)])
        return {"token": s, "logprob": lp, "bytes": list(s.encode()),
                "top_logprobs": [
                    {"token": tokenizer.decode([int(t)]), "logprob": v,
                     "bytes": list(tokenizer.decode([int(t)]).encode())}
                    for t, v in (tops or {}).items()]}

    tops_list = out.top_logprobs or [{}] * len(out.token_ids)
    return {"content": [entry(t, lp, tp) for t, lp, tp in
                        zip(out.token_ids, out.logprobs, tops_list)]}


def completion_logprobs(out, tokenizer, offset: int) -> Optional[dict]:
    """Legacy completions logprobs object: parallel ``tokens`` /
    ``token_logprobs`` / ``top_logprobs`` / ``text_offset`` lists.

    ``offset`` is the caller's position in the ASSEMBLED response text
    (echoed prompt included) at the start of this chunk; every token in
    the chunk reports that offset. The engine emits one token per chunk,
    so this is exact in practice — per-token decode lengths must NOT be
    used here: the incremental detokenizer's emitted text differs from
    the concatenation of single-token decodes (held UTF-8 bytes, jailed
    stop prefixes), and offsets derived from it drift off the text."""
    if not out.logprobs or not out.token_ids:
        return None
    tokens, t_lps, tops, offs = [], [], [], []
    tops_list = out.top_logprobs or [{}] * len(out.token_ids)
    for tid, lp, tp in zip(out.token_ids, out.logprobs, tops_list):
        tokens.append(tokenizer.decode([int(tid)]))
        t_lps.append(lp)
        tops.append({tokenizer.decode([int(t)]): v
                     for t, v in (tp or {}).items()})
        offs.append(offset)
    return {"tokens": tokens, "token_logprobs": t_lps,
            "top_logprobs": tops, "text_offset": offs}
