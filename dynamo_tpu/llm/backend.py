"""Backend: the detokenizing stage between engine and preprocessor.

Reference lib/llm/src/backend.rs:58-120 + ``Decoder``: wraps the token-level
engine (``ExecutionContext``); incrementally detokenizes the stream, applies
stop-sequence "jailing" (text that could be the prefix of a stop sequence is
withheld until disambiguated), detects EOS / stop-token / max-token finishes,
and stamps finish reasons.
"""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor
from typing import AsyncIterator, List, Optional

from ..runtime import profiling
from ..runtime.config import env_bool
from ..runtime.engine import Context
from .protocols.common import (FINISH_EOS, FINISH_LENGTH, FINISH_STOP,
                               EngineOutput, PreprocessedRequest)
from .tokenizer import Tokenizer

# Shared detokenization executor (dynaturbo change 4): token→text work for
# every stream runs here instead of on the event-loop thread, so a slow
# decode never inflates OTHER streams' inter-chunk latency. Per-request
# ordering needs no queue machinery: Backend.generate awaits each chunk's
# decode before pulling the next engine chunk, so a request never has two
# decodes in flight (an ordered queue of depth one); the DecodeStream's
# state is therefore only ever touched by one thread at a time.
_DETOK_EXEC: Optional[ThreadPoolExecutor] = None


def _detok_executor() -> ThreadPoolExecutor:
    global _DETOK_EXEC
    if _DETOK_EXEC is None:
        _DETOK_EXEC = ThreadPoolExecutor(max_workers=2,
                                         thread_name_prefix="dyn-detok")
    return _DETOK_EXEC


def _decode_many(decode, ids: List[int]) -> str:
    return "".join(p for p in map(decode.step, ids) if p)


class StopSequenceJail:
    """Holds back emitted text while it matches a proper prefix of any stop
    sequence; releases or truncates once disambiguated (reference backend.rs
    toktrie-based jail)."""

    def __init__(self, stop: List[str]):
        self._stop = [s for s in stop if s]
        self._held = ""

    def feed(self, text: str) -> tuple[str, bool]:
        """Returns (releasable_text, hit_stop)."""
        if not self._stop:
            return text, False
        buf = self._held + text
        # full stop sequence present → truncate at the earliest match
        cut = -1
        for s in self._stop:
            i = buf.find(s)
            if i != -1 and (cut == -1 or i < cut):
                cut = i
        if cut != -1:
            self._held = ""
            return buf[:cut], True
        # otherwise hold the longest suffix that is a prefix of some stop seq
        hold = 0
        for s in self._stop:
            for k in range(min(len(s) - 1, len(buf)), 0, -1):
                if buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._held = buf[-hold:]
            return buf[:-hold], False
        self._held = ""
        return buf, False

    def flush(self) -> str:
        out, self._held = self._held, ""
        return out


class Backend:
    """Engine wrapper adding detokenization + stop handling.

    ``engine.generate(PreprocessedRequest, Context)`` must yield
    ``EngineOutput`` (or dicts thereof) with ``token_ids`` deltas; this
    stage fills ``text`` and ``finish_reason``.
    """

    # bound (seconds) on draining the engine's in-flight finish chunk
    # after a Backend-side stop: ~an engine iteration, never a hang
    COST_HARVEST_BOUND_S = 0.25

    def __init__(self, engine, tokenizer: Tokenizer):
        self.engine = engine
        self.tokenizer = tokenizer

    async def _harvest_finish_cost(self, agen, context):
        """Drain a few more engine chunks (bounded) for the cost block
        riding the engine's own finish; registers + returns it, or None
        on timeout/exhaustion. Without this, any request the Backend
        finishes first (length cap, eos) would lose its remote cost
        attribution — /v1/traces/{rid} on the frontend, the usage
        extension and the KV router's predicted-vs-realized calibration
        all feed off this block (found live by the dynashard
        multi-process verify: cost never crossed the wire)."""
        try:
            while True:
                raw = await asyncio.wait_for(agen.__anext__(),
                                             self.COST_HARVEST_BOUND_S)
                out = raw if isinstance(raw, EngineOutput) \
                    else EngineOutput.from_dict(raw)
                if out.cost is not None:
                    profiling.record_attribution(context.id, out.cost)
                    return out.cost
                if out.finish_reason:
                    return None
        except (StopAsyncIteration, asyncio.TimeoutError):
            return None

    async def generate(self, request: PreprocessedRequest,
                       context: Context) -> AsyncIterator[EngineOutput]:
        decode = self.tokenizer.decode_stream(
            skip_special_tokens=request.output.skip_special_tokens)
        jail = StopSequenceJail(request.stop.stop or [])
        eos_ids = set() if request.stop.ignore_eos else set(request.eos_token_ids)
        stop_ids = set(request.stop.stop_token_ids or [])
        max_tokens = request.stop.max_tokens
        min_tokens = request.stop.min_tokens or 0
        produced = 0
        finished: Optional[str] = None

        if max_tokens is not None and max_tokens < 1:
            yield EngineOutput(token_ids=[], text="", finish_reason=FINISH_LENGTH,
                               completion_tokens=0)
            context.stop_generating()
            return

        def _final_text(released: str, stop_seq_hit: bool) -> str:
            """Append held decoder/jail text to the finish-bearing chunk
            (downstream consumers stop at the first finish_reason). When a
            stop STRING matched, the jail already truncated at the match and
            held text is intentionally dropped; every other finish (eos,
            stop TOKEN, length, cancel) must flush held text."""
            if stop_seq_hit:
                return released
            tail, _ = jail.feed(decode.flush())
            return released + tail + jail.flush()

        offload = env_bool("DYN_ASYNC_DETOK")
        loop = asyncio.get_running_loop() if offload else None

        agen = _aiter(self.engine.generate(request, context))
        async for raw in agen:
            out = raw if isinstance(raw, EngineOutput) else EngineOutput.from_dict(raw)
            if out.cost is not None:
                # remote workers attach dynaprof cost attribution to the
                # finish chunk; registering it here makes the FRONTEND
                # process's /v1/traces/{rid} and usage extension work even
                # when the engine ran in another process
                profiling.record_attribution(context.id, out.cost)
            # Stop checks are pure host arithmetic and stay inline: they
            # decide which ids are even eligible for decoding (skipped
            # eos under skip_special_tokens, nothing past the finish).
            # Only the tokenizer work ships to the detok executor.
            emit_ids: List[int] = []
            decode_ids: List[int] = []
            for tid in out.token_ids:
                produced += 1
                is_eos = tid in eos_ids and produced >= min_tokens
                is_stop_tok = tid in stop_ids and produced >= min_tokens
                if not (is_eos and request.output.skip_special_tokens):
                    decode_ids.append(tid)
                emit_ids.append(tid)
                if is_eos:
                    finished = FINISH_EOS
                elif is_stop_tok:
                    finished = FINISH_STOP
                elif max_tokens is not None and produced >= max_tokens:
                    finished = FINISH_LENGTH
                if finished:
                    break
            if not decode_ids:
                text = ""
            elif offload:
                # awaited before the next engine chunk is pulled — the
                # per-request decode order is preserved by construction
                text = await loop.run_in_executor(
                    _detok_executor(), _decode_many, decode, decode_ids)
            else:
                text = _decode_many(decode, decode_ids)
            released, hit = jail.feed(text) if text else ("", False)
            if hit:
                finished = finished or FINISH_STOP
            out.token_ids = emit_ids
            out.finish_reason = finished or out.finish_reason
            out.completion_tokens = produced
            if out.finish_reason:
                out.text = _final_text(released, stop_seq_hit=hit)
                if out.cost is None and finished is not None and not hit:
                    # the Backend's own stop (token cap / eos / stop
                    # token) fired BEFORE the engine's finish chunk —
                    # the chunk that carries the dynaprof cost block
                    # (replica, prefix split). The engine enforces the
                    # same budget/eos on device, so its finish is
                    # already in flight: drain it (bounded) so remote
                    # cost attribution still lands in this process's
                    # ring. Skipped for stop-STRING matches (`hit`) —
                    # the engine doesn't know host-side stop sequences
                    # and would not finish within the bound.
                    out.cost = await self._harvest_finish_cost(
                        agen, context)
                yield out
                context.stop_generating()
                return
            out.text = released
            yield out
            if context.stopped:
                # deadline expiry finishes as "timeout" (client-visible),
                # caller cancellation as "cancelled"
                context.stop_generating()
                yield EngineOutput(text=_final_text("", False) or None,
                                   finish_reason=context.cancel_reason(),
                                   completion_tokens=produced)
                return
        # engine stream exhausted without a finish reason: flush held text and
        # stamp a terminal reason so downstream never fabricates one
        yield EngineOutput(token_ids=[], text=_final_text("", False) or "",
                           finish_reason=FINISH_STOP, completion_tokens=produced)


async def _aiter(gen):
    """Engines may return an async generator directly or a coroutine that
    resolves to one."""
    if hasattr(gen, "__aiter__"):
        async for item in gen:
            yield item
    else:
        async for item in await gen:
            yield item
