"""Prometheus metrics for the HTTP frontend (hand-rolled text exposition).

Reference lib/llm/src/http/service/metrics.rs:82-260:
``dyn_llm_http_service_requests_total{model,endpoint,request_type,status}``,
``..._inflight_requests{model}``, ``..._request_duration_seconds{model}``
histogram, and the RAII ``InflightGuard`` that stamps status on drop.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Tuple

PREFIX = "dyn_llm_http_service"

# histogram buckets in seconds (reference uses prometheus defaults + LLM tail)
BUCKETS = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
           30.0, 60.0, 120.0, 300.0]
# inter-token-latency buckets: tuned for token cadence (ms-scale steady
# state, sub-second tail when a decode window or preemption stalls a
# stream) — the request-scale BUCKETS would collapse all ITLs into the
# first two buckets
ITL_BUCKETS = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
               1.0, 2.5]
# per-stage (trace span) durations: sub-ms transfer stages up to
# multi-second prefills
STAGE_BUCKETS = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0]


class _Histogram:
    """One labeled histogram family (cumulative buckets + sum + count)."""

    def __init__(self, buckets: List[float]):
        self.ubs = buckets
        self.buckets: Dict[str, List[int]] = defaultdict(
            lambda: [0] * (len(buckets) + 1))
        self.sum: Dict[str, float] = defaultdict(float)
        self.count: Dict[str, int] = defaultdict(int)

    def observe(self, label: str, value: float) -> None:
        self.sum[label] += value
        self.count[label] += 1
        b = self.buckets[label]
        for i, ub in enumerate(self.ubs):
            if value <= ub:
                b[i] += 1
        b[-1] += 1  # +Inf

    def render(self, lines: List[str], metric: str, label_key: str) -> None:
        for label in sorted(self.count):
            for i, ub in enumerate(self.ubs):
                lines.append(
                    f'{metric}_bucket{{{label_key}="{label}",le="{ub}"}} '
                    f'{self.buckets[label][i]}')
            lines.append(
                f'{metric}_bucket{{{label_key}="{label}",le="+Inf"}} '
                f'{self.buckets[label][-1]}')
            lines.append(f'{metric}_sum{{{label_key}="{label}"}} '
                         f'{self.sum[label]}')
            lines.append(f'{metric}_count{{{label_key}="{label}"}} '
                         f'{self.count[label]}')


class Metrics:
    def __init__(self) -> None:
        self.requests_total: Dict[Tuple[str, str, str, str], int] = defaultdict(int)
        self.inflight: Dict[str, int] = defaultdict(int)
        self.duration_buckets: Dict[str, List[int]] = defaultdict(
            lambda: [0] * (len(BUCKETS) + 1))
        self.duration_sum: Dict[str, float] = defaultdict(float)
        self.duration_count: Dict[str, int] = defaultdict(int)
        # streaming metrics
        self.ttft_sum: Dict[str, float] = defaultdict(float)
        self.ttft_count: Dict[str, int] = defaultdict(int)
        self.output_tokens_total: Dict[str, int] = defaultdict(int)
        # inter-token latency (streamed requests, gap between successive
        # token-bearing chunks) — the pair metric TTFT alone can't show
        self.itl = _Histogram(ITL_BUCKETS)
        # per-stage durations fed from finished dyntrace spans
        self.stage = _Histogram(STAGE_BUCKETS)

    def guard(self, model: str, endpoint: str, request_type: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type)

    def observe_duration(self, model: str, seconds: float) -> None:
        self.duration_sum[model] += seconds
        self.duration_count[model] += 1
        buckets = self.duration_buckets[model]
        for i, ub in enumerate(BUCKETS):
            if seconds <= ub:
                buckets[i] += 1
        buckets[-1] += 1  # +Inf

    def observe_ttft(self, model: str, seconds: float) -> None:
        self.ttft_sum[model] += seconds
        self.ttft_count[model] += 1

    def observe_itl(self, model: str, seconds: float) -> None:
        self.itl.observe(model, seconds)

    def observe_stage(self, stage: str, seconds: float) -> None:
        self.stage.observe(stage, seconds)

    def count_output_tokens(self, model: str, n: int) -> None:
        self.output_tokens_total[model] += n

    def render(self) -> str:
        lines: List[str] = []

        def _h(name: str, typ: str, help_: str) -> None:
            lines.append(f"# HELP {PREFIX}_{name} {help_}")
            lines.append(f"# TYPE {PREFIX}_{name} {typ}")

        _h("requests_total", "counter", "Total requests by model/endpoint/type/status")
        for (model, endpoint, rtype, status), n in sorted(self.requests_total.items()):
            lines.append(
                f'{PREFIX}_requests_total{{model="{model}",endpoint="{endpoint}",'
                f'request_type="{rtype}",status="{status}"}} {n}')
        _h("inflight_requests", "gauge", "Requests currently being processed")
        for model, n in sorted(self.inflight.items()):
            lines.append(f'{PREFIX}_inflight_requests{{model="{model}"}} {n}')
        _h("request_duration_seconds", "histogram", "Request duration")
        for model in sorted(self.duration_count):
            cum = 0
            for i, ub in enumerate(BUCKETS):
                cum = self.duration_buckets[model][i]
                lines.append(
                    f'{PREFIX}_request_duration_seconds_bucket{{model="{model}",'
                    f'le="{ub}"}} {cum}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_bucket{{model="{model}",'
                f'le="+Inf"}} {self.duration_buckets[model][-1]}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_sum{{model="{model}"}} '
                f'{self.duration_sum[model]}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_count{{model="{model}"}} '
                f'{self.duration_count[model]}')
        _h("time_to_first_token_seconds", "summary", "TTFT for streamed requests")
        for model in sorted(self.ttft_count):
            lines.append(
                f'{PREFIX}_time_to_first_token_seconds_sum{{model="{model}"}} '
                f'{self.ttft_sum[model]}')
            lines.append(
                f'{PREFIX}_time_to_first_token_seconds_count{{model="{model}"}} '
                f'{self.ttft_count[model]}')
        _h("output_tokens_total", "counter", "Total generated tokens")
        for model, n in sorted(self.output_tokens_total.items()):
            lines.append(f'{PREFIX}_output_tokens_total{{model="{model}"}} {n}')
        _h("itl_seconds", "histogram",
           "Inter-token latency for streamed requests")
        self.itl.render(lines, f"{PREFIX}_itl_seconds", "model")
        _h("stage_duration_seconds", "histogram",
           "Per-stage request durations from dyntrace spans")
        self.stage.render(lines, f"{PREFIX}_stage_duration_seconds", "stage")
        # dynaguard plane: route-fallback/hedge/deadline counters + per-
        # endpoint circuit-breaker state gauges (guard.render_prom_lines)
        from ...runtime import guard, profiling

        lines.extend(guard.render_prom_lines())
        # dynaprof plane: this process's event-loop lag + stall captures
        lines.extend(profiling.render_prom_lines())
        return "\n".join(lines) + "\n"


class InflightGuard:
    """RAII-style guard (reference metrics.rs:188-260): counts inflight and
    stamps the final status; default status is 'error' unless marked ok."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str,
                 request_type: str):
        self.metrics = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        self.status = "error"
        self.t0 = time.monotonic()
        metrics.inflight[model] += 1

    def mark_ok(self) -> None:
        self.status = "success"

    def done(self) -> None:
        m = self.metrics
        m.inflight[self.model] -= 1
        m.requests_total[(self.model, self.endpoint, self.request_type,
                          self.status)] += 1
        m.observe_duration(self.model, time.monotonic() - self.t0)
