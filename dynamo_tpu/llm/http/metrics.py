"""Prometheus metrics for the HTTP frontend (hand-rolled text exposition).

Reference lib/llm/src/http/service/metrics.rs:82-260:
``dyn_llm_http_service_requests_total{model,endpoint,request_type,status}``,
``..._inflight_requests{model}``, ``..._request_duration_seconds{model}``
histogram, and the RAII ``InflightGuard`` that stamps status on drop.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Tuple

PREFIX = "dyn_llm_http_service"

# histogram buckets in seconds (reference uses prometheus defaults + LLM tail)
BUCKETS = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
           30.0, 60.0, 120.0, 300.0]


class Metrics:
    def __init__(self) -> None:
        self.requests_total: Dict[Tuple[str, str, str, str], int] = defaultdict(int)
        self.inflight: Dict[str, int] = defaultdict(int)
        self.duration_buckets: Dict[str, List[int]] = defaultdict(
            lambda: [0] * (len(BUCKETS) + 1))
        self.duration_sum: Dict[str, float] = defaultdict(float)
        self.duration_count: Dict[str, int] = defaultdict(int)
        # streaming metrics
        self.ttft_sum: Dict[str, float] = defaultdict(float)
        self.ttft_count: Dict[str, int] = defaultdict(int)
        self.output_tokens_total: Dict[str, int] = defaultdict(int)

    def guard(self, model: str, endpoint: str, request_type: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type)

    def observe_duration(self, model: str, seconds: float) -> None:
        self.duration_sum[model] += seconds
        self.duration_count[model] += 1
        buckets = self.duration_buckets[model]
        for i, ub in enumerate(BUCKETS):
            if seconds <= ub:
                buckets[i] += 1
        buckets[-1] += 1  # +Inf

    def observe_ttft(self, model: str, seconds: float) -> None:
        self.ttft_sum[model] += seconds
        self.ttft_count[model] += 1

    def count_output_tokens(self, model: str, n: int) -> None:
        self.output_tokens_total[model] += n

    def render(self) -> str:
        lines: List[str] = []

        def _h(name: str, typ: str, help_: str) -> None:
            lines.append(f"# HELP {PREFIX}_{name} {help_}")
            lines.append(f"# TYPE {PREFIX}_{name} {typ}")

        _h("requests_total", "counter", "Total requests by model/endpoint/type/status")
        for (model, endpoint, rtype, status), n in sorted(self.requests_total.items()):
            lines.append(
                f'{PREFIX}_requests_total{{model="{model}",endpoint="{endpoint}",'
                f'request_type="{rtype}",status="{status}"}} {n}')
        _h("inflight_requests", "gauge", "Requests currently being processed")
        for model, n in sorted(self.inflight.items()):
            lines.append(f'{PREFIX}_inflight_requests{{model="{model}"}} {n}')
        _h("request_duration_seconds", "histogram", "Request duration")
        for model in sorted(self.duration_count):
            cum = 0
            for i, ub in enumerate(BUCKETS):
                cum = self.duration_buckets[model][i]
                lines.append(
                    f'{PREFIX}_request_duration_seconds_bucket{{model="{model}",'
                    f'le="{ub}"}} {cum}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_bucket{{model="{model}",'
                f'le="+Inf"}} {self.duration_buckets[model][-1]}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_sum{{model="{model}"}} '
                f'{self.duration_sum[model]}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_count{{model="{model}"}} '
                f'{self.duration_count[model]}')
        _h("time_to_first_token_seconds", "summary", "TTFT for streamed requests")
        for model in sorted(self.ttft_count):
            lines.append(
                f'{PREFIX}_time_to_first_token_seconds_sum{{model="{model}"}} '
                f'{self.ttft_sum[model]}')
            lines.append(
                f'{PREFIX}_time_to_first_token_seconds_count{{model="{model}"}} '
                f'{self.ttft_count[model]}')
        _h("output_tokens_total", "counter", "Total generated tokens")
        for model, n in sorted(self.output_tokens_total.items()):
            lines.append(f'{PREFIX}_output_tokens_total{{model="{model}"}} {n}')
        return "\n".join(lines) + "\n"


class InflightGuard:
    """RAII-style guard (reference metrics.rs:188-260): counts inflight and
    stamps the final status; default status is 'error' unless marked ok."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str,
                 request_type: str):
        self.metrics = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        self.status = "error"
        self.t0 = time.monotonic()
        metrics.inflight[model] += 1

    def mark_ok(self) -> None:
        self.status = "success"

    def done(self) -> None:
        m = self.metrics
        m.inflight[self.model] -= 1
        m.requests_total[(self.model, self.endpoint, self.request_type,
                          self.status)] += 1
        m.observe_duration(self.model, time.monotonic() - self.t0)
