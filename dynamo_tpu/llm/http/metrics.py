"""Prometheus metrics for the HTTP frontend (hand-rolled text exposition).

Reference lib/llm/src/http/service/metrics.rs:82-260:
``dyn_llm_http_service_requests_total{model,endpoint,request_type,status}``,
``..._inflight_requests{model}``, ``..._request_duration_seconds{model}``
histogram, and the RAII ``InflightGuard`` that stamps status on drop.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from ...runtime import slo

PREFIX = "dyn_llm_http_service"

# histogram buckets in seconds (reference uses prometheus defaults + LLM tail)
BUCKETS = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
           30.0, 60.0, 120.0, 300.0]
# TTFT shares the request-scale grid (LLM tail: queueing + prefill can
# run to minutes) — dynaslo promoted TTFT from a sum/count summary to a
# real histogram so p95/p99 are scrapeable
TTFT_BUCKETS = BUCKETS
# inter-token-latency buckets: tuned for token cadence (ms-scale steady
# state, sub-second tail when a decode window or preemption stalls a
# stream) — the request-scale BUCKETS would collapse all ITLs into the
# first two buckets
ITL_BUCKETS = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
               1.0, 2.5]
# per-stage (trace span) durations: sub-ms transfer stages up to
# multi-second prefills
STAGE_BUCKETS = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0]


class _Histogram:
    """One labeled histogram family (cumulative buckets + sum + count)."""

    def __init__(self, buckets: List[float]):
        self.ubs = buckets
        self.buckets: Dict[str, List[int]] = defaultdict(
            lambda: [0] * (len(buckets) + 1))
        self.sum: Dict[str, float] = defaultdict(float)
        self.count: Dict[str, int] = defaultdict(int)

    def observe(self, label: str, value: float) -> None:
        self.sum[label] += value
        self.count[label] += 1
        b = self.buckets[label]
        for i, ub in enumerate(self.ubs):
            if value <= ub:
                b[i] += 1
        b[-1] += 1  # +Inf

    def render(self, lines: List[str], metric: str, label_key: str) -> None:
        for label in sorted(self.count):
            for i, ub in enumerate(self.ubs):
                lines.append(
                    f'{metric}_bucket{{{label_key}="{label}",le="{ub}"}} '
                    f'{self.buckets[label][i]}')
            lines.append(
                f'{metric}_bucket{{{label_key}="{label}",le="+Inf"}} '
                f'{self.buckets[label][-1]}')
            lines.append(f'{metric}_sum{{{label_key}="{label}"}} '
                         f'{self.sum[label]}')
            lines.append(f'{metric}_count{{{label_key}="{label}"}} '
                         f'{self.count[label]}')


class Metrics:
    def __init__(self) -> None:
        self.requests_total: Dict[Tuple[str, str, str, str], int] = defaultdict(int)
        self.inflight: Dict[str, int] = defaultdict(int)
        self.duration_buckets: Dict[str, List[int]] = defaultdict(
            lambda: [0] * (len(BUCKETS) + 1))
        self.duration_sum: Dict[str, float] = defaultdict(float)
        self.duration_count: Dict[str, int] = defaultdict(int)
        # streaming metrics. TTFT is a REAL histogram since dynaslo (the
        # sum/count summary had no quantiles); its _sum/_count lines are
        # unchanged for existing scrapers.
        self.ttft = _Histogram(TTFT_BUCKETS)
        self.output_tokens_total: Dict[str, int] = defaultdict(int)
        # inter-token latency (streamed requests, gap between successive
        # token-bearing chunks) — the pair metric TTFT alone can't show
        self.itl = _Histogram(ITL_BUCKETS)
        # per-stage durations fed from finished dyntrace spans
        self.stage = _Histogram(STAGE_BUCKETS)
        # dynaslo: the frontend's own SLO plane — objectives from the
        # DYN_SLO_* registry evaluated over this process's TTFT/ITL/e2e
        # histograms, plus per-request goodput (met-all-objectives)
        self.slo_registry = slo.SloRegistry.from_env()
        self.goodput = slo.GoodputTracker(self.slo_registry)
        self.slo = slo.SloEngine(self.slo_registry, source=self._slo_source)

    def guard(self, model: str, endpoint: str, request_type: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type)

    def observe_duration(self, model: str, seconds: float) -> None:
        self.duration_sum[model] += seconds
        self.duration_count[model] += 1
        buckets = self.duration_buckets[model]
        for i, ub in enumerate(BUCKETS):
            if seconds <= ub:
                buckets[i] += 1
        buckets[-1] += 1  # +Inf

    def observe_ttft(self, model: str, seconds: float) -> None:
        self.ttft.observe(model, seconds)

    def observe_itl(self, model: str, seconds: float) -> None:
        self.itl.observe(model, seconds)

    def observe_stage(self, stage: str, seconds: float) -> None:
        self.stage.observe(stage, seconds)

    # --------------------------------------------------------- dynaslo

    def observe_request_slo(self, metrics: Dict[str, float]) -> None:
        """Per-request goodput accounting: ``metrics`` maps metric name
        (ttft/itl/e2e) → the request's scalar in seconds (ITL = the
        request's mean gap). No-op without registered objectives."""
        if self.slo_registry.objectives:
            self.goodput.observe_request(metrics)

    def _slo_source(self) -> Dict[str, slo.Histogram]:
        """Cumulative metric → histogram view for the SLO engine: each
        frontend family's per-model rows merged into one distribution
        (the rows are CUMULATIVE bucket counts; dynaslo histograms keep
        per-bucket counts plus +Inf)."""
        out = {}
        for metric, fam in (("ttft", self.ttft), ("itl", self.itl)):
            h = _family_to_slo_hist(fam.ubs, fam.buckets.values(),
                                    sum(fam.sum.values()),
                                    sum(fam.count.values()))
            if h is not None:
                out[metric] = h
        h = _family_to_slo_hist(BUCKETS, self.duration_buckets.values(),
                                sum(self.duration_sum.values()),
                                sum(self.duration_count.values()))
        if h is not None:
            out["e2e"] = h
        return out

    def slo_snapshot(self) -> dict:
        """The frontend's GET /debug/slo payload."""
        self.slo.tick()
        snap = self.slo.snapshot()
        snap["goodput"] = self.goodput.snapshot()
        return snap

    def count_output_tokens(self, model: str, n: int) -> None:
        self.output_tokens_total[model] += n

    def render(self) -> str:
        lines: List[str] = []

        def _h(name: str, typ: str, help_: str) -> None:
            lines.append(f"# HELP {PREFIX}_{name} {help_}")
            lines.append(f"# TYPE {PREFIX}_{name} {typ}")

        _h("requests_total", "counter", "Total requests by model/endpoint/type/status")
        for (model, endpoint, rtype, status), n in sorted(self.requests_total.items()):
            lines.append(
                f'{PREFIX}_requests_total{{model="{model}",endpoint="{endpoint}",'
                f'request_type="{rtype}",status="{status}"}} {n}')
        _h("inflight_requests", "gauge", "Requests currently being processed")
        for model, n in sorted(self.inflight.items()):
            lines.append(f'{PREFIX}_inflight_requests{{model="{model}"}} {n}')
        _h("request_duration_seconds", "histogram", "Request duration")
        for model in sorted(self.duration_count):
            cum = 0
            for i, ub in enumerate(BUCKETS):
                cum = self.duration_buckets[model][i]
                lines.append(
                    f'{PREFIX}_request_duration_seconds_bucket{{model="{model}",'
                    f'le="{ub}"}} {cum}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_bucket{{model="{model}",'
                f'le="+Inf"}} {self.duration_buckets[model][-1]}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_sum{{model="{model}"}} '
                f'{self.duration_sum[model]}')
            lines.append(
                f'{PREFIX}_request_duration_seconds_count{{model="{model}"}} '
                f'{self.duration_count[model]}')
        _h("time_to_first_token_seconds", "histogram",
           "TTFT for streamed requests")
        self.ttft.render(lines, f"{PREFIX}_time_to_first_token_seconds",
                         "model")
        _h("output_tokens_total", "counter", "Total generated tokens")
        for model, n in sorted(self.output_tokens_total.items()):
            lines.append(f'{PREFIX}_output_tokens_total{{model="{model}"}} {n}')
        _h("itl_seconds", "histogram",
           "Inter-token latency for streamed requests")
        self.itl.render(lines, f"{PREFIX}_itl_seconds", "model")
        _h("stage_duration_seconds", "histogram",
           "Per-stage request durations from dyntrace spans")
        self.stage.render(lines, f"{PREFIX}_stage_duration_seconds", "stage")
        # dynaslo plane: objective attainment / burn rates / alerts over
        # this process's TTFT/ITL/e2e histograms + per-request goodput
        if self.slo_registry.objectives:
            self.slo.tick()
            lines.extend(self.slo.render_prom_lines())
            lines.extend(self.goodput.render_prom_lines())
        # dynaguard plane: route-fallback/hedge/deadline counters + per-
        # endpoint circuit-breaker state gauges (guard.render_prom_lines)
        from ...runtime import guard, profiling

        lines.extend(guard.render_prom_lines())
        # dynaprof plane: this process's event-loop lag + stall captures
        lines.extend(profiling.render_prom_lines())
        return "\n".join(lines) + "\n"


def _family_to_slo_hist(ubs: List[float], rows, total_sum: float,
                        total_count: int) -> Optional[slo.Histogram]:
    """Merge a `_Histogram` family's per-label CUMULATIVE rows into one
    dynaslo histogram (per-bucket counts + trailing +Inf)."""
    rows = list(rows)
    if not rows:
        return None
    cum = [0] * (len(ubs) + 1)
    for row in rows:
        for i, c in enumerate(row):
            cum[i] += c
    h = slo.Histogram(ubs)
    prev = 0
    for i in range(len(ubs)):
        h.counts[i] = cum[i] - prev
        prev = cum[i]
    h.counts[-1] = cum[-1] - prev     # +Inf remainder
    h.sum = total_sum
    h.count = total_count
    return h


class InflightGuard:
    """RAII-style guard (reference metrics.rs:188-260): counts inflight and
    stamps the final status; default status is 'error' unless marked ok."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str,
                 request_type: str):
        self.metrics = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        self.status = "error"
        self.t0 = time.monotonic()
        # dynaslo: set once a stream has recorded its full goodput
        # metric set, so the unary fallback doesn't double-count
        self.slo_observed = False
        metrics.inflight[model] += 1

    def mark_ok(self) -> None:
        self.status = "success"

    def done(self) -> None:
        m = self.metrics
        m.inflight[self.model] -= 1
        m.requests_total[(self.model, self.endpoint, self.request_type,
                          self.status)] += 1
        m.observe_duration(self.model, time.monotonic() - self.t0)
