"""OpenAI-compatible HTTP frontend (reference lib/llm/src/http/service/)."""

from .discovery import ModelWatcher
from .metrics import Metrics
from .service import HttpService, ModelManager
