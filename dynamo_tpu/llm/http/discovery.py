"""Frontend model discovery: KV watcher → ModelManager registration.

Reference lib/llm/src/http/service/discovery.rs:36-145 (``model_watcher``):
watch the ``models/`` prefix; on Put build a client to the worker endpoint
and register a chat/completions engine for the model; on Delete remove it.
This is what makes workers (and ``llmctl``-registered models) appear on the
frontend with zero restarts.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from ...runtime.dcp_client import unpack
from ...runtime.runtime import DistributedRuntime
from ...runtime.tasks import cancel_join, spawn_tracked
from ..engines import RemoteOpenAIEngine
from ..entry import MODEL_PREFIX, ModelEntry
from .service import ModelManager

log = logging.getLogger("dynamo_tpu.http.discovery")


class ModelWatcher:
    def __init__(self, drt: DistributedRuntime, manager: ModelManager):
        self.drt = drt
        self.manager = manager
        self._clients: Dict[str, object] = {}
        self._task: Optional[asyncio.Task] = None
        self._watch = None

    async def start(self) -> None:
        items, watch = await self.drt.dcp.kv_watch_prefix(MODEL_PREFIX)
        self._watch = watch
        for item in items:
            await self._register(ModelEntry.from_dict(unpack(item.value)))
        self._task = spawn_tracked(self._loop(), name="model-watcher")

    async def stop(self) -> None:
        if self._watch:
            await self._watch.stop()
        await cancel_join(self._task)

    async def _loop(self) -> None:
        async for ev in self._watch:
            try:
                if ev.event == "put":
                    await self._register(ModelEntry.from_dict(unpack(ev.value)))
                elif ev.event == "delete":
                    self._unregister(ev.key)
            except Exception:
                log.exception("model watcher event failed for %s", ev.key)

    async def _register(self, entry: ModelEntry) -> None:
        addr = entry.address
        client = await self.drt.namespace(addr.namespace) \
            .component(addr.component).endpoint(addr.endpoint).client()
        engine = RemoteOpenAIEngine(client)
        if entry.model_type in ("chat", "both"):
            self.manager.add_chat_model(entry.name, engine)
        if entry.model_type in ("completions", "both"):
            self.manager.add_completions_model(entry.name, engine)
        old = self._clients.pop(entry.kv_key(), None)
        if old is not None:  # re-registration (worker restart/card refresh)
            spawn_tracked(old.close(), name="stale-client-close")
        self._clients[entry.kv_key()] = client
        log.info("discovered model %r -> %s", entry.name, entry.endpoint)

    def _unregister(self, kv_key: str) -> None:
        # key: models/<type>/<name> — remove only that type's route
        parts = kv_key[len(MODEL_PREFIX):].split("/", 1)
        if len(parts) != 2:
            return
        mtype, name = parts
        self.manager.remove_model(name, model_type=mtype)
        client = self._clients.pop(kv_key, None)
        if client is not None:
            spawn_tracked(client.close(), name="withdrawn-client-close")
        log.info("model %r withdrawn (type=%s)", name, mtype)
