"""OpenAI-compatible HTTP frontend.

Reference lib/llm/src/http/service/{service_v2.rs,openai.rs,service.rs}:
axum server with ``/v1/chat/completions``, ``/v1/completions``,
``/v1/models``, ``/metrics``, ``/health``; SSE streaming with a final
``[DONE]``; a ``ModelManager`` mapping model name → engine. Implemented on
aiohttp; engines are OpenAI-level async generators so local chains
(preprocessor→backend→JAX engine) and remote workers plug in uniformly.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import AsyncIterator, Callable, Dict, Optional

from aiohttp import web

from ...runtime import blackbox, guard, profiling, revive, tracing
from ...runtime.dcp_client import NoRespondersError
from ...runtime.engine import Annotated, Context
from ...runtime.tasks import spawn_tracked
from ..protocols.openai import (ChatAggregator, ChatCompletionRequest,
                                CompletionAggregator, CompletionRequest,
                                ModelInfo, ModelList)
from .metrics import Metrics

log = logging.getLogger("dynamo_tpu.http")

# An OpenAI-level engine: request (pydantic model) + Context -> async iterator
# of chunk dicts (ChatCompletionChunk-shaped) or Annotated envelopes.
OpenAIEngine = Callable[[object, Context], AsyncIterator]


class ModelManager:
    """Per-model engine registry (reference service.rs ModelManager)."""

    def __init__(self) -> None:
        self.chat_engines: Dict[str, OpenAIEngine] = {}
        self.completion_engines: Dict[str, OpenAIEngine] = {}

    def add_chat_model(self, name: str, engine: OpenAIEngine) -> None:
        self.chat_engines[name] = engine
        log.info("registered chat model %r", name)

    def add_completions_model(self, name: str, engine: OpenAIEngine) -> None:
        self.completion_engines[name] = engine
        log.info("registered completions model %r", name)

    def remove_model(self, name: str, model_type: str = "both") -> None:
        if model_type in ("chat", "both"):
            self.chat_engines.pop(name, None)
        if model_type in ("completions", "both"):
            self.completion_engines.pop(name, None)
        log.info("removed model %r (type=%s)", name, model_type)

    def list_models(self) -> ModelList:
        names = sorted(set(self.chat_engines) | set(self.completion_engines))
        return ModelList(data=[ModelInfo(id=n) for n in names])


class HttpService:
    def __init__(self, manager: Optional[ModelManager] = None,
                 metrics: Optional[Metrics] = None,
                 admission: Optional[revive.AdmissionController] = None):
        self.manager = manager or ModelManager()
        self.metrics = metrics or Metrics()
        # dynarevive SLO-aware admission control: shed load (early 503 +
        # load-derived jittered Retry-After) before the engines melt.
        # None = admit everything (wire one with set_admission()).
        self.admission = admission
        self.app = web.Application()
        self.app.add_routes([
            web.post("/v1/chat/completions", self._chat),
            web.post("/v1/completions", self._completions),
            web.get("/v1/models", self._models),
            web.get("/v1/traces", self._traces),
            web.get("/v1/traces/{request_id}", self._trace_one),
            web.get("/debug/cache", self._debug_cache),
            web.get("/debug/slo", self._debug_slo),
            web.get("/debug/profile", self._debug_profile),
            web.get("/debug/profile/stacks", self._debug_stacks),
            web.post("/debug/profile/start", self._profile_start),
            web.post("/debug/profile/stop", self._profile_stop),
            web.get("/debug/incidents", self._incidents),
            web.get("/debug/incidents/{incident_id}", self._incident_one),
            web.post("/debug/incidents/capture", self._incident_capture),
            web.post("/drain", self._drain),
            web.get("/metrics", self._metrics),
            web.get("/health", self._health),
            web.get("/live", self._health),
        ])
        self._runner: Optional[web.AppRunner] = None
        self.port = 0
        # dynarevive graceful drain: POST /drain flips this — new
        # requests get 503 while the registered drain callbacks run
        # (serve handles / local engines finishing their in-flight work)
        self.draining = False
        self._drain_cbs: list = []
        # on-demand jax.profiler capture state (/debug/profile/start)
        self._jax_trace_dir: Optional[str] = None
        # summarize finished dyntrace spans into the per-stage duration
        # histograms (dyn_llm_http_service_stage_duration_seconds)
        tracing.get_tracer().add_listener(self._on_span_end)

    def set_admission(self,
                      admission: Optional[revive.AdmissionController]
                      ) -> None:
        self.admission = admission

    def on_drain(self, cb) -> None:
        """Register an async zero-arg drain callback run by POST /drain
        (in registration order) after new admissions stop."""
        self._drain_cbs.append(cb)

    def _on_span_end(self, span) -> None:
        if span.duration_s is not None:
            self.metrics.observe_stage(span.name, span.duration_s)

    # ------------------------------------------------------------ lifecycle

    async def start(self, host: str = "0.0.0.0", port: int = 8080) -> None:
        # dynaprof: always-on loop-lag monitor + stall watchdog for the
        # frontend's event loop (refcounted; released in stop())
        profiling.acquire_loop_profiler()
        # dynablack: fold the frontend's SLO view into incident bundles
        # (weakly held; a disabled recorder ignores everything)
        rec = blackbox.get_recorder()
        if rec.enabled:
            rec.add_source("slo", self.metrics.slo_snapshot)
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("OpenAI HTTP service on %s:%d", host, self.port)

    async def stop(self) -> None:
        # claim before the await: concurrent stop() calls must not
        # double-cleanup or double-release the loop profiler
        runner, self._runner = self._runner, None
        if runner:
            await runner.cleanup()
            await profiling.release_loop_profiler()

    # ------------------------------------------------------------- handlers

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "draining" if self.draining else "healthy",
            "models": [m.id for m in self.manager.list_models().data]})

    async def _drain(self, request: web.Request) -> web.Response:
        """dynarevive graceful drain: stop admitting (every new request
        503s with Retry-After), then run the registered drain callbacks
        — worker handles finishing in-flight sequences bounded by
        DYN_DRAIN_TIMEOUT_MS, KV event flushes, engine drains."""
        if self.draining:
            return web.json_response({"draining": True,
                                      "already": True}, status=409)
        self.draining = True
        log.info("POST /drain: shedding new requests, running %d drain "
                 "callbacks", len(self._drain_cbs))
        results = []
        for cb in self._drain_cbs:
            try:
                results.append(await cb())
            # drain every target even when one callback fails; the
            # per-target error is reported in the drain response, and no
            # client request rides on this admin path
            except Exception as e:  # noqa: BLE001  # dynalint: disable=typed-error-swallow
                log.exception("drain callback failed")
                results.append(f"error: {e!r}")
        return web.json_response({"draining": True, "results":
                                  [r if isinstance(r, (bool, str, int,
                                                       float, type(None)))
                                   else repr(r) for r in results]})

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response(self.manager.list_models().model_dump())

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render(),
                            content_type="text/plain", charset="utf-8")

    async def _traces(self, request: web.Request) -> web.Response:
        """Debug listing: recent traces (newest first) + the registered
        engine step timelines (with their wall/monotonic anchor pairs,
        so cross-worker rollups can put every ring on one time axis).
        ``?limit=`` caps both listings (default 100 traces / 200 timeline
        events); ``?since_ms=`` (epoch ms) is the incremental-poll
        filter — the defaults keep the response bounded at production
        ring sizes."""
        try:
            limit = _query_num(request, "limit", int)
            since_ms = _query_num(request, "since_ms", float)
        except ValueError as e:
            return _error_response(400, str(e))
        tracer = tracing.get_tracer()
        return web.json_response({
            "traces": tracer.traces_summary(
                limit=limit if limit is not None else 100,
                since_ms=since_ms),
            "engine_steps": tracing.timelines_snapshot(
                limit=limit if limit is not None else 200,
                since_ms=since_ms),
            "engine_step_anchors": tracing.timeline_anchors(),
        })

    async def _trace_one(self, request: web.Request) -> web.Response:
        rid = request.match_info["request_id"]
        data = tracing.get_tracer().get_request_trace(rid)
        # dynaprof cost attribution joins the trace payload; it is also
        # served alone when tracing was sampled out (attribution is
        # always-on, spans are not)
        cost = profiling.request_attribution(rid)
        if data is None and cost is None:
            return _error_response(404, f"no trace for request {rid!r}",
                                   {"X-Request-Id": rid})
        if data is None:
            data = {"request_id": rid, "trace_id": None, "spans": [],
                    "stages": {}}
        if cost is not None:
            data["cost"] = cost
        return web.json_response(data, headers={"X-Request-Id": rid})

    # ------------------------------------------------- dynaprof debug hooks

    async def _debug_cache(self, request: web.Request) -> web.Response:
        """dynacache snapshot: every registered cache view in the process
        — per-engine pool/host-tier occupancy, windowed hit rate, hot
        prefix chains, restore queue — plus the KV router's calibration
        counters when a router runs here."""
        return web.json_response({"caches": profiling.caches_snapshot()})

    async def _debug_slo(self, request: web.Request) -> web.Response:
        """dynaslo snapshot: the registered objectives, their windowed
        attainment / error budget / fast+slow burn rates / alert state,
        the planner-facing pressure signals, and goodput (per-request
        met-all-objectives accounting)."""
        return web.json_response(self.metrics.slo_snapshot())

    async def _debug_profile(self, request: web.Request) -> web.Response:
        """One-stop profiling snapshot: loop lag + stall-watchdog stats,
        every live engine's sampled cost table, and the attribution ring
        depth."""
        prof = profiling.current_loop_profiler()
        return web.json_response({
            "loop": prof.snapshot() if prof is not None else None,
            "engines": profiling.profiles_snapshot(),
            "attributions": len(profiling.attributions_snapshot(10 ** 9)),
            "jax_trace_dir": self._jax_trace_dir,
        })

    async def _debug_stacks(self, request: web.Request) -> web.Response:
        """Flamegraph-ready collapsed-stack dump of event-loop stalls
        (pipe straight into flamegraph.pl). ``?limit=`` keeps the top-N
        hottest stacks (default 200); ``?since_ms=`` drops stacks not
        sampled since that wall time."""
        try:
            limit = _query_num(request, "limit", int)
            since_ms = _query_num(request, "since_ms", float)
        except ValueError as e:
            return _error_response(400, str(e))
        text = profiling.stall_stacks_folded(
            limit=limit if limit is not None else 200, since_ms=since_ms)
        return web.Response(text=text,
                            content_type="text/plain", charset="utf-8")

    # ------------------------------------------------ dynablack incidents

    async def _incidents(self, request: web.Request) -> web.Response:
        """dynablack incident table: one summary row per captured (or
        contributed-to) incident, newest first."""
        rec = blackbox.get_recorder()
        return web.json_response({
            "enabled": rec.enabled,
            "window_s": rec.window_s,
            "cooldown_remaining_s": round(rec.cooldown_remaining_s(), 3),
            "captures_total": rec.captures_total,
            "suppressed_total": rec.suppressed_total,
            "incidents": rec.incidents_summary(),
        })

    async def _incident_one(self, request: web.Request) -> web.Response:
        """One full incident bundle, in the canonical serialization the
        persisted file and the admin renderer consume."""
        iid = request.match_info["incident_id"]
        bundle = blackbox.get_recorder().get(iid)
        if bundle is None:
            return _error_response(404, f"no incident {iid!r}")
        return web.Response(text=blackbox.render_bundle_json(bundle),
                            content_type="application/json",
                            charset="utf-8")

    async def _incident_capture(self, request: web.Request) -> web.Response:
        """Manual trip: capture now unless the cooldown debounce is
        active (409 + Retry-After) or the recorder is disabled."""
        rec = blackbox.get_recorder()
        if not rec.enabled:
            return _error_response(
                409, "flight recorder disabled (DYN_BLACKBOX_WINDOW_S=0)")
        remaining = rec.cooldown_remaining_s()
        if remaining > 0:
            return _error_response(
                409, f"capture cooldown active ({remaining:.1f}s left)",
                {"Retry-After": str(max(1, int(remaining + 0.999)))})
        bundle = rec.trip("manual", {"via": "http"})
        if bundle is None:
            # raced into a cooldown, or DYN_BLACKBOX_TRIGGERS excludes
            # 'manual'
            return _error_response(
                409, "capture suppressed (cooldown or trigger filter)",
                {"Retry-After": str(max(1, int(rec.cooldown_s)))})
        return web.json_response({
            "id": bundle["id"], "trigger": bundle["trigger"],
            "at_wall_ms": bundle["at_wall_ms"],
            "workers": sorted(bundle["workers"]),
        })

    async def _profile_start(self, request: web.Request) -> web.Response:
        """Start an on-demand jax.profiler trace capture. Body may carry
        {"dir": path}; defaults to DYN_PROFILE_DIR or a temp dir."""
        try:
            body = await request.json()
        # empty/absent body is fine; the parse awaits only the client's
        # own bytes — no routed hop can raise the typed guard errors here
        except Exception:  # noqa: BLE001  # dynalint: disable=typed-error-swallow
            body = {}
        # busy-check AFTER the await: everything from here to the state
        # write is sync, so a concurrent start cannot interleave
        if self._jax_trace_dir is not None:
            return _error_response(409, "profiler trace already running "
                                        f"({self._jax_trace_dir})")
        from ...runtime.config import env_str

        trace_dir = (body or {}).get("dir") or env_str("DYN_PROFILE_DIR")
        if not trace_dir:
            import tempfile

            trace_dir = tempfile.mkdtemp(prefix="dynaprof-jax-")
        try:
            import jax.profiler

            jax.profiler.start_trace(trace_dir)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            return _error_response(501, f"jax profiler unavailable: {e!r}")
        self._jax_trace_dir = trace_dir
        return web.json_response({"started": True, "dir": trace_dir})

    async def _profile_stop(self, request: web.Request) -> web.Response:
        if self._jax_trace_dir is None:
            return _error_response(409, "no profiler trace running")
        trace_dir, self._jax_trace_dir = self._jax_trace_dir, None
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            return _error_response(500, f"stop_trace failed: {e!r}")
        return web.json_response({"stopped": True, "dir": trace_dir})

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, ChatCompletionRequest,
                                 self.manager.chat_engines, "chat_completions")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, CompletionRequest,
                                 self.manager.completion_engines, "completions")

    async def _serve(self, request: web.Request, model_cls, engines: dict,
                     endpoint: str) -> web.StreamResponse:
        # request identity: echo the client's X-Request-Id (or mint one) on
        # EVERY response — SSE streams and error paths included — so logs,
        # traces and client records join on one id
        rid = (request.headers.get("X-Request-Id") or "").strip()[:128] \
            or uuid.uuid4().hex
        tracing.bind_request_id(rid)
        tracer = tracing.get_tracer()
        span = tracer.start_span(
            "http.request",
            parent=tracing.parse_traceparent(
                request.headers.get("traceparent")),
            attributes={"endpoint": endpoint, "method": request.method,
                        "path": request.path},
            request_id=rid)
        hdrs = {"X-Request-Id": rid}
        tp = tracing.format_traceparent(span)
        if tp:
            hdrs["traceparent"] = tp
        with span:
            try:
                body = await request.json()
                req = model_cls(**body)
            # body parse/validation awaits only the client's own bytes —
            # the typed guard errors cannot arise before dispatch, and
            # 400 is the correct mapping for everything that can
            except Exception as e:  # noqa: BLE001  # dynalint: disable=typed-error-swallow
                return _error_response(400, f"invalid request: {e}", hdrs)
            engine = engines.get(req.model)
            if engine is None:
                return _error_response(
                    404, f"model {req.model!r} not found; available: "
                         f"{sorted(engines)}", hdrs)
            if self.draining:
                # draining frontend: refuse new work, point clients at a
                # sibling (the LB retries elsewhere within Retry-After)
                return _error_response(
                    503, "frontend draining",
                    {**hdrs, "Retry-After": str(self._retry_after())},
                    err_type="overloaded_error")
            if self.admission is not None:
                # dynarevive SLO-aware shed: answer an early 503 from
                # load signals the stack already exports instead of
                # queueing a request the engine will deadline anyway
                retry_after = self.admission.admit()
                if retry_after is not None:
                    span.set_attribute("shed", True)
                    return _error_response(
                        503, "shedding load (overloaded)",
                        {**hdrs, "Retry-After": str(retry_after)},
                        err_type="overloaded_error")
            span.set_attribute("model", req.model)
            span.set_attribute("stream", bool(req.stream))
            mguard = self.metrics.guard(
                req.model, endpoint, "stream" if req.stream else "unary")
            # end-to-end deadline: `timeout` body field (seconds) beats the
            # X-Request-Deadline-Ms header beats the registered default
            deadline = _request_deadline(request, req)
            ctx = Context(rid, deadline=deadline)
            try:
                t0 = time.monotonic()
                n = getattr(req, "n", 1) or 1
                if n > 1:
                    aiter = _fanout_choices(engine, req, ctx, n).__aiter__()
                else:
                    aiter = engine(req, ctx).__aiter__()
                # pull the first item BEFORE committing response headers so
                # early failures (validation, routing) map to clean errors;
                # the pull itself is bounded by the request deadline
                try:
                    first = await guard.bound(aiter.__anext__(),
                                              deadline=deadline,
                                              what="first response item")
                except StopAsyncIteration:
                    first = None
                if req.stream:
                    return await self._sse(request, req, first, aiter, ctx,
                                           mguard, t0, hdrs, endpoint)
                return await self._unary(req, first, aiter, endpoint,
                                         mguard, hdrs, deadline)
            except guard.DeadlineExceeded as e:
                ctx.kill()  # release whatever is still running upstream
                return _error_response(504, f"deadline exceeded: {e}",
                                       hdrs, err_type="timeout_error")
            except guard.NoCapacity as e:
                # no live/healthy instance right now: retryable, tell the
                # client when to come back — not a 500. The Retry-After
                # is load-derived and jittered (dynarevive): a constant
                # "1" synchronized every client's retry into a second
                # stampede against a recovering fleet.
                return _error_response(
                    503, str(e),
                    {**hdrs, "Retry-After": str(self._retry_after())},
                    err_type="overloaded_error")
            except NoRespondersError as e:
                return _error_response(
                    503, str(e),
                    {**hdrs, "Retry-After": str(self._retry_after())},
                    err_type="overloaded_error")
            except ValueError as e:
                return _error_response(400, str(e), hdrs)
            except (ConnectionResetError, asyncio.CancelledError):
                raise  # client went away; never answer a second time
            except Exception as e:  # noqa: BLE001
                log.exception("request %s failed", ctx.id)
                return _error_response(500, repr(e), hdrs)
            finally:
                # dynaslo goodput: streams record their full
                # ttft/itl/e2e set in _sse; everything else that entered
                # serving (unary, 5xx) is judged on e2e alone
                if not getattr(mguard, "slo_observed", False):
                    self.metrics.observe_request_slo(
                        {"e2e": time.monotonic() - mguard.t0})
                mguard.done()

    def _retry_after(self) -> int:
        """Retry-After seconds for 503s: the admission controller's
        pressure-derived jittered value when one is wired, else the
        unit-pressure jitter (never the old synchronized constant 1)."""
        if self.admission is not None:
            _, pressure = self.admission.evaluate()
            return self.admission.retry_after(max(pressure, 1.0))
        return revive.retry_after_s()

    async def _sse(self, http_request: web.Request, req, first, aiter,
                   ctx: Context, mguard, t0: float,
                   hdrs: Optional[dict] = None,
                   endpoint: str = "completions") -> web.StreamResponse:
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
            **(hdrs or {}),
        })
        await resp.prepare(http_request)
        errored = False
        saw_first_token = False
        last_token_t: Optional[float] = None
        # dynaslo goodput inputs for this request (mean ITL over the gaps)
        ttft_s: Optional[float] = None
        itl_total, itl_n = 0.0, 0

        async def _write_chunk(chunk) -> bool:
            """Writes one stream item; returns False to stop the stream."""
            nonlocal errored, saw_first_token, last_token_t
            nonlocal ttft_s, itl_total, itl_n
            if chunk is None:
                return True
            if isinstance(chunk, Annotated) and chunk.event and chunk.data is None:
                if chunk.is_error:
                    errored = True
                    await resp.write(
                        b"event: error\ndata: " +
                        json.dumps(chunk.error_message()).encode() + b"\n\n")
                    return False
                # annotation event (formatted_prompt, token_ids, ...)
                await resp.write(
                    f"event: {chunk.event}\n".encode() + b"data: " +
                    json.dumps(chunk.comment).encode() + b"\n\n")
                return True
            data = _chunk_dict(chunk)
            if data is None:
                return True
            now = time.monotonic()
            if not saw_first_token:
                ttft_s = now - t0
                self.metrics.observe_ttft(req.model, ttft_s)
                saw_first_token = True
            elif last_token_t is not None:
                # inter-token latency: gap between successive data chunks
                self.metrics.observe_itl(req.model, now - last_token_t)
                itl_total += now - last_token_t
                itl_n += 1
            last_token_t = now
            await resp.write(b"data: " + json.dumps(data).encode() + b"\n\n")
            return True

        try:
            if await _write_chunk(first):
                while True:
                    # each pull is bounded by the request deadline: a
                    # wedged upstream turns into a clean final timeout
                    # chunk, never a hung stream
                    try:
                        chunk = await guard.bound(
                            aiter.__anext__(), deadline=ctx.deadline,
                            what="stream item")
                    except StopAsyncIteration:
                        break
                    if not await _write_chunk(chunk):
                        break
            if not errored:
                await resp.write(b"data: [DONE]\n\n")
                mguard.mark_ok()
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.kill()  # client went away → propagate cancellation upstream
            raise
        except guard.DeadlineExceeded:
            # deadline ran out mid-stream and the engine chain could not
            # emit its own finish: close the stream with a well-formed
            # final chunk carrying finish_reason "timeout"
            ctx.kill()
            try:
                await resp.write(
                    b"data: " +
                    json.dumps(_timeout_chunk(endpoint, req.model,
                                              ctx.id)).encode() + b"\n\n")
                await resp.write(b"data: [DONE]\n\n")
            except (ConnectionError, RuntimeError):
                pass
        except Exception as e:  # noqa: BLE001 — headers are committed; emit
            # an SSE error event instead of a second response
            log.exception("stream %s failed mid-flight", ctx.id)
            errored = True
            try:
                await resp.write(b"event: error\ndata: " +
                                 json.dumps(repr(e)).encode() + b"\n\n")
            except (ConnectionError, RuntimeError):
                pass
        # dynaslo goodput: one verdict per stream that ran to a close
        # (clean, timeout or error — a failed stream is a bad-latency
        # observation, not a skipped one); disconnects re-raised above
        req_slo = {"e2e": time.monotonic() - t0}
        if ttft_s is not None:
            req_slo["ttft"] = ttft_s
        if itl_n:
            req_slo["itl"] = itl_total / itl_n
        self.metrics.observe_request_slo(req_slo)
        mguard.slo_observed = True
        await resp.write_eof()
        return resp

    async def _unary(self, req, first, aiter, endpoint: str,
                     mguard, hdrs: Optional[dict] = None,
                     deadline=None) -> web.Response:
        async def _items():
            # every pull bounded by the request deadline: the 504 path in
            # _serve handles the resulting DeadlineExceeded
            if first is not None:
                yield first
            while True:
                try:
                    yield await guard.bound(aiter.__anext__(),
                                            deadline=deadline,
                                            what="response item")
                except StopAsyncIteration:
                    return

        if endpoint == "chat_completions":
            agg = ChatAggregator(req.model)
            async for chunk in _items():
                if isinstance(chunk, Annotated) and chunk.is_error:
                    return _error_response(500, chunk.error_message(), hdrs)
                data = _chunk_dict(chunk)
                if data is None:
                    continue
                from ..protocols.openai import ChatCompletionChunk

                agg.add_chunk(ChatCompletionChunk(**data))
            out = agg.response()
            if any(c.finish_reason == "timeout" for c in out.choices):
                # unary semantics: a partial answer is not an answer —
                # deadline expiry maps to 504 (streams instead end with a
                # finish_reason "timeout" chunk)
                return _error_response(504, "deadline exceeded", hdrs,
                                       err_type="timeout_error")
            mguard.mark_ok()
            return web.json_response(out.model_dump(exclude_none=True),
                                     headers=hdrs)
        agg = CompletionAggregator(req.model)
        async for chunk in _items():
            if isinstance(chunk, Annotated) and chunk.is_error:
                return _error_response(500, chunk.error_message(), hdrs)
            data = _chunk_dict(chunk)
            if data is None:
                continue
            for choice in data.get("choices", []):
                agg.add_text(choice.get("text", ""),
                             choice.get("finish_reason"),
                             index=choice.get("index", 0),
                             logprobs=choice.get("logprobs"))
            if data.get("usage"):
                from ..protocols.openai import Usage

                agg.usage = Usage(**data["usage"])
        out = agg.response()
        if any(c.finish_reason == "timeout" for c in out.choices):
            return _error_response(504, "deadline exceeded", hdrs,
                                   err_type="timeout_error")
        mguard.mark_ok()
        return web.json_response(out.model_dump(exclude_none=True),
                                 headers=hdrs)


async def _fanout_choices(engine, req, ctx: Context, n: int):
    """n>1 (OpenAI parallel sampling): run n single-choice generations
    concurrently — each a full pipeline pass whose prompt prefill the
    engine's prefix cache dedups after the first — and multiplex their
    chunks with per-stream choice indices. The reference inherits n from
    vLLM's SamplingParams; here it composes from the existing machinery.

    Seeds: an explicit request seed derives per-choice seeds (seed+i, so
    the choices differ but the SET is reproducible); no seed keeps each
    stream's own entropy. Cancellation: the outer context's stop/kill
    propagates to every child stream. Annotation events (comments,
    formatted_prompt) pass through from choice 0 only — n identical
    copies would duplicate them."""
    import time as _time
    import uuid as _uuid

    queue: asyncio.Queue = asyncio.Queue()
    DONE = object()
    kids = [Context(f"{ctx.id}-c{i}") for i in range(n)]
    # ONE stream identity: OpenAI streaming semantics give all chunks of
    # a response a single id/created, choices distinguished by index.
    # The id PREFIX is derived from the first child chunk that carries
    # one ("cmpl-..." for completions, "chatcmpl-..." for chat) so n>1
    # completions streams keep their endpoint's id shape.
    stream_id = None
    created = int(_time.time())

    def child_req(i):
        upd = {"n": 1}
        if getattr(req, "seed", None) is not None:
            upd["seed"] = req.seed + i
        return req.model_copy(update=upd)

    async def pump(i):
        try:
            async for chunk in engine(child_req(i), kids[i]):
                await queue.put((i, chunk))
        # not a swallow: the exception object is forwarded through the
        # queue and re-raised by the merge loop, so the typed guard
        # errors still reach _serve's 504/503 mappers
        except Exception as e:  # noqa: BLE001  # dynalint: disable=typed-error-swallow
            await queue.put((i, e))
        finally:
            await queue.put((i, DONE))

    async def propagate_cancel():
        await ctx.wait_stopped()  # kill() sets _stop too
        for k in kids:
            (k.kill if ctx.killed else k.stop_generating)()

    tasks = [spawn_tracked(pump(i), name=f"fanout-pump-{i}")
             for i in range(n)]
    canceller = spawn_tracked(propagate_cancel(), name="fanout-cancel")
    live = n
    merged_usage = None
    usage_template = None
    try:
        while live:
            # bounded by the request deadline (504/timeout-chunk upstream)
            i, item = await guard.bound(queue.get(), deadline=ctx.deadline,
                                        what="fanout item")
            if item is DONE:
                live -= 1
                continue
            if isinstance(item, Exception):
                raise item
            if isinstance(item, Annotated) and item.data is None:
                if item.is_error or i == 0:
                    yield item
                continue
            u = _chunk_usage(item)
            if u is not None:
                # one merged usage chunk at the end (OpenAI semantics:
                # completion tokens sum over choices, shared prompt
                # once). Per-child usage never passes through — even on
                # chunks that also carry choices — or aggregators would
                # double-count it against the merged chunk
                from ..protocols.openai import Usage, _merge_usage

                merged_usage = _merge_usage(merged_usage, Usage(**u))
                usage_template = item
                if not _chunk_choices(item):
                    continue  # usage-only chunk: held back entirely
                item = _strip_usage(item)
            if stream_id is None:
                cid = _chunk_id(item)
                if cid is not None:
                    prefix = cid.split("-", 1)[0] if "-" in cid \
                        else "chatcmpl"
                    stream_id = f"{prefix}-{_uuid.uuid4().hex}"
            yield _reindex(item, i, stream_id, created)
        if merged_usage is not None and usage_template is not None:
            yield _reindex(_set_usage(usage_template, merged_usage),
                           0, stream_id, created)
    finally:
        canceller.cancel()
        for k in kids:
            k.stop_generating()
        for t in tasks:
            t.cancel()


def _chunk_target(chunk):
    return chunk.data if isinstance(chunk, Annotated) else chunk


def _chunk_usage(chunk):
    t = _chunk_target(chunk)
    if isinstance(t, dict):
        return t.get("usage")
    u = getattr(t, "usage", None)
    return u.model_dump() if u is not None else None


def _chunk_id(chunk):
    t = _chunk_target(chunk)
    if isinstance(t, dict):
        return t.get("id")
    return getattr(t, "id", None)


def _chunk_choices(chunk):
    t = _chunk_target(chunk)
    if isinstance(t, dict):
        return t.get("choices") or []
    return getattr(t, "choices", None) or []


def _set_usage(chunk, usage):
    t = _chunk_target(chunk)
    if isinstance(t, dict):
        t = dict(t, usage=usage.model_dump(), choices=[])
        if isinstance(chunk, Annotated):
            return Annotated(data=t)
        return t
    t = t.model_copy(update={"usage": usage, "choices": []})
    return Annotated(data=t.model_dump(exclude_none=True))         if isinstance(chunk, Annotated) else t


def _reindex(chunk, i: int, stream_id=None, created=None):
    """Stamp a child stream's chunk with its choice index and (for n>1
    streams) the single parent-stream id/created."""
    target = chunk.data if isinstance(chunk, Annotated) else chunk
    if isinstance(target, dict):
        for c in target.get("choices", []):
            c["index"] = i
        if stream_id is not None and "id" in target:
            target["id"] = stream_id
            target["created"] = created
    elif hasattr(target, "choices"):
        for c in target.choices:
            c.index = i
        if stream_id is not None and hasattr(target, "id"):
            target.id = stream_id
            target.created = created
    return chunk


def _strip_usage(chunk):
    target = chunk.data if isinstance(chunk, Annotated) else chunk
    if isinstance(target, dict):
        target.pop("usage", None)
    elif hasattr(target, "usage"):
        target.usage = None
    return chunk


def _chunk_dict(chunk) -> Optional[dict]:
    """Normalize engine output: pydantic model / Annotated / dict → dict."""
    if chunk is None:
        return None
    if isinstance(chunk, Annotated):
        if chunk.is_error:
            return {"event": "error", "comment": chunk.error_message()}
        if chunk.data is None:
            return None  # pure annotation/comment event; not an SSE data chunk
        return chunk.data
    if hasattr(chunk, "model_dump"):
        return chunk.model_dump(exclude_none=True)
    return chunk


def _request_deadline(http_request: web.Request, req):
    """Resolve the request's end-to-end deadline: `timeout` body field
    (seconds) > X-Request-Deadline-Ms header > DYN_REQUEST_DEADLINE_MS
    registered default > none."""
    body_timeout = getattr(req, "timeout", None)
    if body_timeout is not None and body_timeout > 0:
        return guard.Deadline.after_s(float(body_timeout))
    hdr = (http_request.headers.get("X-Request-Deadline-Ms") or "").strip()
    if hdr:
        try:
            return guard.Deadline.from_wire_ms(float(hdr))
        except ValueError:
            log.warning("ignoring malformed X-Request-Deadline-Ms %r", hdr)
    return guard.default_deadline()


def _timeout_chunk(endpoint: str, model: str, rid: str) -> dict:
    """Well-formed final SSE chunk closing a stream whose deadline
    expired before the engine chain could emit its own finish."""
    import time as _time

    if endpoint == "chat_completions":
        return {"id": f"chatcmpl-{rid}", "object": "chat.completion.chunk",
                "created": int(_time.time()), "model": model,
                "choices": [{"index": 0, "delta": {},
                             "finish_reason": "timeout"}]}
    return {"id": f"cmpl-{rid}", "object": "text_completion",
            "created": int(_time.time()), "model": model,
            "choices": [{"index": 0, "text": "",
                         "finish_reason": "timeout"}]}


def _query_num(request: web.Request, name: str, cast):
    """Optional numeric query param; raises ValueError with a client-
    facing message on junk (mapped to 400 by the handlers)."""
    raw = request.query.get(name)
    if raw is None or raw == "":
        return None
    try:
        return cast(raw)
    except (TypeError, ValueError):
        raise ValueError(f"query param {name!r} must be numeric, "
                         f"got {raw!r}") from None


def _error_response(status: int, message: str,
                    headers: Optional[dict] = None,
                    err_type: Optional[str] = None) -> web.Response:
    if err_type is None:
        err_type = ("invalid_request_error" if status < 500
                    else "internal_error")
    return web.json_response(
        {"error": {"message": message, "type": err_type, "code": status}},
        status=status, headers=headers)
