"""Worker-side model serving: wire a core engine behind the LLM pipeline
and publish it for frontend discovery.

Reference launch/dynamo-run/src/input/endpoint.rs:35-117 (``in=dyn://``
worker mode): build ``SegmentSource → OpenAIPreprocessor → Backend →
engine`` behind an Ingress, then self-register a ``ModelEntry`` (and the
model deployment card) in the KV store under the worker's lease so the
frontend's model watcher picks it up — and drops it on lease expiry.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..runtime.engine import Context
from ..runtime.runtime import DistributedRuntime
from .engines import LocalChatChain, LocalCompletionChain
from .entry import ModelEntry, register_model
from .model_card import ModelDeploymentCard
from .preprocessor import OpenAIPreprocessor
from .protocols.openai import ChatCompletionRequest, CompletionRequest

log = logging.getLogger("dynamo_tpu.llm.worker")


def _component_slug(mdc: ModelDeploymentCard) -> str:
    return mdc.name.replace("/", "-").replace(".", "-").lower()


async def serve_openai_model(
    drt: DistributedRuntime,
    mdc: ModelDeploymentCard,
    core_engine,
    *,
    namespace: str = "dynamo",
    component: Optional[str] = None,
    endpoint: str = "generate",
    stats_handler=None,
    model_type: Optional[str] = None,
):
    """Serve ``mdc``'s model with ``core_engine`` (token-level) and register
    it for discovery. Returns the ServeHandle."""
    component = component or _component_slug(mdc)
    preprocessor = OpenAIPreprocessor(mdc)
    chat_chain = LocalChatChain(mdc, core_engine, preprocessor)
    completion_chain = LocalCompletionChain(mdc, core_engine, preprocessor)

    async def handler(request: dict, context: Context):
        # chat requests carry "messages"; completion requests carry "prompt"
        if "messages" in request:
            req = ChatCompletionRequest(**request)
            async for chunk in chat_chain(req, context):
                yield _to_payload(chunk)
        else:
            req = CompletionRequest(**request)
            async for chunk in completion_chain(req, context):
                yield _to_payload(chunk)

    comp = drt.namespace(namespace).component(component)
    await comp.create_service()
    ep = comp.endpoint(endpoint)
    handle = await ep.serve(handler, stats_handler=stats_handler)

    await mdc.publish(drt.dcp)
    mtype = model_type or mdc.model_type
    entry = ModelEntry(name=mdc.name, endpoint=ep.path, model_type=mtype)
    await register_model(drt.dcp, entry, lease=drt.primary_lease)
    log.info("model %r serving at %s (type=%s)", mdc.name, ep.path, mtype)
    return handle


async def serve_token_model(
    drt: DistributedRuntime,
    mdc: ModelDeploymentCard,
    engine,
    *,
    namespace: str = "dynamo",
    component: Optional[str] = None,
    endpoint: str = "generate_tokens",
    publish_kv_events: bool = True,
):
    """Serve the token-level engine endpoint (PreprocessedRequest dicts in,
    EngineOutput dicts out) with ForwardPassMetrics stats and KV event
    publishing — the worker of the KV-routed graph (reference
    examples/llm/components/worker.py: engine + KV metrics/event
    publishers behind a direct()-routable endpoint).

    Returns (ServeHandle, KvEventPublisher|None).
    """
    from .kv_router.publisher import KvEventPublisher
    from .protocols.common import PreprocessedRequest

    component = component or _component_slug(mdc)

    async def handler(request: dict, context: Context):
        pre = PreprocessedRequest.from_dict(request)
        async for out in engine.generate(pre, context):
            yield out.to_dict()

    comp = drt.namespace(namespace).component(component)
    await comp.create_service()
    ep = comp.endpoint(endpoint)
    handle = await ep.serve(handler,
                            stats_handler=getattr(engine, "stats", None))
    # the card is shared by all workers of the model: publish WITHOUT a
    # lease so one worker's death cannot delete it from under the others
    await mdc.publish(drt.dcp)

    publisher = None
    if publish_kv_events and hasattr(engine, "pm"):
        publisher = KvEventPublisher(
            drt.dcp, namespace, component, drt.instance_id, engine)
        publisher.start()
    log.info("token-level model %r serving at %s", mdc.name, ep.path)
    return handle, publisher


def _to_payload(chunk):
    """Chunks cross the wire as plain dicts (Annotated pass through)."""
    from ..runtime.engine import Annotated

    if isinstance(chunk, Annotated):
        return chunk
    if hasattr(chunk, "model_dump"):
        return chunk.model_dump(exclude_none=True)
    return chunk
