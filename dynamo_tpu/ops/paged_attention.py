"""Pallas TPU kernel: decode-time paged GQA attention.

The serving hot loop. The XLA fallback (models/llama.py _paged_attention)
gathers every sequence's pages into a dense [B, S, KV, hd] tensor each
decode step — O(B·S) HBM traffic through an intermediate buffer. This
kernel instead walks the page table (scalar-prefetched so the index map
can address pages before the body runs), streams each needed page
HBM→VMEM exactly once, and runs an online-softmax (flash) accumulation
on-chip for ALL heads of the sequence at once:

  grid = (batch, pages); per (b, p): q·Kᵀ for every GQA group (MXU,
  batched over the leading KV axis — the pool layout [N, KV, ps, hd] is
  chosen so no in-kernel transpose is needed) → running max/sum rescale →
  acc += softmax·V, output written on the final page step.

Pages past a sequence's length are clamped to the row's first page in the
index map: Pallas skips re-fetching a block whose index is unchanged, so
trailing invalid pages cost no HBM traffic (and `pl.when` skips their
compute). Short sequences therefore pay for the pages they own, not for
the padded page-table width.

This is the role block_copy.cu + the engines' paged-attention CUDA
kernels play in the reference (SURVEY §2.3), expressed TPU-natively.

Correctness contract (tests/test_ops.py): exact match with the XLA gather
path in float32, masking by sequence length, page-0 padding convention
(page_table rows padded with 0s; rows with length 0 produce zeros).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite "masked" value: keeps exp() NaN-free
NO_WINDOW = 1 << 30  # "infinite" effective sliding window (int32-safe)


def effective_window(window, is_sliding, B: int):
    """Per-row effective sliding window for the kernels: ``window`` on
    sliding layers, :data:`NO_WINDOW` on global ones. ``is_sliding`` is
    a traced scalar bool (Gemma-2 layer parity under lax.scan)."""
    return jnp.broadcast_to(
        jnp.where(is_sliding, jnp.int32(window), jnp.int32(NO_WINDOW)),
        (B,))


def _decode_kernel(ps: int, scale: float, return_stats: bool,
                   softcap: float | None,
                   # scalar prefetch (leading extras ignored: the layered
                   # variant prefetches the layer index first)
                   pt_ref, len_ref, lo_ref,
                   # blocks (leading dims squeezed by BlockSpec None-dims)
                   q_ref, k_ref, v_ref, o_ref, *rest):
    if return_stats:
        m_out, l_out, m_ref, l_ref, acc_ref = rest
    else:
        m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    KV, group, hd = q_ref.shape
    H = KV * group

    @pl.when(p == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = len_ref[b]
    lower = lo_ref[b]  # first visible position (sliding window); else 0

    # pages wholly outside [lower, length): no compute (and the index map
    # re-points them at an already-fetched page, so no HBM traffic)
    @pl.when(jnp.logical_and(p * ps < length, (p + 1) * ps > lower))
    def _():
        q = q_ref[...].astype(jnp.float32)            # [KV, group, hd]
        k = k_ref[...].astype(jnp.float32)            # [KV, ps, hd]
        v = v_ref[...].astype(jnp.float32)

        # batched over the shared leading KV axis (MXU, no transposes)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [KV, group, ps]
        if softcap:  # Gemma-2 score softcap — BEFORE masking
            s = softcap * jnp.tanh(s / softcap)
        pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        valid = jnp.logical_and(pos >= lower, pos < length)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:, :1].reshape(KV, group, 1)
        l_prev = l_ref[:, :1].reshape(KV, group, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                # [KV, group, 1]
        # exp only where valid: an all-masked page (possible when the
        # sliding window empties the pool view) would otherwise compute
        # exp(NEG_INF - NEG_INF) = 1 and corrupt the running sum
        p_exp = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_prev + jnp.sum(p_exp, axis=2, keepdims=True)
        pv = jax.lax.dot_general(
            p_exp, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [KV, group, hd]
        acc_ref[...] = acc_ref[...] * alpha.reshape(H, 1) + pv.reshape(H, hd)
        m_ref[...] = jnp.broadcast_to(m_new.reshape(H, 1), m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new.reshape(H, 1), l_ref.shape)

    @pl.when(p == pl.num_programs(1) - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-9)  # length-0 (padding) rows → 0
        o_ref[...] = (acc_ref[...] / l).reshape(KV, group, hd).astype(
            o_ref.dtype)
        if return_stats:
            m_out[...] = m_ref[...]
            l_out[...] = l_ref[...]


def _decode_kernel_layered(ps: int, scale: float, return_stats: bool,
                           softcap: float | None,
                           l_ref, pt_ref, len_ref, lo_ref, *refs):
    # layered variant: the layer index rides as the first scalar-prefetch
    # operand (consumed by the BlockSpec index maps); the body is identical
    del l_ref
    return _decode_kernel(ps, scale, return_stats, softcap,
                          pt_ref, len_ref, lo_ref, *refs)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "return_stats",
                                    "softcap"))
def paged_attention_decode(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_table: jax.Array,
                           lengths: jax.Array, *, scale: float | None = None,
                           interpret: bool = False,
                           return_stats: bool = False,
                           softcap: float | None = None,
                           lower: jax.Array | None = None):
    """One decode step of paged GQA attention.

    q: [B, H, hd]; k_pages/v_pages: [num_pages, KV, ps, hd];
    page_table: [B, P] int32 (pad with 0 — page 0 is reserved);
    lengths: [B] int32 — tokens of context per row INCLUDING the one just
    written (rows with length 0 are padding and return zeros).
    Returns [B, H, hd] in q.dtype; with ``return_stats`` also the online-
    softmax running stats (m, l) as float32 [B, H] so a caller can merge
    this result with attention over extra keys outside the pool (the fused
    decode window's in-flight buffer — models/llama.py
    _pool_window_attention_pallas).
    """
    # thin wrapper: a 4-D pool is the layered kernel with L=1 (the [None]
    # reshape is metadata-only — no copy)
    return paged_attention_decode_layered(
        q, k_pages[None], v_pages[None], jnp.zeros((), jnp.int32),
        page_table, lengths, scale=scale, interpret=interpret,
        return_stats=return_stats, softcap=softcap, lower=lower)


def paged_attention_decode_sharded(q: jax.Array, k_pools: jax.Array,
                                   v_pools: jax.Array, layer: jax.Array,
                                   page_table: jax.Array,
                                   lengths: jax.Array, *, mesh,
                                   scale: float | None = None,
                                   interpret: bool = False,
                                   return_stats: bool = True,
                                   softcap: float | None = None,
                                   lower: jax.Array | None = None):
    """Tensor-parallel wrapper: runs the layered kernel per model-shard
    via shard_map over the head axis. The KV pool is sharded
    [L, pages, KV@model, ps, hd] (parallel/mesh.py kv_cache_pspec) and q
    heads follow their kv heads (GQA groups never straddle shards while
    num_kv_heads % tp == 0), so each shard's kernel call is the ordinary
    single-chip kernel on its local heads — no collectives inside; the
    surrounding GSPMD program keeps the output head-sharded into wo.
    Batch rows ride the "data" axis. Replaces r2's allow_pallas=False
    fallback that dropped the kernel the moment TP was on (VERDICT r2
    weak #5). With ``return_stats`` (the fused-window caller's merge
    input) returns (out, m, l); without, just ``out`` — the K=1 decode
    path skips the two [B, H, 128] f32 stat outputs per call."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if lower is None:
        lower = jnp.zeros_like(lengths)

    def local(q_, k_, v_, l_, t_, ln_, lo_):
        return paged_attention_decode_layered(
            q_, k_, v_, l_, t_, ln_, scale=scale, interpret=interpret,
            return_stats=return_stats, softcap=softcap, lower=lo_)

    out_specs = (P("data", "model", None), P("data", "model"),
                 P("data", "model")) if return_stats \
        else P("data", "model", None)
    return shard_map(
        local, mesh=mesh,
        in_specs=(P("data", "model", None),
                  P(None, None, "model", None, None),
                  P(None, None, "model", None, None),
                  P(), P("data", None), P("data"), P("data")),
        out_specs=out_specs,
        check_vma=False,  # pallas_call outputs carry no vma annotation
    )(q, k_pools, v_pools, jnp.asarray(layer, jnp.int32), page_table,
      lengths, lower)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "return_stats",
                                    "softcap"))
def paged_attention_decode_layered(q: jax.Array, k_pools: jax.Array,
                                   v_pools: jax.Array, layer: jax.Array,
                                   page_table: jax.Array,
                                   lengths: jax.Array, *,
                                   scale: float | None = None,
                                   interpret: bool = False,
                                   return_stats: bool = False,
                                   softcap: float | None = None,
                                   lower: jax.Array | None = None):
    """paged_attention_decode against ONE layer of the stacked pools.

    k_pools/v_pools: [L, num_pages, KV, ps, hd]; ``layer`` a traced int32
    scalar. The layer rides as a scalar-prefetch operand consumed only by
    the BlockSpec index maps, so the kernel streams pages of that layer
    straight out of the stacked pool — no [num_pages, ...] layer slice is
    ever materialized. That matters because XLA materializes `pool[l]`
    (≈200 MB/layer at serving sizes) when it feeds a pallas_call, and a
    K-step fused decode window would pay that copy L·K times per window
    (measured: ~30 ms/step at B=32 — 4x the whole model's weight
    bandwidth); this variant makes the pool read O(live pages) as the
    kernel intends."""
    B, H, hd = q.shape
    L, _, KV, ps, _ = k_pools.shape
    P = page_table.shape[1]
    group = H // KV
    if scale is None:
        scale = hd ** -0.5
    q4 = q.reshape(B, KV, group, hd)
    if lower is None:
        lower = jnp.zeros_like(lengths)

    def page_index(b, p, l, pt, ln, lo):
        # pages outside [lower, length) re-point at the first NEEDED page
        # (index unchanged between steps → Pallas skips the fetch)
        needed = jnp.logical_and(p * ps < ln[b], (p + 1) * ps > lo[b])
        first = jnp.minimum(lo[b] // ps, P - 1)
        return (l[0], jnp.where(needed, pt[b, p], pt[b, first]),
                0, 0, 0)

    out_shape = [jax.ShapeDtypeStruct((B, KV, group, hd), q.dtype)]
    out_specs = [pl.BlockSpec((None, KV, group, hd),
                              lambda b, p, l, pt, ln, lo: (b, 0, 0, 0))]
    if return_stats:
        out_shape += [jax.ShapeDtypeStruct((B, H, 128), jnp.float32),
                      jax.ShapeDtypeStruct((B, H, 128), jnp.float32)]
        out_specs += [pl.BlockSpec((None, H, 128),
                                   lambda b, p, l, pt, ln, lo: (b, 0, 0))] * 2

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((None, KV, group, hd),
                         lambda b, p, l, pt, ln, lo: (b, 0, 0, 0)),
            pl.BlockSpec((None, None, KV, ps, hd), page_index),
            pl.BlockSpec((None, None, KV, ps, hd), page_index),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, hd), jnp.float32),
        ],
    )
    res = pl.pallas_call(
        functools.partial(_decode_kernel_layered, ps, scale, return_stats,
                          softcap),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1),
      page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      lower.astype(jnp.int32),
      q4, k_pools, v_pools)
    out = res[0].reshape(B, H, hd)
    if return_stats:
        return out, res[1][:, :, 0], res[2][:, :, 0]
    return out


def paged_attention_prefill_sharded(q: jax.Array, k_pages: jax.Array,
                                    v_pages: jax.Array,
                                    page_table: jax.Array,
                                    q_positions: jax.Array, *, mesh,
                                    scale: float | None = None,
                                    interpret: bool = False,
                                    softcap: float | None = None,
                                    eff_win: jax.Array | None = None
                                    ) -> jax.Array:
    """Tensor-parallel chunked-prefill kernel: shard_map over the head
    ("model") and batch ("data") axes, same decomposition as
    paged_attention_decode_sharded — each shard runs the ordinary kernel
    on its local KV heads (q heads follow their kv heads; GQA groups
    never straddle shards while num_kv_heads % tp == 0) and local batch
    rows. No collectives inside: softmax is per-head, so the output
    stays head-sharded into wo. Closes the r3 gap where prefill dropped
    to the XLA gather path the moment the pool was mesh-sharded
    (VERDICT r3 weak #3)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if eff_win is None:
        eff_win = jnp.full((q.shape[0],), jnp.int32(NO_WINDOW))

    def local(q_, k_, v_, t_, qp_, win_):
        return paged_attention_prefill(q_, k_, v_, t_, qp_, scale=scale,
                                       interpret=interpret,
                                       softcap=softcap, eff_win=win_)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P("data", None, "model", None),
                  P(None, "model", None, None),
                  P(None, "model", None, None),
                  P("data", None), P("data", None), P("data")),
        out_specs=P("data", None, "model", None),
        check_vma=False,  # pallas_call outputs carry no vma annotation
    )(q, k_pages, v_pages, page_table, q_positions, eff_win)


# ------------------------------------------------------- prefill kernel


def _prefill_kernel(ps: int, scale: float, softcap: float | None,
                    pt_ref, len_ref, lo_ref, win_ref,    # scalar prefetch
                    q_ref, qpos_ref, k_ref, v_ref, o_ref,
                    m_ref, l_ref, acc_ref):
    """Chunked-prefill flash attention over the paged pool.

    Per (b, kv) the query chunk stays VMEM-resident while pages stream
    in (grid innermost axis); online softmax runs per query row. The
    causal structure is positional: kv slot j of table entry p holds
    logical position p*ps+j, visible to query t iff within
    (q_position[t] - window, q_position[t]] — window is the per-row
    effective sliding window (huge when the layer is global).
    """
    b = pl.program_id(0)
    p = pl.program_id(2)
    T, group, hd = q_ref.shape

    @pl.when(p == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = len_ref[b]
    lower = lo_ref[b]  # first position any query of the row can see
    win = win_ref[b]

    # pages wholly outside [lower, length): no compute, no fetch
    @pl.when(jnp.logical_and(p * ps < length, (p + 1) * ps > lower))
    def _():
        q = q_ref[...].astype(jnp.float32).reshape(T * group, hd)
        k = k_ref[...].astype(jnp.float32)             # [ps, hd]
        v = v_ref[...].astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [T*group, ps]
        s = s.reshape(T, group, ps)
        if softcap:  # Gemma-2 score softcap — BEFORE masking
            s = softcap * jnp.tanh(s / softcap)
        kv_pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        q_pos = qpos_ref[...].reshape(T, 1, 1)
        valid = jnp.logical_and(kv_pos <= q_pos,       # causal + padding
                                kv_pos > q_pos - win)  # sliding window
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[...].reshape(T, group, 1)
        l_prev = l_ref[...].reshape(T, group, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # exp only where valid: an all-masked (t, page) pair (window
        # already slid past the page) would otherwise add exp(0)=1 rows
        p_exp = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_prev + jnp.sum(p_exp, axis=2, keepdims=True)
        pv = jax.lax.dot_general(
            p_exp.reshape(T * group, ps), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [T*group, hd]
        acc_ref[...] = (acc_ref[...] * alpha.reshape(T * group, 1) + pv)
        m_ref[...] = m_new.reshape(T, group)
        l_ref[...] = l_new.reshape(T, group)

    @pl.when(p == pl.num_programs(2) - 1)
    def _():
        l = jnp.maximum(l_ref[...].reshape(T * group, 1), 1e-9)
        o_ref[...] = (acc_ref[...] / l).reshape(T, group, hd).astype(
            o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret",
                                             "softcap"))
def paged_attention_prefill(q: jax.Array, k_pages: jax.Array,
                            v_pages: jax.Array, page_table: jax.Array,
                            q_positions: jax.Array, *,
                            scale: float | None = None,
                            interpret: bool = False,
                            softcap: float | None = None,
                            eff_win: jax.Array | None = None) -> jax.Array:
    """Chunked-prefill paged GQA attention (flash form).

    q: [B, T, H, hd] (the current chunk); k_pages/v_pages:
    [num_pages, KV, ps, hd] — the chunk's K/V already written;
    page_table: [B, P]; q_positions: [B, T] absolute (-1 padding).
    Returns [B, T, H, hd] in q.dtype, numerically matching the XLA
    gather path (models/llama.py _paged_attention) which materializes
    a dense [B, P*ps, KV, hd] copy per layer; here pages stream through
    VMEM once. Opt-in via DYN_PREFILL_PALLAS (see llama._attention).
    """
    B, T, H, hd = q.shape
    _, KV, ps, _ = k_pages.shape
    P = page_table.shape[1]
    group = H // KV
    if scale is None:
        scale = hd ** -0.5
    q5 = q.reshape(B, T, KV, group, hd).transpose(0, 2, 1, 3, 4)
    # pages to visit per row: those covering [lower, max position]
    lengths = jnp.max(q_positions, axis=1) + 1  # [B]; all-pad rows → 0
    if eff_win is None:
        eff_win = jnp.full((B,), jnp.int32(NO_WINDOW))
    # first position visible to ANY query of the row: min valid q_pos
    # minus the window; pages before it are skipped outright
    minq = jnp.min(jnp.where(q_positions >= 0, q_positions, NO_WINDOW),
                   axis=1)
    lower = jnp.clip(minq + 1 - eff_win, 0, jnp.maximum(lengths - 1, 0))

    def page_index(b, kv, p, pt, ln, lo, win):
        needed = jnp.logical_and(p * ps < ln[b], (p + 1) * ps > lo[b])
        first = jnp.minimum(lo[b] // ps, P - 1)
        return (jnp.where(needed, pt[b, p], pt[b, first]), kv, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, KV, P),
        in_specs=[
            pl.BlockSpec((None, None, T, group, hd),
                         lambda b, kv, p, pt, ln, lo, win:
                         (b, kv, 0, 0, 0)),
            pl.BlockSpec((None, T),
                         lambda b, kv, p, pt, ln, lo, win: (b, 0)),
            pl.BlockSpec((None, None, ps, hd), page_index),
            pl.BlockSpec((None, None, ps, hd), page_index),
        ],
        out_specs=pl.BlockSpec((None, None, T, group, hd),
                               lambda b, kv, p, pt, ln, lo, win:
                               (b, kv, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((T, group), jnp.float32),
            pltpu.VMEM((T, group), jnp.float32),
            pltpu.VMEM((T * group, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_prefill_kernel, ps, scale, softcap),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, T, group, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      lower.astype(jnp.int32), eff_win.astype(jnp.int32),
      q5, q_positions.astype(jnp.int32), k_pages, v_pages)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, T, H, hd)
