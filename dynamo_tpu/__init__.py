"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

A from-scratch re-design of the capabilities of NVIDIA Dynamo
(reference: /root/reference, snapshot v0.1.0) for TPU hardware:

- ``dynamo_tpu.runtime``   — distributed runtime: control-plane service
  (discovery/leases/watches + request plane + event plane + work queues),
  TCP streaming response plane, Component/Endpoint addressing, AsyncEngine.
  (reference: lib/runtime/src/)
- ``dynamo_tpu.llm``       — OpenAI protocol + HTTP frontend, preprocessor,
  detokenizing backend, model cards, KV-aware router, disagg router.
  (reference: lib/llm/src/)
- ``dynamo_tpu.engine``    — the JAX serving engine: paged KV cache,
  continuous batching scheduler, prefill/decode programs. (replaces the
  reference's patched-vLLM worker data plane)
- ``dynamo_tpu.models``    — JAX model implementations (Llama, Mixtral, ...).
- ``dynamo_tpu.ops``       — Pallas/XLA kernels (paged attention, block copy).
- ``dynamo_tpu.parallel``  — mesh construction, shardings, ring attention.
- ``dynamo_tpu.sdk``       — ``@service`` graph SDK + CLI (dynamo serve/run).
"""

__version__ = "0.1.0"
