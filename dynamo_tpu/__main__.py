"""Top-level CLI dispatch: ``python -m dynamo_tpu <command>``.

Commands mirror the reference's binaries (SURVEY §2.5):
  run         dynamo-run: in=… out=… single-process serving
  serve       SDK graph deployment (deploy/dynamo/sdk CLI)
  llmctl      model registration CLI (launch/llmctl)
  dcp-server  standalone control-plane server (etcd+NATS analog)
  fetch-model seed a checkpoint to a directory (DynamoModelRequest Job)
"""

from __future__ import annotations

import sys


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    cmd, argv = sys.argv[1], sys.argv[2:]
    if cmd == "run":
        from .run import main as run_main

        return run_main(argv)
    if cmd in ("serve", "serve-worker"):
        from .sdk.cli import main as sdk_main

        return sdk_main([cmd] + argv)
    if cmd == "llmctl":
        from .llm.llmctl import main as llmctl_main

        return llmctl_main(argv)
    if cmd == "dcp-server":
        from .runtime.dcp_server import main as dcp_main

        return dcp_main(argv)
    if cmd == "fetch-model":
        from .models.hub import fetch_model_cli

        return fetch_model_cli(argv)
    print(f"unknown command {cmd!r}\n{__doc__}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
