"""Shared key scheme for the deployment spec store.

The admin API writes specs here (api_server.py) and the planner's
``--apply`` path edits them (planner/planner.py); a single constant keeps
the two components on the same keys.
"""

DEPLOYMENT_PREFIX = "deployments/"
