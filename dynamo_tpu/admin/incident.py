"""dynablack postmortem renderer: ``python -m dynamo_tpu.admin.incident``.

Turns one persisted incident bundle (a ``GET /debug/incidents/{id}``
payload / ``DYN_BLACKBOX_DIR`` file / fleet-sim report ``incident``
block) into the human-readable 3 a.m. view:

- header: trigger, detail, capture time, contributing workers
- burn-rate timeline (SLO alert transitions found in the bundle)
- per-stage trace rollup (span name -> count / total / max duration)
- worst cost-table buckets vs their pre-incident baseline
- cache hit-rate cliff (windowed vs lifetime hit rate per cache)
- per-worker shadow rings, aligned by their timeline anchors

Every section renders defensively: a bundle missing a plane (sim
bundles carry no process telemetry; a frontend-only capture carries no
fleet scrape) prints "(not captured)" instead of crashing — the
acceptance bar is that the renderer never errors on a real bundle.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional


def _fmt_ms(ms: Optional[float]) -> str:
    if ms is None:
        return "-"
    return f"{ms:,.1f}ms"


def _section(title: str) -> List[str]:
    return ["", title, "-" * len(title)]


def render_header(bundle: dict) -> List[str]:
    lines = [f"incident {bundle.get('id', '?')}",
             "=" * max(len(f"incident {bundle.get('id', '?')}"), 8)]
    lines.append(f"trigger:     {bundle.get('trigger', '?')}")
    detail = bundle.get("detail") or {}
    if detail:
        lines.append(f"detail:      {json.dumps(detail, sort_keys=True)}")
    lines.append(f"captured at: {_fmt_ms(bundle.get('at_wall_ms'))} "
                 f"(window {bundle.get('window_s', '?')}s)")
    if bundle.get("origin"):
        lines.append(f"origin:      {bundle['origin']} (remote capture)")
    workers = bundle.get("workers") or {}
    contributed = bundle.get("contributed") or []
    lines.append(f"workers:     {len(workers)} ring(s): "
                 f"{', '.join(sorted(workers)) or '(none)'}")
    if contributed:
        lines.append(f"contributed: {', '.join(contributed)}")
    return lines


def render_burn_timeline(bundle: dict) -> List[str]:
    lines = _section("burn-rate timeline")
    events: List[dict] = []
    detail = bundle.get("detail") or {}
    if "burn_fast" in detail:
        events.append(detail)
    scrape = (bundle.get("sources") or {}).get("fleet_scrape") or {}
    for ev in scrape.get("alerts", []):
        if ev not in events:
            events.append(ev)
    if not events:
        lines.append("(no alert transitions captured)")
        return lines
    for ev in events:
        lines.append(
            f"  t={ev.get('at', '?')}  {ev.get('objective', '?'):<24} "
            f"{ev.get('state', '?'):<8} "
            f"fast={ev.get('burn_fast', '?')} slow={ev.get('burn_slow', '?')}")
    return lines


def render_stage_rollup(bundle: dict) -> List[str]:
    lines = _section("per-stage trace rollup")
    spans = (bundle.get("telemetry") or {}).get("spans") or []
    if not spans:
        lines.append("(no spans captured)")
        return lines
    stages: Dict[str, List[float]] = {}
    for s in spans:
        dur = s.get("duration_ms")
        if dur is not None:
            stages.setdefault(s.get("name", "?"), []).append(float(dur))
    rows = sorted(stages.items(), key=lambda kv: -sum(kv[1]))
    lines.append(f"  {'stage':<32} {'count':>6} {'total':>12} {'max':>12}")
    for name, durs in rows[:20]:
        lines.append(f"  {name:<32} {len(durs):>6} "
                     f"{_fmt_ms(sum(durs)):>12} {_fmt_ms(max(durs)):>12}")
    return lines


def _cost_buckets(profiles: Any) -> Dict[str, dict]:
    """Flatten {engine: {buckets: {key: {...}}}} into one keyed table."""
    out: Dict[str, dict] = {}
    for engine, prof in (profiles or {}).items():
        for key, row in ((prof or {}).get("buckets") or {}).items():
            out[f"{engine}/{key}"] = row if isinstance(row, dict) else {}
    return out


def render_cost_table(bundle: dict) -> List[str]:
    lines = _section("worst cost-table buckets vs pre-incident baseline")
    now = _cost_buckets((bundle.get("telemetry") or {}).get("profiles"))
    base = _cost_buckets((bundle.get("baseline") or {}).get("profiles"))
    if not now:
        lines.append("(no cost table captured)")
        return lines

    def _us(row: dict) -> Optional[float]:
        for k in ("dispatch_us_mean", "dispatch_us", "host_us_mean"):
            if isinstance(row.get(k), (int, float)):
                return float(row[k])
        return None

    rows = []
    for key, row in now.items():
        cur = _us(row)
        if cur is None:
            continue
        ref = _us(base.get(key, {}))
        delta = None if ref is None or ref == 0 else (cur - ref) / ref
        rows.append((key, cur, ref, delta))
    if not rows:
        lines.append("(cost table has no dispatch timings)")
        return lines
    rows.sort(key=lambda r: -(r[3] if r[3] is not None else 0.0))
    lines.append(f"  {'bucket':<44} {'now':>10} {'baseline':>10} "
                 f"{'delta':>8}")
    for key, cur, ref, delta in rows[:15]:
        d = "-" if delta is None else f"{delta:+.0%}"
        r = "-" if ref is None else f"{ref:.1f}us"
        lines.append(f"  {key:<44} {cur:>9.1f}us {r:>10} {d:>8}")
    return lines


def render_cache_cliff(bundle: dict) -> List[str]:
    lines = _section("cache hit-rate cliff (windowed vs lifetime)")
    caches = (bundle.get("telemetry") or {}).get("caches") or {}
    base = (bundle.get("baseline") or {}).get("caches") or {}
    if not caches:
        lines.append("(no cache snapshots captured)")
        return lines

    def _rates(snap: dict) -> tuple:
        windowed = snap.get("hit_rate_windowed", snap.get("hit_rate"))
        lifetime = snap.get("hit_rate_lifetime", snap.get("hit_rate"))
        return windowed, lifetime

    for name, snap in sorted(caches.items()):
        if not isinstance(snap, dict):
            continue
        windowed, lifetime = _rates(snap)
        base_w, _ = _rates(base.get(name, {})) if isinstance(
            base.get(name), dict) else (None, None)
        parts = [f"  {name:<40}"]
        parts.append(f"windowed={windowed if windowed is not None else '-'}")
        parts.append(f"lifetime={lifetime if lifetime is not None else '-'}")
        if base_w is not None:
            parts.append(f"baseline={base_w}")
        lines.append(" ".join(str(p) for p in parts))
    return lines


def render_worker_rings(bundle: dict, max_events: int = 12) -> List[str]:
    lines = _section("per-worker shadow rings (timeline-anchor aligned)")
    workers = bundle.get("workers") or {}
    if not workers:
        lines.append("(no shadow rings captured)")
        return lines
    for label in sorted(workers):
        data = workers[label] or {}
        anchors = data.get("anchors") or {}
        events = data.get("events") or []
        lines.append(f"  {label}: {len(events)} event(s), "
                     f"anchor wall={anchors.get('anchor_wall', '-')} "
                     f"mono={anchors.get('anchor_monotonic', '-')}")
        for ev in events[-max_events:]:
            kind = ev.get("kind", "?")
            rest = {k: v for k, v in ev.items()
                    if k not in ("kind", "mono_ms", "ts_ms")}
            lines.append(f"    +{ev.get('mono_ms', '?')}ms {kind:<14} "
                         + json.dumps(rest, sort_keys=True))
        if len(events) > max_events:
            lines.append(f"    ... ({len(events) - max_events} earlier "
                         "event(s) omitted)")
    return lines


def render_guard_state(bundle: dict) -> List[str]:
    lines = _section("guard plane (breakers / counters / chaos)")
    tel = bundle.get("telemetry") or {}
    breakers = tel.get("breakers") or {}
    counters = tel.get("guard_counters") or {}
    chaos = tel.get("chaos")
    if not breakers and not counters and chaos is None:
        lines.append("(not captured)")
        return lines
    for board, rows in sorted(breakers.items()):
        for key, st in sorted((rows or {}).items()):
            lines.append(f"  breaker {board}/{key}: {st.get('state', '?')} "
                         f"(failures={st.get('failures', '?')}, "
                         f"opened_total={st.get('opened_total', '?')})")
    for name, val in sorted(counters.items()):
        lines.append(f"  counter {name} = {val}")
    if chaos:
        lines.append(f"  chaos injected: "
                     f"{json.dumps(chaos.get('injected', {}), sort_keys=True)}")
    return lines


def render_postmortem(bundle: dict) -> str:
    lines: List[str] = []
    lines += render_header(bundle)
    lines += render_burn_timeline(bundle)
    lines += render_stage_rollup(bundle)
    lines += render_cost_table(bundle)
    lines += render_cache_cliff(bundle)
    lines += render_guard_state(bundle)
    lines += render_worker_rings(bundle)
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print("usage: python -m dynamo_tpu.admin.incident <bundle.json>\n"
              "       (also accepts '-' for stdin)", file=sys.stderr)
        return 2
    if argv[0] == "-":
        raw = sys.stdin.read()
    else:
        try:
            with open(argv[0], "r", encoding="utf-8") as f:
                raw = f.read()
        except OSError as e:
            print(f"error: cannot read {argv[0]}: {e}", file=sys.stderr)
            return 1
    try:
        bundle = json.loads(raw)
    except ValueError as e:
        print(f"error: {argv[0]} is not JSON: {e}", file=sys.stderr)
        return 1
    if not isinstance(bundle, dict):
        print("error: bundle must be a JSON object", file=sys.stderr)
        return 1
    # a fleet-sim report was passed instead of a bundle: descend
    if "incident" in bundle and "trigger" not in bundle:
        bundle = bundle["incident"]
    print(render_postmortem(bundle))
    return 0


if __name__ == "__main__":
    sys.exit(main())
