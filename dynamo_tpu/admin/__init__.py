"""Admin/control REST API (reference deploy/dynamo/api-server)."""

from .api_server import AdminApiServer

__all__ = ["AdminApiServer"]
