"""REST admin/control API over the distributed runtime.

Reference deploy/dynamo/api-server (Go, ~11k LoC: REST services for
clusters/deployments/components backed by a DB + K8s, with per-user/org
auth): here the control plane's KV store IS the database, so the API
server is a thin aiohttp app exposing what operators need — registered
models, live endpoint instances, service records, model cards, and
stored deployment specs (consumed by the deploy/kubernetes renderer or
the in-cluster controller).

Multi-tenancy: bearer-token auth with role + namespace scoping (the
api-server's organizations/users plane, collapsed to what a serving
control plane actually gates). Tokens come from ``--tokens-file`` /
``DYN_ADMIN_TOKENS`` as a JSON list of ``{"token", "label", "role":
"admin"|"writer"|"reader", "namespace"?}``:

- ``admin``    — everything;
- ``writer``   — read everything; mutate only resources whose namespace
  matches its claim (deployments carry ``metadata.namespace``; models
  are global, so namespace-restricted writers cannot mutate them);
- ``reader``   — GET only.

No tokens configured → the API is open (single-operator deployments,
and the in-cluster default where the pod network is the boundary).
Every mutation is audit-logged with the token LABEL, never the token.

    python -m dynamo_tpu.admin.api_server --port 8800 --dcp 127.0.0.1:6650
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from aiohttp import web

from ..llm.entry import MODEL_PREFIX, ModelEntry, register_model, remove_model
from ..llm.model_card import MDC_PREFIX
from ..runtime.component import INSTANCE_ROOT, EndpointInstance
from ..runtime.config import env_str
from ..runtime.dcp_client import pack, unpack
from ..runtime.runtime import DistributedRuntime

from .store import DEPLOYMENT_PREFIX

log = logging.getLogger("dynamo_tpu.admin")


class Principal:
    """Resolved identity of a request: role + optional namespace claim."""

    __slots__ = ("label", "role", "namespace")

    def __init__(self, label: str, role: str,
                 namespace: Optional[str] = None):
        self.label, self.role, self.namespace = label, role, namespace

    def can_mutate(self, namespace: Optional[str]) -> bool:
        """namespace=None marks a GLOBAL resource (models): those need
        an unrestricted writer or admin."""
        if self.role == "admin":
            return True
        if self.role != "writer":
            return False
        if self.namespace is None:
            return True
        return namespace == self.namespace


_OPEN = Principal("anonymous", "admin")  # no tokens configured


class AdminApiServer:
    def __init__(self, drt: DistributedRuntime,
                 tokens: Optional[List[Dict]] = None):
        self.drt = drt
        # None = auth not configured (open); [] = auth CONFIGURED with
        # zero valid tokens (a templated file whose values were unset) —
        # that must fail closed, not silently grant anonymous admin
        self._auth_enabled = tokens is not None
        self._tokens: Dict[str, Principal] = {}
        for t in tokens or []:
            if not t.get("token"):
                raise ValueError(f"token entry {t.get('label')!r}: "
                                 "missing 'token'")
            if t.get("role") not in ("admin", "writer", "reader"):
                raise ValueError(f"token {t.get('label')!r}: role must be "
                                 "admin|writer|reader")
            self._tokens[t["token"]] = Principal(
                t.get("label", "unnamed"), t["role"], t.get("namespace"))
        self.app = web.Application(middlewares=[self._auth_middleware])
        r = self.app.router
        r.add_get("/healthz", self._health)
        r.add_get("/api/v1/models", self._models_list)
        r.add_post("/api/v1/models", self._models_add)
        r.add_delete("/api/v1/models/{mtype}/{name}", self._models_delete)
        r.add_get("/api/v1/instances", self._instances)
        r.add_get("/api/v1/services", self._services)
        r.add_get("/api/v1/cards", self._cards)
        r.add_get("/api/v1/planner/advisories", self._planner_advisories)
        r.add_get("/api/v1/deployments", self._deployments_list)
        r.add_post("/api/v1/deployments", self._deployments_put)
        r.add_get("/api/v1/deployments/{name}", self._deployments_get)
        r.add_delete("/api/v1/deployments/{name}", self._deployments_delete)
        self._runner: Optional[web.AppRunner] = None

    async def start(self, host: str = "0.0.0.0", port: int = 8800) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        await web.TCPSite(self._runner, host, port).start()
        log.info("admin api on %s:%d", host, port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # ---------------------------------------------------------------- auth

    @web.middleware
    async def _auth_middleware(self, req, handler):
        if not self._auth_enabled or req.path == "/healthz":
            req["principal"] = _OPEN
            return await handler(req)
        auth = req.headers.get("Authorization", "")
        # RFC 7235: the auth-scheme is case-insensitive
        token = (auth[7:] if auth[:7].lower() == "bearer " else "")
        p = self._tokens.get(token)
        if p is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        if req.method not in ("GET", "HEAD") and p.role == "reader":
            return web.json_response(
                {"error": "forbidden: read-only token"}, status=403)
        req["principal"] = p
        return await handler(req)

    @staticmethod
    def _audit(req, action: str, target: str) -> None:
        log.info("audit: %s %s by %s(%s)", action, target,
                 req["principal"].label, req["principal"].role)

    @staticmethod
    def _forbid(req, namespace: Optional[str]):
        """None result = allowed; a response = the 403 to return."""
        p = req["principal"]
        if p.can_mutate(namespace):
            return None
        scope = namespace if namespace is not None else "(global)"
        return web.json_response(
            {"error": f"forbidden: token {p.label!r} cannot mutate "
                      f"namespace {scope}"}, status=403)

    # ------------------------------------------------------------ handlers

    async def _health(self, _req):
        return web.json_response({"ok": True,
                                  "instance_id": self.drt.instance_id})

    async def _models_list(self, _req):
        items = await self.drt.dcp.kv_get_prefix(MODEL_PREFIX)
        return web.json_response(
            {"models": [unpack(i.value) for i in items]})

    async def _models_add(self, req):
        denied = self._forbid(req, None)  # models are global
        if denied:
            return denied
        body = await req.json()
        entry = ModelEntry(name=body["name"], endpoint=body["endpoint"],
                           model_type=body.get("model_type", "chat"))
        await register_model(self.drt.dcp, entry)
        self._audit(req, "models.add", entry.name)
        return web.json_response({"added": entry.to_dict()})

    async def _models_delete(self, req):
        denied = self._forbid(req, None)
        if denied:
            return denied
        ok = await remove_model(self.drt.dcp, req.match_info["name"],
                                req.match_info["mtype"])
        if ok:  # audit records what HAPPENED, not what was attempted
            self._audit(req, "models.delete", req.match_info["name"])
        return web.json_response({"removed": ok},
                                 status=200 if ok else 404)

    async def _instances(self, _req):
        items = await self.drt.dcp.kv_get_prefix(INSTANCE_ROOT)
        out = []
        for i in items:
            try:
                out.append(EndpointInstance.from_dict(unpack(i.value))
                           .to_dict())
            except Exception:
                log.debug("skipping malformed instance record %s", i.key,
                          exc_info=True)
        return web.json_response({"instances": out})

    async def _services(self, _req):
        items = await self.drt.dcp.kv_get_prefix("services/")
        return web.json_response(
            {"services": [unpack(i.value) for i in items]})

    async def _cards(self, _req):
        items = await self.drt.dcp.kv_get_prefix(MDC_PREFIX)
        return web.json_response(
            {"cards": [unpack(i.value) for i in items]})

    async def _planner_advisories(self, _req):
        from ..planner import read_advisories
        return web.json_response(
            {"advisories": await read_advisories(self.drt.dcp)})

    async def _deployments_list(self, _req):
        items = await self.drt.dcp.kv_get_prefix(DEPLOYMENT_PREFIX)
        return web.json_response(
            {"deployments": [unpack(i.value) for i in items]})

    async def _deployments_put(self, req):
        spec = await req.json()
        name = (spec.get("metadata") or {}).get("name")
        if not name:
            return web.json_response({"error": "metadata.name required"},
                                     status=400)
        ns = (spec.get("metadata") or {}).get("namespace", "default")
        denied = self._forbid(req, ns)
        if denied:
            return denied
        p = req["principal"]
        if p.role == "writer" and p.namespace is not None:
            # a scoped writer must also not OVERWRITE a spec that lives
            # in another namespace under the same name (the extra KV
            # read is skipped for admin/open, where it cannot fail)
            cur = await self.drt.dcp.kv_get(f"{DEPLOYMENT_PREFIX}{name}")
            if cur is not None:
                cur_ns = ((unpack(cur).get("metadata") or {})
                          .get("namespace", "default"))
                denied = self._forbid(req, cur_ns)
                if denied:
                    return denied
        await self.drt.dcp.kv_put(f"{DEPLOYMENT_PREFIX}{name}", pack(spec))
        self._audit(req, "deployments.put", f"{ns}/{name}")
        return web.json_response({"stored": name})

    async def _deployments_get(self, req):
        raw = await self.drt.dcp.kv_get(
            f"{DEPLOYMENT_PREFIX}{req.match_info['name']}")
        if raw is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(unpack(raw))

    async def _deployments_delete(self, req):
        name = req.match_info["name"]
        cur = await self.drt.dcp.kv_get(f"{DEPLOYMENT_PREFIX}{name}")
        if cur is None:
            return web.json_response({"removed": False}, status=404)
        ns = ((unpack(cur).get("metadata") or {})
              .get("namespace", "default"))
        denied = self._forbid(req, ns)
        if denied:
            return denied
        ok = await self.drt.dcp.kv_delete(f"{DEPLOYMENT_PREFIX}{name}")
        self._audit(req, "deployments.delete", f"{ns}/{name}")
        return web.json_response({"removed": ok},
                                 status=200 if ok else 404)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="dynamo-admin")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8800)
    ap.add_argument("--dcp", default=None)
    ap.add_argument("--tokens-file", default=None,
                    help="JSON list of {token,label,role,namespace?}; "
                         "also DYN_ADMIN_TOKENS (inline JSON). Absent = "
                         "open API")
    args = ap.parse_args(argv)

    import json as _json

    tokens = None
    if args.tokens_file:
        with open(args.tokens_file) as f:
            tokens = _json.load(f)
    elif env_str("DYN_ADMIN_TOKENS"):
        tokens = _json.loads(env_str("DYN_ADMIN_TOKENS"))

    async def amain():
        drt = await DistributedRuntime.attach(
            args.dcp or env_str("DYN_DCP_ADDRESS"))
        srv = AdminApiServer(drt, tokens=tokens)
        await srv.start(args.host, args.port)
        try:
            await asyncio.Event().wait()
        finally:
            await srv.stop()
            await drt.shutdown()

    logging.basicConfig(level="INFO")
    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    main()
