"""REST admin/control API over the distributed runtime.

Reference deploy/dynamo/api-server (Go, ~11k LoC: REST services for
clusters/deployments/components backed by a DB + K8s): here the control
plane's KV store IS the database, so the API server is a thin aiohttp app
exposing what operators need — registered models, live endpoint instances,
service records, model cards, and stored deployment specs (consumed by
the deploy/kubernetes renderer or a future in-cluster controller).

    python -m dynamo_tpu.admin.api_server --port 8800 --dcp 127.0.0.1:6650
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from aiohttp import web

from ..llm.entry import MODEL_PREFIX, ModelEntry, register_model, remove_model
from ..llm.model_card import MDC_PREFIX
from ..runtime.component import INSTANCE_ROOT, EndpointInstance
from ..runtime.dcp_client import pack, unpack
from ..runtime.runtime import DistributedRuntime

from .store import DEPLOYMENT_PREFIX

log = logging.getLogger("dynamo_tpu.admin")


class AdminApiServer:
    def __init__(self, drt: DistributedRuntime):
        self.drt = drt
        self.app = web.Application()
        r = self.app.router
        r.add_get("/healthz", self._health)
        r.add_get("/api/v1/models", self._models_list)
        r.add_post("/api/v1/models", self._models_add)
        r.add_delete("/api/v1/models/{mtype}/{name}", self._models_delete)
        r.add_get("/api/v1/instances", self._instances)
        r.add_get("/api/v1/services", self._services)
        r.add_get("/api/v1/cards", self._cards)
        r.add_get("/api/v1/planner/advisories", self._planner_advisories)
        r.add_get("/api/v1/deployments", self._deployments_list)
        r.add_post("/api/v1/deployments", self._deployments_put)
        r.add_get("/api/v1/deployments/{name}", self._deployments_get)
        r.add_delete("/api/v1/deployments/{name}", self._deployments_delete)
        self._runner: Optional[web.AppRunner] = None

    async def start(self, host: str = "0.0.0.0", port: int = 8800) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        await web.TCPSite(self._runner, host, port).start()
        log.info("admin api on %s:%d", host, port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # ------------------------------------------------------------ handlers

    async def _health(self, _req):
        return web.json_response({"ok": True,
                                  "instance_id": self.drt.instance_id})

    async def _models_list(self, _req):
        items = await self.drt.dcp.kv_get_prefix(MODEL_PREFIX)
        return web.json_response(
            {"models": [unpack(i.value) for i in items]})

    async def _models_add(self, req):
        body = await req.json()
        entry = ModelEntry(name=body["name"], endpoint=body["endpoint"],
                           model_type=body.get("model_type", "chat"))
        await register_model(self.drt.dcp, entry)
        return web.json_response({"added": entry.to_dict()})

    async def _models_delete(self, req):
        ok = await remove_model(self.drt.dcp, req.match_info["name"],
                                req.match_info["mtype"])
        return web.json_response({"removed": ok},
                                 status=200 if ok else 404)

    async def _instances(self, _req):
        items = await self.drt.dcp.kv_get_prefix(INSTANCE_ROOT)
        out = []
        for i in items:
            try:
                out.append(EndpointInstance.from_dict(unpack(i.value))
                           .to_dict())
            except Exception:
                pass
        return web.json_response({"instances": out})

    async def _services(self, _req):
        items = await self.drt.dcp.kv_get_prefix("services/")
        return web.json_response(
            {"services": [unpack(i.value) for i in items]})

    async def _cards(self, _req):
        items = await self.drt.dcp.kv_get_prefix(MDC_PREFIX)
        return web.json_response(
            {"cards": [unpack(i.value) for i in items]})

    async def _planner_advisories(self, _req):
        from ..planner import read_advisories
        return web.json_response(
            {"advisories": await read_advisories(self.drt.dcp)})

    async def _deployments_list(self, _req):
        items = await self.drt.dcp.kv_get_prefix(DEPLOYMENT_PREFIX)
        return web.json_response(
            {"deployments": [unpack(i.value) for i in items]})

    async def _deployments_put(self, req):
        spec = await req.json()
        name = (spec.get("metadata") or {}).get("name")
        if not name:
            return web.json_response({"error": "metadata.name required"},
                                     status=400)
        await self.drt.dcp.kv_put(f"{DEPLOYMENT_PREFIX}{name}", pack(spec))
        return web.json_response({"stored": name})

    async def _deployments_get(self, req):
        raw = await self.drt.dcp.kv_get(
            f"{DEPLOYMENT_PREFIX}{req.match_info['name']}")
        if raw is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(unpack(raw))

    async def _deployments_delete(self, req):
        ok = await self.drt.dcp.kv_delete(
            f"{DEPLOYMENT_PREFIX}{req.match_info['name']}")
        return web.json_response({"removed": ok},
                                 status=200 if ok else 404)


def main(argv=None) -> int:
    import argparse
    import os

    ap = argparse.ArgumentParser(prog="dynamo-admin")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8800)
    ap.add_argument("--dcp", default=None)
    args = ap.parse_args(argv)

    async def amain():
        drt = await DistributedRuntime.attach(
            args.dcp or os.environ.get("DYN_DCP_ADDRESS"))
        srv = AdminApiServer(drt)
        await srv.start(args.host, args.port)
        try:
            await asyncio.Event().wait()
        finally:
            await srv.stop()
            await drt.shutdown()

    logging.basicConfig(level="INFO")
    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    main()
