"""TCP streaming response plane.

The request plane (DCP request/reply) carries only the request; responses
stream back over a dedicated raw TCP connection from the worker to the
caller ("call-home" pattern — reference
lib/runtime/src/pipeline/network/tcp/server.rs and egress/push.rs:121-158):

1. The caller registers a pending stream (uuid subject) with its local
   ``TcpStreamServer`` and sends its ``(address, subject)`` inside the request.
2. The worker connects back, sends a handshake frame naming the subject, then
   streams ``data`` frames followed by a ``complete``/``error`` sentinel.
3. The connection is full-duplex: the caller can send ``ctrl`` frames
   (``stop``/``kill``) upstream, which the worker surfaces on the request's
   ``Context`` (reference AsyncEngineContext stop_generating/kill,
   lib/runtime/src/engine.rs:47-85).
"""

from __future__ import annotations

import asyncio
import logging
import socket
import uuid
from dataclasses import dataclass
from typing import Dict, Optional

from . import guard, wire
from .codec import TwoPartMessage, decode, encode
from .config import env_float
from .tasks import cancel_join, spawn_tracked

log = logging.getLogger("dynamo_tpu.tcp")


def _io_timeout() -> float:
    """Bound on single IO steps (connect/handshake/drain): a dead peer
    fails a hop in DYN_IO_TIMEOUT instead of wedging it forever."""
    return env_float("DYN_IO_TIMEOUT", 30.0) or 30.0

# sentinel objects pushed into the receive queue
STREAM_COMPLETE = object()


@dataclass
class StreamError:
    message: str
    kind: str = ""  # exception class name from the worker, if known


@dataclass
class TcpConnectionInfo:
    """Sent in the request header so the worker can call home."""

    address: str  # host:port of the caller's TcpStreamServer
    subject: str  # uuid identifying the pending stream

    def to_dict(self) -> dict:
        return {"address": self.address, "subject": self.subject}

    @classmethod
    def from_dict(cls, d: dict) -> "TcpConnectionInfo":
        return cls(address=d["address"], subject=d["subject"])


class PendingStream:
    """Caller-side handle: an async queue of response payloads plus an
    upstream control channel once the worker has connected."""

    def __init__(self, subject: str, server: "TcpStreamServer"):
        self.subject = subject
        self._server = server
        self.queue: asyncio.Queue = asyncio.Queue()
        self._writer: Optional[asyncio.StreamWriter] = None
        self._connected = asyncio.Event()
        self._wlock = asyncio.Lock()
        self._pending_ctrl: list = []

    def _attach(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer
        self._connected.set()
        for kind in self._pending_ctrl:
            spawn_tracked(self.send_ctrl(kind),
                          name=f"tcp-ctrl-flush-{kind}")
        self._pending_ctrl.clear()

    async def wait_connected(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._connected.wait(), timeout)

    async def send_ctrl(self, kind: str) -> None:
        """Send a control frame upstream (kind: 'stop' | 'kill'). Frames
        issued before the worker's call-home attaches are buffered and
        flushed on attach."""
        if self._writer is None:
            self._pending_ctrl.append(kind)
            return
        async with self._wlock:
            try:
                self._writer.write(encode(TwoPartMessage(wire.checked(
                    wire.TCP_CTRL, {"t": "ctrl", "kind": kind}))))
                # frame atomicity needs the lock across the (bounded) drain
                await asyncio.wait_for(  # dynalint: disable=lock-across-blocking
                    self._writer.drain(), _io_timeout())
            except (ConnectionError, RuntimeError, asyncio.TimeoutError):
                pass

    def close(self) -> None:
        self._server._pending.pop(self.subject, None)
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class TcpStreamServer:
    """Caller-side listener for call-home response streams.

    One per process (lazily created by the DistributedRuntime — reference
    distributed.rs:110-120); all in-flight requests multiplex onto it via
    per-request subjects.
    """

    def __init__(self) -> None:
        self._pending: Dict[str, PendingStream] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()
        self.host = ""
        self.port = 0

    @classmethod
    async def start(cls, host: str = "0.0.0.0",
                    advertise_host: Optional[str] = None) -> "TcpStreamServer":
        self = cls()
        self._server = await asyncio.start_server(self._on_conn, host, 0)
        sock = self._server.sockets[0]
        self.port = sock.getsockname()[1]
        self.host = advertise_host or _local_ip()
        log.debug("tcp stream server on %s:%d", self.host, self.port)
        return self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        for w in list(self._writers):  # unblock handlers so wait_closed returns
            try:
                w.close()
            except Exception:
                log.debug("writer close failed during stop", exc_info=True)
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                log.warning("tcp stream server wait_closed timed out")

    def register(self) -> PendingStream:
        subject = uuid.uuid4().hex
        ps = PendingStream(subject, self)
        self._pending[subject] = ps
        return ps

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        ps: Optional[PendingStream] = None
        self._writers.add(writer)
        try:
            hello = await asyncio.wait_for(decode(reader), _io_timeout())
            hh = wire.decoded(wire.TCP_HELLO, hello.header)
            if hh.get("t") != "hello":
                raise ValueError(f"bad handshake: {hh}")
            subject = hh.get("subject")
            ps = self._pending.get(subject)
            if ps is None:
                writer.write(encode(TwoPartMessage(wire.checked(
                    wire.TCP_ERR,
                    {"t": "err", "message": f"unknown stream {subject}"}))))
                await asyncio.wait_for(writer.drain(), _io_timeout())
                return
            ps._attach(writer)
            while True:
                # idle server read: a response stream legitimately waits
                # as long as the worker generates; the REQUEST's deadline
                # bounds the consumer side (AsyncResponseStream)
                msg = await decode(reader)  # dynalint: disable=unbounded-await
                mh = wire.decoded(
                    (wire.TCP_DATA, wire.TCP_COMPLETE, wire.TCP_ERR),
                    msg.header)
                t = mh.get("t")
                if t == "data":
                    ps.queue.put_nowait(msg.body)
                elif t == "complete":
                    ps.queue.put_nowait(STREAM_COMPLETE)
                    break
                elif t == "err":
                    ps.queue.put_nowait(StreamError(mh.get("message", ""),
                                                    mh.get("kind", "")))
                    break
                else:
                    raise ValueError(f"unexpected frame type {t}")
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.TimeoutError):
            if ps is not None:
                ps.queue.put_nowait(StreamError("response stream disconnected"))
        except Exception as e:  # noqa: BLE001
            log.exception("response stream error")
            if ps is not None:
                ps.queue.put_nowait(StreamError(repr(e)))
        finally:
            self._writers.discard(writer)
            if ps is not None:
                self._pending.pop(ps.subject, None)
            try:
                writer.close()
            except Exception:
                pass


class TcpCallHome:
    """Worker-side: connect back to the caller and stream responses.

    Reads ``ctrl`` frames concurrently and invokes ``on_ctrl(kind)``
    (reference ingress/push_handler.rs: response publisher + context control).
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 on_ctrl=None):
        self._reader = reader
        self._writer = writer
        self._on_ctrl = on_ctrl
        self._wlock = asyncio.Lock()
        self._ctrl_task = spawn_tracked(self._ctrl_loop(),
                                        name="tcp-callhome-ctrl")

    @classmethod
    async def connect(cls, info: TcpConnectionInfo, on_ctrl=None,
                      timeout: Optional[float] = None) -> "TcpCallHome":
        await guard.chaos_point("tcp.connect")
        host, _, port = info.address.rpartition(":")
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)),
            timeout if timeout is not None else _io_timeout())
        self = cls(reader, writer, on_ctrl)
        await self._send(TwoPartMessage(wire.checked(
            wire.TCP_HELLO, {"t": "hello", "subject": info.subject})))
        return self

    async def _ctrl_loop(self) -> None:
        try:
            while True:
                # ctrl frames arrive whenever the caller chooses; this
                # read lives exactly as long as the connection
                msg = await decode(self._reader)  # dynalint: disable=unbounded-await
                ch = wire.decoded(wire.TCP_CTRL, msg.header)
                if ch.get("t") == "ctrl" and self._on_ctrl is not None:
                    self._on_ctrl(ch.get("kind"))
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            # peer hung up: treat as kill (caller went away)
            if self._on_ctrl is not None:
                self._on_ctrl("disconnect")

    async def _send(self, msg: TwoPartMessage) -> None:
        await guard.chaos_point("tcp.send", self._writer)
        async with self._wlock:
            self._writer.write(encode(msg))
            # frame atomicity needs the lock across the (bounded) drain
            await asyncio.wait_for(  # dynalint: disable=lock-across-blocking
                self._writer.drain(), _io_timeout())

    async def send_data(self, body: bytes) -> None:
        await self._send(TwoPartMessage(
            wire.checked(wire.TCP_DATA, {"t": "data"}), body))

    async def complete(self) -> None:
        await self._send(TwoPartMessage(
            wire.checked(wire.TCP_COMPLETE, {"t": "complete"})))

    async def error(self, message: str, kind: str = "") -> None:
        await self._send(TwoPartMessage(wire.checked(wire.TCP_ERR, {
            "t": "err", "message": message, "kind": kind})))

    async def close(self) -> None:
        await cancel_join(self._ctrl_task)
        try:
            self._writer.close()
            await asyncio.wait_for(self._writer.wait_closed(), _io_timeout())
        except Exception:
            pass


def _local_ip() -> str:
    """Best-effort routable local address (falls back to loopback)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"
