"""Declared wire-schema registry: every on-the-wire frame, in one place.

The Rust reference gets cross-component wire safety from serde-typed
structs — adding a field to a frame is a type change both the encoder and
every decoder must compile against. This port's frames are msgpack/JSON
dicts whose keys used to be edited independently on the encode and decode
sides (PRs 2 and 4 each grew the KV-transfer and DCP envelopes by hand).
This module is the serde replacement: each frame is declared ONCE with
field name, type, required/optional and since-version, and both the
static analyzer (dynaflow rules DL009/DL010 in ``tools/dynalint``) and an
optional runtime debug mode check real traffic against the same table.

Declarations are **pure literals** on purpose: ``tools/dynalint`` parses
this file with ``ast.literal_eval`` (no import of the runtime package) to
drive the static conformance pass, while the serving processes import it
normally. Keep every ``register_frame(...)`` argument a literal.

Usage at encode sites::

    header = wire.checked(wire.KV_TRANSFER_CHUNK, {"kind": "chunk", ...})

and at decode sites::

    h = wire.decoded((wire.KV_TRANSFER_BULK, wire.KV_TRANSFER_CHUNK), h)

Both are identity functions unless ``DYN_WIRE_VALIDATE`` is set (default
off — zero hot-path cost in production), but they are the *anchors* the
static pass keys on: a literal key written or read through an anchor that
is absent from the frame's schema is a tier-1 lint failure
(``wire-field-drift``), as is a ``codec.encode``/``encode_parts`` call
site whose header matches no registered frame (``undeclared-wire-frame``).

Compatibility policy (the version/compat contract):

- **Adding a field** is backward compatible: declare it ``optional`` with
  ``since`` = the new frame version and bump the frame ``version``.
  Receivers treat an absent field as legacy (``decoded`` never requires).
- **Requiring a new field / changing a type** is a breaking change: bump
  the frame ``version``; senders stamp ``v`` and receivers reject frames
  with ``v`` above what they support with a typed error (see
  ``KvTransferServer``) instead of a KeyError deep in a handler.
- **Removing a field** first demotes it to optional for one release so
  in-flight peers drain, then deletes the row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Sequence, Tuple, Union

from .config import env_bool


class WireError(RuntimeError):
    """Base class for wire-schema violations."""


class WireValidationError(WireError):
    """A frame's content contradicts its declared schema."""


class UnknownWireFrame(WireError):
    """A frame (or header) matches no registered schema."""


class WireVersionMismatch(WireError):
    """Peer sent a frame stamped with a schema version newer than ours."""


# type name (as written in declarations) -> accepted Python types.
# ``None`` values always pass (an explicit-null field is treated as absent).
_TYPES: Dict[str, tuple] = {
    "str": (str,),
    "int": (int,),
    "float": (int, float),
    "bool": (bool,),
    "bytes": (bytes, bytearray, memoryview),
    "list": (list, tuple),
    "dict": (dict,),
    "any": (object,),
}


@dataclass(frozen=True)
class WireField:
    name: str
    type: str          # key into _TYPES
    required: bool
    since: int         # frame version that introduced the field
    doc: str


@dataclass(frozen=True)
class WireFrame:
    name: str
    version: int
    doc: str
    # discriminator hints for frame inference: key -> expected value, or
    # key -> None meaning "key must be present" (any value)
    when: Dict[str, object]
    fields: Tuple[WireField, ...]

    @property
    def field_names(self) -> frozenset:
        return frozenset(f.name for f in self.fields)

    @property
    def required_names(self) -> frozenset:
        return frozenset(f.name for f in self.fields if f.required)

    def field(self, name: str) -> Optional[WireField]:
        for f in self.fields:
            if f.name == name:
                return f
        return None

    def matches(self, header: dict) -> bool:
        """Discriminator + shape test used by frame inference."""
        for key, want in self.when.items():
            if key not in header:
                return False
            if want is not None and header.get(key) != want:
                return False
        keys = set(header)
        return self.required_names <= keys and keys <= self.field_names


FRAMES: Dict[str, WireFrame] = {}


def register_frame(name: str, *, version: int = 1, doc: str = "",
                   when: Optional[dict] = None,
                   fields: Sequence[tuple] = ()) -> str:
    """Declare one wire frame; returns ``name`` so module constants double
    as registry keys. ``fields`` rows are literal tuples
    ``(name, type, "required"|"optional", since_version, doc)`` — keep all
    arguments literals (tools/dynalint parses them without importing)."""
    fs = tuple(WireField(n, t, mode == "required", since, fdoc)
               for n, t, mode, since, fdoc in fields)
    FRAMES[name] = WireFrame(name=name, version=version, doc=doc,
                             when=dict(when or {}), fields=fs)
    return name


def frame_version(name: str) -> int:
    return FRAMES[name].version


def validation_enabled() -> bool:
    """Debug validation knob (DYN_WIRE_VALIDATE; default off)."""
    return env_bool("DYN_WIRE_VALIDATE")


def _check_types(frame: WireFrame, header: dict) -> None:
    for f in frame.fields:
        val = header.get(f.name)
        if val is None:
            continue
        if not isinstance(val, _TYPES[f.type]):
            raise WireValidationError(
                f"frame {frame.name!r} field {f.name!r} expects {f.type}, "
                f"got {type(val).__name__}")


def _validate_encode(frame: WireFrame, header: dict) -> None:
    unknown = set(header) - frame.field_names
    if unknown:
        raise WireValidationError(
            f"frame {frame.name!r} encoded with undeclared field(s) "
            f"{sorted(unknown)}; declare them in runtime/wire.py")
    missing = frame.required_names - set(header)
    if missing:
        raise WireValidationError(
            f"frame {frame.name!r} encoded without required field(s) "
            f"{sorted(missing)}")
    for key, want in frame.when.items():
        if want is not None and header.get(key) != want:
            raise WireValidationError(
                f"frame {frame.name!r} expects {key}={want!r}, "
                f"got {header.get(key)!r}")
    _check_types(frame, header)


def _validate_decode(frames: Iterable[WireFrame], header: dict) -> None:
    """Receiver-side check: unknown keys and wrong types fail; *absent*
    fields never do (absent-field = legacy peer, accepted by policy)."""
    frames = list(frames)
    known = frozenset().union(*(f.field_names for f in frames))
    unknown = set(header) - known
    if unknown:
        names = "/".join(f.name for f in frames)
        raise WireValidationError(
            f"frame {names} decoded with undeclared field(s) "
            f"{sorted(unknown)}; declare them in runtime/wire.py")
    # type-check each present field against the first frame declaring it
    for key in header:
        for f in frames:
            fld = f.field(key)
            if fld is not None:
                _check_types(f, {key: header[key]})
                break


def checked(frame: str, header: dict) -> dict:
    """Encode-site anchor: validates ``header`` against the registered
    frame when ``DYN_WIRE_VALIDATE`` is on; identity otherwise. The static
    pass (DL009) checks literal keys flowing through this call either way.
    """
    if validation_enabled():
        _validate_encode(FRAMES[frame], header)
    return header


def decoded(frame: Union[str, Tuple[str, ...]], header: dict) -> dict:
    """Decode-site anchor (see :func:`checked`); ``frame`` may be a tuple
    when one receive path handles several frame shapes."""
    if validation_enabled():
        names = (frame,) if isinstance(frame, str) else frame
        _validate_decode([FRAMES[n] for n in names], header)
    return header


def infer_frame(header: dict) -> WireFrame:
    """Match a raw header to exactly one registered frame (the runtime
    twin of lint rule DL010 — used by the codec's debug hook)."""
    candidates = [f for f in FRAMES.values() if f.matches(header)]
    if len(candidates) > 1:
        # prefer frames with an explicit discriminator over shape-only hits
        strong = [f for f in candidates if f.when]
        if len(strong) == 1:
            candidates = strong
    if not candidates:
        raise UnknownWireFrame(
            f"header with keys {sorted(header)} matches no registered wire "
            f"frame; declare it in runtime/wire.py")
    if len(candidates) > 1:
        raise UnknownWireFrame(
            f"header with keys {sorted(header)} is ambiguous between "
            f"frames {sorted(f.name for f in candidates)}")
    return candidates[0]


def validate_outgoing(header: dict) -> None:
    """codec.encode/encode_parts debug hook: every frame leaving through
    the two-part codec must match a registered schema."""
    _validate_encode(infer_frame(header), header)


# ------------------------------------------------------------- the registry
#
# Grouped by plane. Field rows: (name, type, required?, since, doc).
# KEEP EVERY ARGUMENT A LITERAL — tools/dynalint parses this file with
# ast.literal_eval; computed values would silently drop the frame from the
# static conformance pass (and are rejected by its loader).

# --- DCP request plane (runtime/component.py) ------------------------------

DCP_REQUEST_ENVELOPE = register_frame(
    "dcp.request_envelope", version=3,
    doc="Request-plane envelope a Client sends to a served endpoint; the "
        "response streams back over the TCP call-home connection named in "
        "`conn`.",
    fields=[
        ("req_id", "str", "required", 1, "request/context id (rid)"),
        ("conn", "dict", "required", 1,
         "TcpConnectionInfo {address, subject} for the call-home stream"),
        ("payload", "bytes", "required", 1, "msgpack-packed request body"),
        ("trace", "dict", "optional", 2,
         "dyntrace ctx {trace_id, span_id}; absent = not sampled"),
        ("deadline_ms", "int", "optional", 3,
         "remaining end-to-end budget in ms at send time (each hop "
         "re-stamps what is left); absent = no deadline"),
    ])

DCP_REQUEST_ACK = register_frame(
    "dcp.request_ack", version=1,
    doc="Worker's request-plane acceptance reply (responses themselves "
        "arrive over TCP).",
    fields=[
        ("accepted", "bool", "required", 1, "request admitted to a worker"),
        ("instance_id", "int", "optional", 1,
         "serving instance's lease id (diagnostic; not consumed)"),
    ])

DCP_STATS_REPLY = register_frame(
    "dcp.stats_reply", version=1,
    doc="Per-instance stats-plane scrape reply (metrics aggregator, KV "
        "router and planner all consume `data` as ForwardPassMetrics).",
    fields=[
        ("instance_id", "int", "optional", 1, "lease id (diagnostic)"),
        ("subject", "str", "optional", 1, "instance subject (diagnostic)"),
        ("inflight", "int", "optional", 1,
         "requests in flight on the instance (diagnostic)"),
        ("data", "dict", "required", 1,
         "stats_handler() payload (ForwardPassMetrics superset)"),
    ])

DCP_PUSH_WATCH = register_frame(
    "dcp.push_watch", version=1,
    doc="Server push: one KV prefix-watch event.",
    when={"push": "watch"},
    fields=[
        ("push", "str", "required", 1, "push discriminator: 'watch'"),
        ("watch_id", "int", "required", 1, "client-chosen watch id"),
        ("event", "str", "required", 1, "'put' | 'delete'"),
        ("key", "str", "required", 1, "KV key"),
        ("value", "bytes", "optional", 1, "new value; absent on delete"),
    ])

DCP_PUSH_MSG = register_frame(
    "dcp.push_msg", version=1,
    doc="Server push: one pub/sub delivery.",
    when={"push": "msg"},
    fields=[
        ("push", "str", "required", 1, "push discriminator: 'msg'"),
        ("sid", "int", "required", 1, "subscription id"),
        ("subject", "str", "required", 1, "published subject"),
        ("payload", "bytes", "required", 1, "published body"),
    ])

DCP_PUSH_REQ = register_frame(
    "dcp.push_req", version=1,
    doc="Server push: one request-plane delivery expecting a reply.",
    when={"push": "req"},
    fields=[
        ("push", "str", "required", 1, "push discriminator: 'req'"),
        ("sid", "int", "required", 1, "subscription id"),
        ("subject", "str", "required", 1, "request subject"),
        ("payload", "bytes", "required", 1, "request body"),
        ("reply", "int", "required", 1, "server-side reply-routing id"),
    ])

# --- disaggregated prefill queue (llm/disagg/protocols.py) -----------------

PREFILL_REMOTE_REQUEST = register_frame(
    "prefill.remote_request", version=3,
    doc="One queued remote-prefill job (decode worker -> prefill queue -> "
        "any prefill worker).",
    fields=[
        ("request_id", "str", "required", 1, "decode-side request id"),
        ("token_ids", "list", "required", 1, "full prompt token ids"),
        ("sampling", "dict", "required", 1, "SamplingOptions dict"),
        ("eos_token_ids", "list", "required", 1, "stop-token ids"),
        ("page_ids", "list", "required", 1,
         "DECODE-side pool pages reserved for the prompt KV"),
        ("skip_pages", "int", "required", 1,
         "leading pages already valid on the decode side (prefix hits)"),
        ("engine_id", "int", "required", 1,
         "decode engine instance id (transfer-endpoint lookup key)"),
        ("trace_ctx", "dict", "optional", 2,
         "dyntrace ctx of the decode-side request; absent = no parent"),
        ("deadline_ms", "int", "optional", 3,
         "remaining request budget in ms at enqueue time; the prefill "
         "worker drops jobs whose budget is spent and caps its ack "
         "waits by what remains. Absent = no deadline"),
    ])

# --- KV transfer plane (llm/disagg/transfer.py) ----------------------------

KV_TRANSFER_BULK = register_frame(
    "kv_transfer.bulk", version=2,
    doc="Legacy single-frame KV payload: all pages + the first sampled "
        "token in one two-part message (chunk_pages=0).",
    fields=[
        ("request_id", "str", "required", 1, "decode-side request id"),
        ("page_ids", "list", "required", 1, "destination pool pages"),
        ("shape", "list", "required", 1, "[L, n, KV, page_size, hd]"),
        ("dtype", "str", "required", 1,
         "ORIGINAL pool dtype to restore into (even when quantized)"),
        ("k_len", "int", "required", 1, "byte length of the K half"),
        ("first_token", "int", "required", 1, "remotely sampled first token"),
        ("quant", "str", "optional", 1, "'int8' when compressed"),
        ("trace", "dict", "optional", 2, "dyntrace ctx {trace_id, span_id}"),
        ("v", "int", "optional", 2, "frame schema version; absent = 1"),
    ])

KV_TRANSFER_CHUNK = register_frame(
    "kv_transfer.chunk", version=2,
    doc="One streamed KV chunk; the final chunk (chunk_idx == n_chunks-1) "
        "is the commit and carries the first token.",
    when={"kind": "chunk"},
    fields=[
        ("kind", "str", "required", 1, "frame discriminator: 'chunk'"),
        ("request_id", "str", "required", 1, "decode-side request id"),
        ("chunk_idx", "int", "required", 1, "0-based chunk index"),
        ("n_chunks", "int", "required", 1, "total chunks in the stream"),
        ("page_ids", "list", "required", 1, "destination pages this chunk"),
        ("shape", "list", "required", 1, "[L, n, KV, page_size, hd]"),
        ("dtype", "str", "required", 1, "ORIGINAL pool dtype"),
        ("k_len", "int", "required", 1, "byte length of the K half"),
        ("quant", "str", "optional", 1, "'int8' when compressed"),
        ("first_token", "int", "optional", 1, "commit chunk only"),
        ("trace", "dict", "optional", 2, "commit chunk only; dyntrace ctx"),
        ("v", "int", "optional", 2, "frame schema version; absent = 1"),
    ])

KV_TRANSFER_ABORT = register_frame(
    "kv_transfer.abort", version=2,
    doc="Sender-side teardown: drop the stream's partial state and fail "
        "the decode-side waiter now.",
    when={"kind": "abort"},
    fields=[
        ("kind", "str", "required", 1, "frame discriminator: 'abort'"),
        ("request_id", "str", "required", 1, "stream being aborted"),
        ("v", "int", "optional", 2, "frame schema version; absent = 1"),
    ])

KV_TRANSFER_ACK = register_frame(
    "kv_transfer.ack", version=2,
    doc="Receiver's per-frame acknowledgement, demultiplexed by "
        "request_id on the sender.",
    when={"ok": None},
    fields=[
        ("ok", "bool", "required", 1, "frame ingested successfully"),
        ("request_id", "str", "required", 1, "ack demux key"),
        ("chunk_idx", "int", "optional", 1,
         "echo of the acked chunk (diagnostic)"),
        ("committed", "bool", "optional", 1,
         "set on the ack of a committed final chunk"),
        ("error", "str", "optional", 1, "failure detail when ok=false"),
        ("conn_lost", "bool", "optional", 1,
         "client-synthesized on connection loss (never on the wire)"),
        ("v", "int", "optional", 2, "frame schema version; absent = 1"),
    ])

# --- TCP call-home response plane (runtime/tcp.py) -------------------------

TCP_HELLO = register_frame(
    "tcp.hello", version=1,
    doc="Worker->caller handshake naming the pending stream.",
    when={"t": "hello"},
    fields=[
        ("t", "str", "required", 1, "frame discriminator: 'hello'"),
        ("subject", "str", "required", 1, "pending-stream uuid"),
    ])

TCP_DATA = register_frame(
    "tcp.data", version=1,
    doc="One streamed response item (body = packed Annotated envelope).",
    when={"t": "data"},
    fields=[("t", "str", "required", 1, "frame discriminator: 'data'")])

TCP_COMPLETE = register_frame(
    "tcp.complete", version=1,
    doc="End-of-stream sentinel.",
    when={"t": "complete"},
    fields=[("t", "str", "required", 1, "frame discriminator: 'complete'")])

TCP_ERR = register_frame(
    "tcp.err", version=1,
    doc="Stream-fatal error sentinel.",
    when={"t": "err"},
    fields=[
        ("t", "str", "required", 1, "frame discriminator: 'err'"),
        ("message", "str", "required", 1, "error detail"),
        ("kind", "str", "optional", 1,
         "worker-side exception class name (maps client errors to 4xx)"),
    ])

TCP_CTRL = register_frame(
    "tcp.ctrl", version=1,
    doc="Caller->worker control frame on the full-duplex stream.",
    when={"t": "ctrl"},
    fields=[
        ("t", "str", "required", 1, "frame discriminator: 'ctrl'"),
        ("kind", "str", "required", 1, "'stop' | 'kill'"),
    ])

BLACKBOX_CAPTURE = register_frame(
    "blackbox.capture", version=1,
    doc="dynablack incident fan-out on the `<namespace>.blackbox.capture` "
        "pub/sub subject. The tripping worker broadcasts an origin "
        "announcement (no `rings`); each sibling replies on the same "
        "subject with its shadow rings attached so all rings merge under "
        "one incident id. Optional plane: peers that never subscribe "
        "simply don't contribute (dynaflow compat policy).",
    when={"event": "blackbox.capture"},
    fields=[
        ("event", "str", "required", 1,
         "frame discriminator: 'blackbox.capture'"),
        ("incident_id", "str", "required", 1,
         "incident id all contributions merge under"),
        ("trigger", "str", "required", 1,
         "tripping trigger name (slo_burn_rate, breaker_open, ...)"),
        ("worker_label", "str", "required", 1,
         "sender's worker label (echo suppression + contribution origin)"),
        ("at_ms", "float", "optional", 1,
         "originator's capture wall time (epoch ms; diagnostic)"),
        ("rings", "dict", "optional", 1,
         "sender's shadow rings {label: {anchors, events}}; absent on "
         "the originating broadcast, present on contributions"),
    ])


# ------------------------------------------------------------ doc rendering

def _frame_markdown(f: WireFrame) -> list:
    lines = [f"### `{f.name}` (v{f.version})", ""]
    if f.doc:
        lines += [f.doc, ""]
    if f.when:
        hints = ", ".join(f"`{k}` present" if v is None else f"`{k} == {v!r}`"
                          for k, v in sorted(f.when.items()))
        lines += [f"Match: {hints}", ""]
    lines += ["| Field | Type | Required | Since | Description |",
              "|---|---|---|---|---|"]
    for fld in f.fields:
        req = "yes" if fld.required else "no"
        lines.append(f"| `{fld.name}` | {fld.type} | {req} | v{fld.since} "
                     f"| {fld.doc} |")
    lines.append("")
    return lines


def render_frame_tables(prefixes: Sequence[str]) -> str:
    """Markdown tables for frames whose names start with any prefix —
    embedded (sync-gated) into docs/disagg_serving.md."""
    lines: list = []
    for name in sorted(FRAMES):
        if any(name.startswith(p) for p in prefixes):
            lines += _frame_markdown(FRAMES[name])
    return "\n".join(lines).rstrip() + "\n"


def render_wire_docs() -> str:
    """docs/wire_schemas.md content, generated from the registry."""
    lines = [
        "# Wire frame schemas",
        "",
        "Generated from `dynamo_tpu/runtime/wire.py` — do not edit by "
        "hand. Regenerate with:",
        "",
        "```",
        "python -m tools.dynalint --wire-schemas docs/wire_schemas.md",
        "```",
        "",
        "Every frame this system puts on a wire — DCP request/response "
        "envelopes and pushes, the disaggregated prefill queue, the KV "
        "transfer plane, the TCP call-home response plane — is declared "
        "once in the registry. Static conformance is enforced in tier-1 "
        "by dynalint rules DL009 (`wire-field-drift`) and DL010 "
        "(`undeclared-wire-frame`); set `DYN_WIRE_VALIDATE=1` to also "
        "check real frames against these tables at encode/decode time "
        "(debug mode, default off). See `docs/static_analysis.md` for "
        "the compat policy and how to add a field.",
        "",
    ]
    for name in sorted(FRAMES):
        lines += _frame_markdown(FRAMES[name])
    return "\n".join(lines).rstrip() + "\n"
