"""Durability for the control-plane service: snapshot + append-only journal.

The reference delegates durability to its infra services — etcd is
raft-replicated and NATS JetStream persists the prefill work queue
(reference deploy/docker-compose.yml:16-31, examples/llm/utils/
nats_queue.py). Our single-process DCP server needs its own story:
this module gives it a write-ahead journal with periodic snapshot
compaction, so a restart replays to the exact pre-crash KV + queue
state.

What is durable and what is deliberately NOT:

- **Unleased KV** (model registry, deployment specs, planner advisories,
  router config): durable.
- **Work queues** (disagg prefill queue): durable at-rest, at-most-once
  across a crash. Queued items survive restarts (appends and pops are
  journaled), and nothing is ever double-delivered — but an item IN
  FLIGHT at the crash can be lost: the pop is journaled before the
  reply frame flushes, and a put handed directly to a blocked puller
  never enters the journal at all. The reference's NATS JetStream queue
  is at-least-once via consumer acks; our single consumer (the prefill
  worker pool) already treats a lost remote prefill as a local-prefill
  fallback (llm/disagg/decode.py remote_fallbacks), so redelivery
  machinery would buy nothing the fallback doesn't.
- **Leases + lease-attached keys** (endpoint instances, service records):
  ephemeral BY DESIGN. A lease exists to say "this worker is alive right
  now"; the restarted server has no live keep-alive sessions, so
  restoring leased keys would resurrect dead instances and the discovery
  plane would route to ghosts. Workers re-register on reconnect — the
  same behavior etcd gives the reference when a lease outlives nobody.
- **Watches / subscriptions / in-flight requests**: connection state,
  gone with the connections; clients re-establish.

File layout: ``<path>.snap`` (one msgpack map: rev + kv + queues) and
``<path>.log`` (length-prefixed msgpack frames, one per mutation).
Recovery = load snapshot, replay log. Compaction = write new snapshot,
truncate log; triggered when the log exceeds ``max_log_bytes``.

Writes are flushed to the OS on every record (survives process death,
e.g. SIGKILL); ``fsync=True`` additionally fsyncs (survives machine
crash) at a heavy per-op cost — the docker-compose single-node etcd the
reference ships makes the same flush-vs-fsync tradeoff by default.
"""

from __future__ import annotations

import logging
import os
from collections import deque
from typing import Dict, Optional, Tuple

import msgpack

log = logging.getLogger("dynamo_tpu.dcp.journal")


class Journal:
    """Append-only mutation log + snapshot for DcpServer state."""

    def __init__(self, path: str, *, max_log_bytes: int = 4 * 1024 * 1024,
                 fsync: bool = False):
        self.snap_path = path + ".snap"
        self.log_path = path + ".log"
        self.max_log_bytes = max_log_bytes
        self.fsync = fsync
        self._f = None  # open log file handle (append mode)
        self._bytes = 0
        # monotone record sequence: every appended record carries one and
        # the snapshot stamps the last it covers, so replay after a crash
        # BETWEEN snapshot-rename and log-truncate skips the already-
        # snapshotted prefix instead of double-applying it
        self._seq = 0
        # set by recover(): log offset before any torn tail. None until
        # recover() runs — open() must not truncate a log it hasn't parsed
        self._valid_log_bytes: Optional[int] = None

    # ------------------------------------------------------------- recovery

    def recover(self) -> Tuple[int, Dict[str, Tuple[bytes, int, int]],
                               Dict[str, deque]]:
        """Load snapshot + replay log.

        Returns ``(rev, kv, queues)`` where ``kv`` maps key ->
        (value, create_rev, mod_rev) for unleased entries and ``queues``
        maps name -> deque of payloads.
        """
        rev = 0
        snap_seq = 0
        kv: Dict[str, Tuple[bytes, int, int]] = {}
        queues: Dict[str, deque] = {}

        if os.path.exists(self.snap_path):
            with open(self.snap_path, "rb") as f:
                snap = msgpack.unpackb(f.read(), raw=False)
            rev = snap["rev"]
            snap_seq = snap.get("seq", 0)
            for k, v, cr, mr in snap["kv"]:
                kv[k] = (v, cr, mr)
            for name, items in snap["queues"].items():
                queues[name] = deque(items)
        self._seq = snap_seq

        if os.path.exists(self.log_path):
            replayed = skipped = truncated = 0
            with open(self.log_path, "rb") as f:
                buf = f.read()
            off = 0
            while off + 4 <= len(buf):
                n = int.from_bytes(buf[off:off + 4], "big")
                if off + 4 + n > len(buf):
                    truncated = len(buf) - off  # torn tail write: drop it
                    break
                rec = msgpack.unpackb(buf[off + 4:off + 4 + n], raw=False)
                off += 4 + n
                seq = rec.get("s", 0)
                self._seq = max(self._seq, seq)
                if seq <= snap_seq and snap_seq > 0:
                    # already folded into the snapshot: a crash between
                    # snapshot-rename and log-truncate must not re-apply
                    # (a replayed qput would double-deliver its item).
                    # Records WITHOUT a seq (seq=0) necessarily predate
                    # any seq-stamped snapshot, so they are covered too.
                    skipped += 1
                    continue
                replayed += 1
                t = rec["t"]
                if t == "put":
                    kv[rec["k"]] = (rec["v"], rec["cr"], rec["mr"])
                    rev = max(rev, rec["mr"])
                elif t == "del":
                    kv.pop(rec["k"], None)
                elif t == "qput":
                    queues.setdefault(rec["q"], deque()).append(rec["p"])
                elif t == "qpop":
                    q = queues.get(rec["q"])
                    if q:
                        q.popleft()
                elif t == "rev":
                    rev = max(rev, rec["r"])
            self._valid_log_bytes = off
            if truncated:
                log.warning("journal: dropped %d-byte torn tail", truncated)
            log.info("journal: recovered rev=%d kv=%d queues=%d "
                     "(replayed %d records, %d pre-snapshot skipped)",
                     rev, len(kv), sum(map(len, queues.values())),
                     replayed, skipped)
        return rev, kv, queues

    # -------------------------------------------------------------- writing

    def open(self) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.log_path)),
                    exist_ok=True)
        self._f = open(self.log_path, "ab")
        if (self._valid_log_bytes is not None
                and self._f.tell() > self._valid_log_bytes):
            # cut the torn tail recover() dropped in memory — appending
            # after garbage bytes would corrupt the NEXT recovery
            self._f.truncate(self._valid_log_bytes)
        self._bytes = (self._valid_log_bytes
                       if self._valid_log_bytes is not None
                       else self._f.tell())

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    def _append(self, rec: dict) -> None:
        self._seq += 1
        rec["s"] = self._seq
        body = msgpack.packb(rec, use_bin_type=True)
        self._f.write(len(body).to_bytes(4, "big") + body)
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._bytes += 4 + len(body)

    def record_put(self, key: str, value: bytes, create_rev: int,
                   mod_rev: int) -> None:
        self._append({"t": "put", "k": key, "v": value,
                      "cr": create_rev, "mr": mod_rev})

    def record_delete(self, key: str) -> None:
        self._append({"t": "del", "k": key})

    def record_qput(self, queue: str, payload: bytes) -> None:
        self._append({"t": "qput", "q": queue, "p": payload})

    def record_qpop(self, queue: str) -> None:
        self._append({"t": "qpop", "q": queue})

    def record_rev(self, rev: int) -> None:
        """Persist a revision bump that has no durable payload (leased
        puts): recovery must never re-issue a pre-crash mod_rev, or stale
        CAS tokens captured before the crash could alias new writes."""
        self._append({"t": "rev", "r": rev})

    @property
    def log_size(self) -> int:
        return getattr(self, "_bytes", 0)

    # ----------------------------------------------------------- compaction

    def snapshot(self, rev: int, kv: Dict[str, Tuple[bytes, int, int]],
                 queues: Dict[str, deque]) -> None:
        """Write current state to ``.snap`` (temp file + atomic rename,
        fsynced) and truncate the log. Crash-safe: the snapshot stamps
        the last record sequence it covers, so a crash BETWEEN rename
        and truncate recovers as (new snap + log whose records are all
        seq-skipped) — nothing double-applies."""
        snap = {
            "rev": rev,
            "seq": self._seq,
            "kv": [[k, v, cr, mr] for k, (v, cr, mr) in kv.items()],
            "queues": {name: list(items) for name, items in queues.items()
                       if items},
        }
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(snap, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        # now the log's contents are all reflected in the snapshot
        if self._f:
            self._f.truncate(0)
            self._bytes = 0
        log.info("journal: compacted (snapshot rev=%d kv=%d)", rev, len(kv))
