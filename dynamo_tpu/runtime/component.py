"""Component model: Namespace → Component → Endpoint addressing + serving.

Reference lib/runtime/src/component.rs: discovery path
``<ns>/components/<comp>/<ep>:<lease_hex>`` in the KV store (under the
worker's primary lease) and request-plane subject
``<ns>.<comp>.<ep>-<lease_hex>``; serving an endpoint (reference
component/endpoint.rs:55-142) registers the subject consumer and writes the
discoverable instance record; a Client (reference component/client.rs)
watches the prefix and routes round_robin / random / direct.
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass
from typing import (Any, AsyncIterator, Awaitable, Callable, Dict, List,
                    Optional, Tuple)

from . import guard, proto, tracing, wire
from .codec import TwoPartMessage
from .config import env_float, env_int
from .dcp_client import DcpClient, Message, NoRespondersError, pack, unpack
from .engine import Annotated, Context
from .tasks import cancel_join, spawn_tracked
from .tcp import (STREAM_COMPLETE, StreamError, TcpCallHome, TcpConnectionInfo,
                  TcpStreamServer)

log = logging.getLogger("dynamo_tpu.component")

INSTANCE_ROOT = "instances/"  # KV prefix for endpoint instance records


def instance_key(namespace: str, component: str, endpoint: str, lease: int) -> str:
    return f"{INSTANCE_ROOT}{namespace}/components/{component}/{endpoint}:{lease:x}"


def instance_prefix(namespace: str, component: str, endpoint: str) -> str:
    return f"{INSTANCE_ROOT}{namespace}/components/{component}/{endpoint}:"


def instance_subject(namespace: str, component: str, endpoint: str,
                     lease: int) -> str:
    return f"{namespace}.{component}.{endpoint}-{lease:x}"


def shared_subject(namespace: str, component: str, endpoint: str) -> str:
    return f"{namespace}.{component}.{endpoint}"


@dataclass(frozen=True)
class EndpointAddress:
    """Parsed ``dyn://namespace.component.endpoint`` address (reference
    lib/runtime/src/protocols.rs Endpoint path parsing)."""

    namespace: str
    component: str
    endpoint: str

    @classmethod
    def parse(cls, path: str) -> "EndpointAddress":
        p = path[len("dyn://"):] if path.startswith("dyn://") else path
        parts = p.split(".")
        if len(parts) == 2:
            parts = [parts[0], parts[1], "generate"]
        if len(parts) != 3:
            raise ValueError(
                f"endpoint path must be namespace.component[.endpoint]: {path!r}")
        return cls(*parts)

    def __str__(self) -> str:
        return f"dyn://{self.namespace}.{self.component}.{self.endpoint}"


@dataclass
class EndpointInstance:
    """A live, discoverable endpoint instance."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int  # == serving worker's lease id
    subject: str
    transport: str = "dcp+tcp"

    def to_dict(self) -> dict:
        return {
            "namespace": self.namespace, "component": self.component,
            "endpoint": self.endpoint, "instance_id": self.instance_id,
            "subject": self.subject, "transport": self.transport,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "EndpointInstance":
        return cls(
            namespace=d["namespace"], component=d["component"],
            endpoint=d["endpoint"], instance_id=d["instance_id"],
            subject=d["subject"], transport=d.get("transport", "dcp+tcp"))


class Namespace:
    def __init__(self, drt: "DistributedRuntime", name: str):  # noqa: F821
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self.drt, self.name, name)


class Component:
    def __init__(self, drt, namespace: str, name: str):
        self.drt = drt
        self.namespace = namespace
        self.name = name
        self._service_created = False

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.drt, self.namespace, self.name, name)

    async def create_service(self) -> None:
        """Registers the component's service record (stats root)."""
        self._service_created = True
        await self.drt.dcp.kv_create(
            f"services/{self.namespace}/{self.name}",
            pack({"namespace": self.namespace, "component": self.name}),
            lease=self.drt.primary_lease,
        )

    @property
    def service_subject(self) -> str:
        return f"{self.namespace}.{self.name}"


Handler = Callable[[Any, Context], AsyncIterator[Any]]


class Endpoint:
    def __init__(self, drt, namespace: str, component: str, name: str):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def address(self) -> EndpointAddress:
        return EndpointAddress(self.namespace, self.component, self.name)

    @property
    def path(self) -> str:
        return str(self.address)

    def subject_for(self, lease: int) -> str:
        return instance_subject(self.namespace, self.component, self.name, lease)

    async def serve(
        self,
        handler: Handler,
        *,
        stats_handler: Optional[Callable[[], dict]] = None,
        metrics_labels: Optional[dict] = None,
    ) -> "ServeHandle":
        """Serve this endpoint with ``handler(request, context) -> aiter``.

        Registers the request-plane consumer (both the per-instance subject
        and the shared queue-group subject), publishes the discoverable
        instance record under the worker's primary lease, and answers stats
        queries (reference component/endpoint.rs:55-142 + service stats).
        """
        drt = self.drt
        lease = drt.primary_lease
        inst = EndpointInstance(
            namespace=self.namespace, component=self.component,
            endpoint=self.name, instance_id=lease,
            subject=self.subject_for(lease))
        serve_handle = ServeHandle(self, inst, handler, stats_handler)
        await serve_handle._start()
        return serve_handle

    async def client(self) -> "Client":
        c = Client(self.drt, self.address)
        await c._start()
        return c


class _WorkerKilled(Exception):
    """Internal: a ``worker.kill`` chaos rule fired — this handle must
    die like a crashed process (conn drops, no error frames, lease and
    discovery record left behind)."""


class ServeHandle:
    """A served endpoint instance; ``stop()`` to withdraw from discovery,
    ``begin_drain()``/``drain()`` for the graceful path (dynarevive)."""

    def __init__(self, endpoint: Endpoint, instance: EndpointInstance,
                 handler: Handler, stats_handler):
        self.endpoint = endpoint
        self.instance = instance
        self.handler = handler
        self.stats_handler = stats_handler
        self._sids: List[int] = []
        self._inflight: Dict[str, Context] = {}
        self._stopped = asyncio.Event()
        # dynarevive lifecycle (declared as `serve_handle.drain` in
        # runtime/proto.py): draining = discovery record withdrawn, new
        # requests nacked, in-flight streams finishing, stats plane
        # still answering (draining ≠ dead). dead = a worker.kill chaos
        # rule fired — the wedged-process shape (lease + discovery record
        # stay, nothing answers). _drain_started makes begin_drain
        # idempotent while keeping the nack flag OFF until the discovery
        # delete has completed (delete-before-nack ordering).
        self.draining = False
        self._drain_started = False
        self._dead = False

    async def _start(self) -> None:
        drt = self.endpoint.drt
        on_req = self._on_request
        # per-instance subject (direct routing)
        self._sids.append(await drt.dcp.subscribe(
            self.instance.subject, on_req, group="workers"))
        # shared subject (server-side balanced routing)
        self._sids.append(await drt.dcp.subscribe(
            shared_subject(self.instance.namespace, self.instance.component,
                           self.instance.endpoint),
            on_req, group="workers"))
        # stats subject
        self._sids.append(await drt.dcp.subscribe(
            f"stats.{self.instance.subject}", self._on_stats, group="stats"))
        # discoverable instance record, attached to our lease
        key = instance_key(self.instance.namespace, self.instance.component,
                           self.instance.endpoint, self.instance.instance_id)
        await drt.dcp.kv_put(key, pack(self.instance.to_dict()),
                             lease=self.instance.instance_id)
        log.info("serving %s as instance %x",
                 self.endpoint.path, self.instance.instance_id)

    async def stop(self) -> None:
        drt = self.endpoint.drt
        self._stopped.set()  # proto: serve_handle.drain live|draining->stopped
        # claim the subscriptions before the awaits: a concurrent
        # stop()/drain() interleaving must not double-unsubscribe
        sids, self._sids = self._sids, []
        for sid in sids:
            try:
                await drt.dcp.unsubscribe(sid)
            # teardown sweep: every subscription must be attempted even
            # when one fails; no request path runs through here
            except Exception:  # dynalint: disable=typed-error-swallow
                log.debug("unsubscribe %d failed during stop", sid,
                          exc_info=True)
        await self._withdraw_discovery()
        for ctx in self._inflight.values():
            ctx.kill()

    async def _withdraw_discovery(self) -> None:
        key = instance_key(self.instance.namespace, self.instance.component,
                           self.instance.endpoint, self.instance.instance_id)
        try:
            await self.endpoint.drt.dcp.kv_delete(key)
        # best-effort withdraw on the way out: the lease expiry is the
        # backstop; no client response rides on this path
        except Exception:  # dynalint: disable=typed-error-swallow
            log.debug("discovery withdraw failed for %s",
                      self.instance.subject, exc_info=True)

    # ------------------------------------------------- dynarevive: drain

    async def begin_drain(self) -> None:
        """Enter the draining state: delete the discovery record FIRST
        (every watching client drops this instance; routers stop picking
        it), only then nack any request that still reaches the subjects,
        keep answering stats with ``draining=1``, and let in-flight
        streams finish. Draining ≠ dead: nothing errors, no breaker
        opens.

        Ordering is load-bearing (model-checked `delete-before-nack`
        invariant of the `serve_handle.drain` machine): flipping the
        nack flag before the delete lands would have clients re-picking
        this still-discoverable instance into repeated nacks until
        their retry budget dies."""
        if self._drain_started:  # claim-before-await: double begin_drain
            return               # must not double-withdraw (draining=True
        self._drain_started = True  # implies _drain_started)
        log.info("draining %s (instance %x, %d in flight)",
                 self.endpoint.path, self.instance.instance_id,
                 len(self._inflight))
        await self._withdraw_discovery()  # proto: serve_handle.drain live->live
        proto.step("serve_handle.drain", "live", "draining")
        self.draining = True

    async def wait_idle(self, timeout_s: float) -> bool:
        """Wall-bounded wait for the in-flight set to empty. Returns
        False when the timeout expired with work still in flight."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(timeout_s, 0.0)
        while self._inflight and loop.time() < deadline:
            await asyncio.sleep(0.02)
        return not self._inflight

    async def drain(self, timeout_s: float = 10.0) -> bool:
        """begin_drain + bounded in-flight wait + full stop. Returns True
        when everything finished inside the budget."""
        await self.begin_drain()
        drained = await self.wait_idle(timeout_s)
        await self.stop()
        return drained

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    async def die(self) -> None:
        """Bench/test hook: apply the ``worker.kill`` chaos shape on
        demand (wedged process: streams drop raw, planes go silent,
        lease + discovery record stay)."""
        await self._on_killed()

    async def _on_killed(self) -> None:
        """worker.kill chaos fired: become a wedged process. Request and
        stats planes go silent (subscriptions dropped, stats errors), the
        lease keepalive and discovery record stay — exactly the
        crashed-but-leased shape the breaker/eviction paths handle —
        and every in-flight context is killed so engine pages free."""
        if self._dead:
            return
        self._dead = True  # proto: serve_handle.drain live|draining->dead
        log.warning("chaos worker.kill: instance %x of %s is now dead "
                    "(lease and discovery record left behind)",
                    self.instance.instance_id, self.endpoint.path)
        sids, self._sids = self._sids, []
        for sid in sids:
            try:
                await self.endpoint.drt.dcp.unsubscribe(sid)
            # chaos-kill teardown: a wedged process answers nothing, so
            # nothing here can owe a typed error to a client
            except Exception:  # dynalint: disable=typed-error-swallow
                log.debug("unsubscribe during chaos kill failed",
                          exc_info=True)
        for ctx in self._inflight.values():
            ctx.kill()

    async def _on_stats(self, msg: Message) -> None:
        if self._dead:
            # a dead process answers nothing; erroring (vs timing out)
            # keeps the test/scrape planes fast while the breaker still
            # counts the failure
            await msg.respond_error("worker killed by chaos")
            return
        try:
            data = self.stats_handler() if self.stats_handler else {}
        except Exception as e:  # noqa: BLE001 — a crashing stats handler
            # must answer (error), not leave the scraper waiting out its
            # full request timeout every round
            log.debug("stats handler failed for %s", self.instance.subject,
                      exc_info=True)
            await msg.respond_error(f"stats handler failed: {e!r}")
            return
        if self.draining:
            # draining ≠ dead: the scrape plane keeps answering, flagged,
            # so the router/aggregator treat this instance as leaving —
            # not as a failure to break on
            data = dict(data, draining=1)
        await msg.respond(pack(wire.checked(wire.DCP_STATS_REPLY, {
            "instance_id": self.instance.instance_id,
            "subject": self.instance.subject,
            "inflight": len(self._inflight),
            "data": data,
        })))

    async def _on_request(self, msg: Message) -> None:
        """Request-plane delivery: ack over the request plane, then stream
        responses over the TCP call-home connection (reference
        ingress/push_handler.rs:20-113)."""
        try:
            envelope = wire.decoded(wire.DCP_REQUEST_ENVELOPE,
                                    unpack(msg.payload))
            req_id = envelope["req_id"]
            conn_info = TcpConnectionInfo.from_dict(envelope["conn"])
            request = unpack(envelope["payload"])
            # dyntrace wire propagation: absent field = no parent (old
            # peers interoperate unchanged)
            trace_ctx = envelope.get("trace")
            # deadline propagation: absent field = no deadline (legacy
            # peer); the value is the REMAINING budget at the sender's
            # send time, rebuilt against this host's clock
            deadline_ms = envelope.get("deadline_ms")
        except Exception as e:  # noqa: BLE001
            if msg.needs_reply:
                await msg.respond_error(f"bad request envelope: {e!r}")
            return
        if self._dead:
            return  # a dead process never acks: the caller's ack wait fails
        if self.draining:
            # drain admits nothing new: a typed nack the Client maps to
            # "request rejected" (retry lands on a live sibling)
            # proto: serve_handle.drain draining->draining
            if msg.needs_reply:
                await msg.respond(pack(wire.checked(wire.DCP_REQUEST_ACK, {
                    "accepted": False,
                    "instance_id": self.instance.instance_id})))
            return
        if msg.needs_reply:
            await msg.respond(pack(wire.checked(wire.DCP_REQUEST_ACK, {
                "accepted": True,
                "instance_id": self.instance.instance_id})))
        spawn_tracked(self._run_request(req_id, conn_info, request, trace_ctx,
                                        deadline_ms),
                      name=f"serve-{req_id}")

    async def _run_request(self, req_id: str, conn_info: TcpConnectionInfo,
                           request: Any,
                           trace_ctx: Optional[dict] = None,
                           deadline_ms: Optional[int] = None) -> None:
        ctx = Context(req_id,
                      deadline=guard.Deadline.from_wire_ms(deadline_ms))
        self._inflight[req_id] = ctx
        tracing.bind_request_id(req_id)
        tracer = tracing.get_tracer()
        span = tracer.start_span(
            f"serve.{self.instance.endpoint}",
            parent=trace_ctx,  # None → new (sampled) root for this worker
            attributes={"subject": self.instance.subject},
            request_id=req_id)

        def on_ctrl(kind: str) -> None:
            if kind == "stop":
                ctx.stop_generating()
            else:  # kill / disconnect
                ctx.kill()

        callhome: Optional[TcpCallHome] = None
        try:
            with span:
                callhome = await TcpCallHome.connect(conn_info, on_ctrl)
                agen = self.handler(request, ctx)
                async for item in agen:
                    if ctx.killed:
                        break
                    if guard.chaos() is not None or self._dead:
                        # worker-scoped chaos (dynarevive): a fired
                        # `worker.kill` rule turns THIS handle into a
                        # wedged process; sibling streams on the same
                        # handle die with it
                        if self._dead:
                            raise _WorkerKilled()
                        try:
                            await guard.chaos_point("worker.kill")
                        except (guard.ChaosError,
                                ConnectionResetError) as e:
                            raise _WorkerKilled() from e
                    env = item if isinstance(item, Annotated) \
                        else Annotated(data=item)
                    if env.id is None:
                        env.id = req_id
                    await callhome.send_data(pack(env.to_dict()))
                if self._dead:
                    raise _WorkerKilled()
                await callhome.complete()
        except _WorkerKilled:
            # die like a process: no error frame, no complete — the
            # caller sees a raw connection drop (finally closes it)
            await self._on_killed()
        except asyncio.CancelledError:
            if callhome:
                await callhome.error("worker cancelled")
        # not a swallow: the exception crosses the wire as an err frame
        # whose `kind` is the exception class name — AsyncResponseStream
        # re-raises DeadlineExceeded/NoCapacity/NoRespondersError typed
        # on the caller side, so the 504/503 mappers still see them
        except Exception as e:  # noqa: BLE001  # dynalint: disable=typed-error-swallow
            log.exception("handler failed for %s", req_id)
            if callhome:
                try:
                    await callhome.error(str(e), kind=type(e).__name__)
                except (ConnectionError, RuntimeError):
                    # conn already dead: the caller sees the drop anyway
                    log.debug("error frame for %s not delivered", req_id,
                              exc_info=True)
        finally:
            self._inflight.pop(req_id, None)
            if callhome:
                await callhome.close()


class AsyncResponseStream:
    """Caller-side response stream: async-iterates Annotated envelopes."""

    def __init__(self, pending, context: Context):
        self._pending = pending
        self.context = context

    def __aiter__(self):
        return self

    async def __anext__(self) -> Annotated:
        # the stream read is bounded by the request deadline: a wedged
        # worker costs the caller its remaining budget, never forever
        try:
            item = await guard.bound(self._pending.queue.get(),
                                     deadline=self.context.deadline,
                                     what="response stream read")
        except guard.DeadlineExceeded:
            self.context.kill()
            await self._pending.send_ctrl("kill")
            self._pending.close()
            raise
        if item is STREAM_COMPLETE:
            self._pending.close()
            raise StopAsyncIteration
        if isinstance(item, StreamError):
            self._pending.close()
            # typed re-raise by worker-side exception kind: client-error
            # kinds map to 4xx, deadline/capacity kinds keep their type
            # across the hop so frontends answer 504/503 — everything
            # else is a server-side RuntimeError
            if item.kind in ("ValueError", "ValidationError"):
                raise ValueError(item.message)
            if item.kind == "DeadlineExceeded":
                raise guard.DeadlineExceeded(item.message)
            if item.kind in ("NoCapacity", "NoRespondersError"):
                raise guard.NoCapacity(item.message)
            raise RuntimeError(
                f"stream error ({item.kind or 'unknown'}): {item.message}")
        return Annotated.from_dict(unpack(item))

    async def stop_generating(self) -> None:
        self.context.stop_generating()
        await self._pending.send_ctrl("stop")

    async def kill(self) -> None:
        self.context.kill()
        await self._pending.send_ctrl("kill")

    def close(self) -> None:
        self._pending.close()


class Client:
    """Endpoint client with discovery + routing (reference
    component/client.rs:64-244): watches the instance prefix, maintains the
    live instance list, and routes ``random`` / ``round_robin`` / ``direct``.
    """

    # consecutive stats-plane failures before an instance's breaker opens
    # (the PR 6 quarantine, now the shared CircuitBreaker implementation)
    STATS_EVICTION_THRESHOLD = 3
    # an open breaker offers a half-open probe every Nth denied round
    STATS_RETRY_EVERY = 5

    def __init__(self, drt, address: EndpointAddress,
                 retry: Optional[guard.RetryPolicy] = None):
        self.drt = drt
        self.address = address
        # written by the watch loop, snapshotted by routing and stats
        # collection; every post-await consumer re-validates membership
        # against it (collect_stats drops instances that departed during
        # the scrape gather rather than resurrecting their breakers)
        self.instances: Dict[int, EndpointInstance] = {}  # guarded-by: loop
        self._watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._rr = 0
        self._instances_event = asyncio.Event()
        # per-endpoint circuit breakers, one per (plane, instance):
        # "stats" guards the scrape plane (a crashed-but-leased worker
        # stops costing every round a failed probe), "request" guards
        # routing (a dead instance stops receiving picks). Discovery,
        # not breaker state, owns membership: instances stay in
        # ``instances`` and a fresh discovery put resets their breakers.
        self.breakers = guard.BreakerBoard(
            f"client:{address}",
            guard.BreakerConfig(
                threshold=env_int("DYN_BREAKER_THRESHOLD",
                                  self.STATS_EVICTION_THRESHOLD) or 3,
                probe_every=env_int("DYN_BREAKER_PROBE_EVERY",
                                    self.STATS_RETRY_EVERY) or 5,
                reset_after_s=env_float("DYN_BREAKER_RESET_S", 0.0) or 0.0))
        # shared retry policy: route resolution, dispatch, stats scrapes
        self.retry = retry or guard.RetryPolicy.from_env()

    async def _start(self) -> None:
        prefix = instance_prefix(self.address.namespace, self.address.component,
                                 self.address.endpoint)
        items, watch = await self.drt.dcp.kv_watch_prefix(prefix)
        for item in items:
            inst = EndpointInstance.from_dict(unpack(item.value))
            self.instances[inst.instance_id] = inst
        if self.instances:
            self._instances_event.set()
        self._watch = watch
        self._watch_task = spawn_tracked(
            self._watch_loop(), name=f"client-watch-{self.address}")

    async def _watch_loop(self) -> None:
        async for ev in self._watch:
            if ev.event == "put":
                inst = EndpointInstance.from_dict(unpack(ev.value))
                # a fresh discovery record closes the instance's
                # breakers: the worker re-registered, so probe it again
                self.breakers.reset("stats", inst.instance_id)
                self.breakers.reset("request", inst.instance_id)
                self.instances[inst.instance_id] = inst
                self._instances_event.set()
            elif ev.event == "delete":
                lease_hex = ev.key.rsplit(":", 1)[-1]
                try:
                    wid = int(lease_hex, 16)
                except ValueError:
                    continue
                self.instances.pop(wid, None)
                self.breakers.drop("stats", wid)
                self.breakers.drop("request", wid)
                if not self.instances:
                    self._instances_event.clear()

    async def close(self) -> None:
        if self._watch:
            await self._watch.stop()
        await cancel_join(self._watch_task)

    def instance_ids(self) -> List[int]:
        return sorted(self.instances)

    async def wait_for_instances(self, timeout: float = 30.0) -> List[int]:
        await asyncio.wait_for(self._instances_event.wait(), timeout)
        return self.instance_ids()

    # ------------------------------------------------------------- routing

    def _pick(self, mode: str, instance_id: Optional[int]
              ) -> Tuple[int, str]:
        """Returns ``(instance_id, subject)`` for the chosen route.
        Instances whose request-plane breaker is open are skipped
        (half-open single probes are admitted); when the breaker blocks
        every live instance the caller gets a typed :class:`NoCapacity`
        (HTTP 503), not a hang or a 500."""
        ids = self.instance_ids()
        if mode == "direct":
            if instance_id not in self.instances:
                raise RuntimeError(
                    f"instance {instance_id:x} of {self.address} not found"
                    if instance_id is not None else "direct() needs instance_id")
            if not self.breakers.get("request", instance_id).allow():
                raise guard.NoCapacity(
                    f"instance {instance_id:x} of {self.address} is "
                    f"circuit-broken")
            return instance_id, self.instances[instance_id].subject
        if not ids:
            raise NoRespondersError(f"no live instances of {self.address}")
        avail = [i for i in ids if self.breakers.get("request", i).allow()]
        if not avail:
            raise guard.NoCapacity(
                f"all {len(ids)} instances of {self.address} are "
                f"circuit-broken")
        if mode == "random":
            wid = random.choice(avail)
        elif mode == "round_robin":
            wid = avail[self._rr % len(avail)]
            self._rr += 1
        else:
            raise ValueError(f"unknown routing mode {mode}")
        for i in avail:  # hand back unused half-open probe permits
            if i != wid:
                self.breakers.get("request", i).release_probe()
        return wid, self.instances[wid].subject

    async def generate(self, request: Any, *, mode: str = "round_robin",
                       instance_id: Optional[int] = None,
                       context: Optional[Context] = None,
                       timeout: Optional[float] = None,
                       retry: Optional[guard.RetryPolicy] = None
                       ) -> AsyncResponseStream:
        """Issue a request; returns the streaming response.

        Reference egress/push.rs:83-181 — registers the local response
        stream, sends the request (with call-home connection info) over the
        request plane, awaits the worker's ack.

        Route resolution and dispatch run under the shared
        :class:`~dynamo_tpu.runtime.guard.RetryPolicy` (budget-aware:
        attempts never outlive ``context.deadline``); each attempt's ack
        wait is capped by the remaining deadline, and per-instance
        request breakers record the outcome. ``direct`` mode never
        retries — the caller (the processor) owns its fallback.
        """
        ctx = context or Context()
        deadline = ctx.deadline
        if timeout is None:
            timeout = env_float("DYN_REQUEST_TIMEOUT", 60.0) or 60.0
        policy = retry or self.retry
        last: Optional[BaseException] = None
        async for _attempt in policy.attempts(deadline):
            try:
                wid, subject = self._pick(mode, instance_id)
            except (NoRespondersError, guard.NoCapacity) as e:
                if mode == "direct":
                    raise
                last = e
                continue  # instances may (re)appear within the budget
            try:
                return await self._dispatch(wid, subject, request, ctx,
                                            timeout, deadline)
            except asyncio.CancelledError:
                raise
            except guard.DeadlineExceeded:
                raise
            except Exception as e:  # noqa: BLE001 — ack timeout/refusal
                self.breakers.get("request", wid).record_failure()
                if mode == "direct":
                    raise
                last = e
                log.warning("dispatch to instance %x of %s failed (%s); "
                            "retrying within budget", wid, self.address, e)
        raise last if last is not None else NoRespondersError(
            f"no live instances of {self.address}")

    async def _dispatch(self, wid: int, subject: str, request: Any,
                        ctx: Context, timeout: float,
                        deadline) -> AsyncResponseStream:
        """One dispatch attempt: register the response stream, send the
        envelope (deadline budget re-stamped at send time), await the
        worker's ack bounded by min(timeout, remaining budget)."""
        server: TcpStreamServer = await self.drt.tcp_server()
        pending = server.register()
        env_dict = {
            "req_id": ctx.id,
            "conn": TcpConnectionInfo(server.address, pending.subject).to_dict(),
            "payload": pack(request),
        }
        trace_ctx = tracing.get_tracer().current_trace_ctx()
        if trace_ctx is not None:  # omitted entirely when not sampled
            env_dict["trace"] = trace_ctx
        if deadline is not None:  # absent on the wire = no deadline
            env_dict["deadline_ms"] = deadline.to_wire_ms()
        envelope = pack(wire.checked(wire.DCP_REQUEST_ENVELOPE, env_dict))
        try:
            ack = wire.decoded(wire.DCP_REQUEST_ACK, unpack(
                await guard.bound(
                    self.drt.dcp.request(subject, envelope,
                                         timeout=timeout),
                    timeout=timeout, deadline=deadline,
                    what=f"request ack from {self.address}")))
            if not ack.get("accepted"):
                raise RuntimeError(f"request rejected: {ack}")
        except BaseException:
            pending.close()
            raise
        self.breakers.get("request", wid).record_success()
        return AsyncResponseStream(pending, ctx)

    async def round_robin(self, request: Any, **kw) -> AsyncResponseStream:
        return await self.generate(request, mode="round_robin", **kw)

    async def random(self, request: Any, **kw) -> AsyncResponseStream:
        return await self.generate(request, mode="random", **kw)

    async def direct(self, request: Any, instance_id: int, **kw) -> AsyncResponseStream:
        return await self.generate(request, mode="direct", instance_id=instance_id, **kw)

    # ------------------------------------------------------------- stats

    def evicted_ids(self) -> List[int]:
        """Instances whose stats-plane breaker is not closed (crashed-
        but-leased or blacked-out workers): off the scrape targets until
        a half-open probe succeeds or a fresh discovery put resets them.
        Only live-discovered instances are reported."""
        return sorted(wid for wid in self.instances
                      if self.breakers.get("stats", wid).state
                      != guard.BREAKER_CLOSED)

    async def collect_stats(self, timeout: Optional[float] = None
                            ) -> Dict[int, dict]:
        """Scrape per-instance stats over the request plane (reference
        service.rs collect_services / $SRV.STATS).

        Each instance's probe runs behind its stats-plane circuit
        breaker: ``STATS_EVICTION_THRESHOLD`` consecutive failed rounds
        open it (the instance stops costing every round a failed probe),
        an open breaker admits a single half-open re-probe every
        ``STATS_RETRY_EVERY``-th round, and a success closes it again.
        A failed probe is retried within the round under the shared
        RetryPolicy before it counts against the breaker."""
        if timeout is None:
            timeout = env_float("DYN_STATS_TIMEOUT", 2.0) or 2.0
        targets = [i for i in sorted(self.instances.values(),
                                     key=lambda i: i.instance_id)
                   if self.breakers.get("stats", i.instance_id).allow()]

        async def _probe(inst: EndpointInstance) -> dict:
            return wire.decoded(wire.DCP_STATS_REPLY, unpack(
                await self.drt.dcp.request(
                    f"stats.{inst.subject}", b"", timeout=timeout)))

        async def _one(inst: EndpointInstance) -> Optional[dict]:
            try:
                return await self.retry.run(
                    lambda: _probe(inst), retry_on=(Exception,),
                    what=f"stats probe {inst.instance_id:x}")
            except Exception:
                log.debug("stats probe failed for instance %x of %s",
                          inst.instance_id, self.address, exc_info=True)
                return None

        replies = await asyncio.gather(*(_one(i) for i in targets))
        # assemble in instance-id order (not completion order) so metric
        # consumers — router scheduler, planner — see a deterministic view
        out: Dict[int, dict] = {}
        for inst, resp in zip(targets, replies):
            if inst.instance_id not in self.instances:
                # departed during the gather (watch-loop delete dropped
                # its breakers): recording would resurrect a breaker for
                # a dead instance and leak a ghost gauge row
                continue
            br = self.breakers.get("stats", inst.instance_id)
            was_open = br.state != guard.BREAKER_CLOSED
            if resp is None:
                br.record_failure()
                if not was_open and br.state == guard.BREAKER_OPEN:
                    log.warning(
                        "instance %x of %s failed %d consecutive stats "
                        "rounds; breaker open (off the scrape targets)",
                        inst.instance_id, self.address, br.cfg.threshold)
            else:
                br.record_success()
                if was_open:
                    log.info("instance %x of %s answered again; breaker "
                             "closed", inst.instance_id, self.address)
                out[inst.instance_id] = resp
        return out
