"""dynarevive: mid-stream request failover, graceful worker drain, and
SLO-aware admission control.

Dynamo's serving story assumes workers die and pods roll (SURVEY §2.2,
§3.3): the router and planner survive churn, and graceful shutdown
drains in-flight work before releasing the lease. This module is the one
place those request-level survival policies live:

- **Mid-stream failover** (:class:`ReviveSession` + :class:`ReviveJournal`)
  — the frontend processor journals every token it has already emitted
  for an in-flight request (bounded, host-list appends only — nothing on
  the device hot path). When the upstream stream dies before a finish
  chunk (connection drop, worker crash, breaker open), the processor
  re-dispatches to a sibling worker with ``prompt + emitted_tokens`` as
  the new prompt and splices the continuation into the SAME client
  stream. Greedy requests are token-identical to an uninterrupted run
  (the resumed prefill recomputes the exact model state the dead worker
  held), and the KV router's overlap scoring lands the retry on the
  replica with the warmest prefix, so resume costs one prefill of
  already-cached blocks instead of a visible error.
- **Graceful drain** (:func:`drain_worker`) — the SIGTERM / ``POST
  /drain`` sequence: delete the discovery record (stop new admissions),
  finish in-flight sequences bounded by ``DYN_DRAIN_TIMEOUT_MS``, flush
  KV events, then release the lease. Draining ≠ dead: the stats plane
  keeps answering (with ``draining=1``) and in-flight streams complete.
- **SLO-aware admission control** (:class:`AdmissionController`) — the
  HTTP frontend sheds load *before* the engine melts, using signals the
  stack already exports (admission queue depth, loop-lag p99,
  kv_free_blocks), answering early 503s with a load-derived, jittered
  ``Retry-After`` instead of queueing requests it will deadline anyway.
  The jitter (injectable rng) decorrelates client retries so a
  recovering fleet is not re-stampeded at one synchronized instant.

Semantics are documented in docs/robustness.md (journal bound, resume
token-identity contract, drain state machine, shed signal table).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import math
import random
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import guard
from .config import env_float, env_int

log = logging.getLogger("dynamo_tpu.revive")


# ------------------------------------------------------------------ journal


class JournalEntry:
    """Emitted-token journal of one in-flight request. Append-only host
    list bounded by ``DYN_REVIVE_JOURNAL_TOKENS``; overflowing the bound
    marks the request non-resumable (we can no longer reconstruct the
    full resume prompt) rather than silently truncating it."""

    __slots__ = ("request_id", "prompt_tokens", "tokens", "resumes",
                 "resumable", "finished", "opened_at", "_bound")

    def __init__(self, request_id: str, prompt_tokens: int,
                 max_tokens: int):
        self.request_id = request_id
        self.prompt_tokens = prompt_tokens
        self.tokens: List[int] = []
        self.resumes = 0
        self.resumable = True
        self.finished = False
        self.opened_at = time.monotonic()
        self._bound = max_tokens

    def record(self, token_ids: List[int]) -> None:
        if not token_ids:
            return
        if len(self.tokens) + len(token_ids) > self._bound:
            self.resumable = False  # proto: revive.journal open->open
            return
        self.tokens.extend(token_ids)


class ReviveJournal:
    """Process-wide bounded ring of per-request token journals.

    Entries open at dispatch and close at finish/cancel, so steady state
    holds one entry per in-flight request; the ring cap
    (``DYN_REVIVE_RING``) only matters under leak bugs — an evicted
    entry's request simply loses resumability, never correctness."""

    def __init__(self, capacity: Optional[int] = None,
                 max_tokens: Optional[int] = None):
        self.capacity = capacity if capacity is not None else \
            (env_int("DYN_REVIVE_RING", 2048) or 2048)
        self.max_tokens = max_tokens if max_tokens is not None else \
            (env_int("DYN_REVIVE_JOURNAL_TOKENS", 4096) or 4096)
        self._entries: "OrderedDict[str, JournalEntry]" = OrderedDict()  # guarded-by: loop
        self.opened_total = 0
        self.resumed_total = 0
        self.evicted_total = 0

    def open(self, request_id: str, prompt_tokens: int) -> JournalEntry:
        entry = JournalEntry(request_id, prompt_tokens, self.max_tokens)
        self._entries[request_id] = entry
        self.opened_total += 1
        while len(self._entries) > self.capacity:
            _, old = self._entries.popitem(last=False)
            old.resumable = False  # proto: revive.journal open->open
            self.evicted_total += 1
        return entry

    def close(self, request_id: str) -> None:
        # idempotent pop = the close-exactly-once contract (model-checked
        # `closes` counter of the revive.journal machine)
        # proto: revive.journal open->closed
        self._entries.pop(request_id, None)

    def get(self, request_id: str) -> Optional[JournalEntry]:
        return self._entries.get(request_id)

    def __len__(self) -> int:
        return len(self._entries)

    def snapshot(self) -> dict:
        return {
            "inflight": len(self._entries),
            "capacity": self.capacity,
            "max_tokens": self.max_tokens,
            "opened_total": self.opened_total,
            "resumed_total": self.resumed_total,
            "evicted_total": self.evicted_total,
        }


_JOURNAL: Optional[ReviveJournal] = None


def journal() -> ReviveJournal:
    """The process journal (lazily constructed from the env knobs)."""
    global _JOURNAL
    if _JOURNAL is None:
        _JOURNAL = ReviveJournal()
    return _JOURNAL


def reset_journal() -> ReviveJournal:
    """Test hook: fresh journal (re-reads the env knobs)."""
    global _JOURNAL
    _JOURNAL = ReviveJournal()
    return _JOURNAL


# ----------------------------------------------------------------- failover

# upstream failure shapes a failover may recover from: worker crash /
# conn drop (RuntimeError via the stream-error plumbing, ConnectionError
# from a severed transport) and vanished instances. Typed budget/client
# errors (DeadlineExceeded, NoCapacity, ValueError) always propagate —
# resuming cannot help an expired budget or a bad request.
RESUMABLE_ERRORS: Tuple[type, ...] = (RuntimeError, ConnectionError)


def max_resumes() -> int:
    return env_int("DYN_REVIVE_MAX", 2) or 0


class ReviveSession:
    """Per-request failover state machine driven by the processor's
    remote-engine adapter.

    The session journals every emitted token (``observe``), decides
    whether a given upstream failure is worth a re-dispatch
    (``should_resume``), and builds the resume request
    (``resume_request``): ``prompt + emitted`` as the new prompt with the
    stop budget decremented by what was already emitted — the overlap
    dedupe that makes greedy resumes token-identical. ``echo_prompt`` is
    force-cleared on resume (the echo already streamed once).
    """

    def __init__(self, request: Any, context: Any, *,
                 limit: Optional[int] = None,
                 ring: Optional[ReviveJournal] = None):
        self.base = request
        self.context = context
        self.limit = limit if limit is not None else max_resumes()
        self.ring = ring if ring is not None else journal()
        self.entry = self.ring.open(context.id, len(request.token_ids))
        self.finished = False

    @property
    def emitted(self) -> List[int]:
        return self.entry.tokens

    @property
    def resumes(self) -> int:
        return self.entry.resumes

    def observe(self, out: Any) -> None:
        """Journal one upstream chunk (host-list append, off the token
        hot path)."""
        self.entry.record(list(out.token_ids or []))
        if out.finish_reason is not None:
            self.finished = True
            # eager ring close: downstream consumers abandon the stream
            # at the finish chunk, so waiting for the generator finalizer
            # would leak the entry until GC
            self.close()  # proto: revive.journal open->closed

    def close(self) -> None:
        self.ring.close(self.entry.request_id)

    def _budget_left(self) -> Optional[int]:
        mt = self.base.stop.max_tokens
        if mt is None:
            return None
        return mt - len(self.emitted)

    def budget_spent(self) -> bool:
        """The emitted tokens already cover the request's whole budget —
        the worker died between the last token and its finish chunk.
        Resume would dispatch a zero-token generation; synthesize the
        lost ``length`` finish instead."""
        left = self._budget_left()
        return left is not None and left <= 0

    def should_resume(self, exc: BaseException) -> bool:
        if self.finished or not isinstance(exc, RESUMABLE_ERRORS):
            return False
        if isinstance(exc, (guard.DeadlineExceeded, guard.NoCapacity)):
            return False
        if self.context.stopped:
            # client gone / budget spent: nothing to save — the guard
            # behind the model-checked no-resume-after-kill invariant
            # proto: request.lifecycle resumed->cancelled
            return False
        if not self.entry.resumable:
            return False
        return self.entry.resumes < self.limit

    def mark_resume(self) -> None:
        # proto: request.lifecycle prefill|decode->resumed
        self.entry.resumes += 1
        self.ring.resumed_total += 1
        guard.counter_inc("dyn_revive_resumes_total")
        # a failover resume means a worker just died mid-stream: capture
        # the evidence of why (cold path — resumes are rare)
        from . import blackbox
        blackbox.notify_trigger("failover_resume", {
            "request_id": self.entry.request_id,
            "resumes": self.entry.resumes,
        })

    def resume_request(self) -> Any:
        """The re-dispatch request: original prompt + journaled tokens,
        stop budget decremented, echo suppressed."""
        pre = self.base
        emitted = list(self.emitted)
        stop = dataclasses.replace(
            pre.stop,
            max_tokens=(None if pre.stop.max_tokens is None
                        else max(pre.stop.max_tokens - len(emitted), 1)),
            min_tokens=(None if not pre.stop.min_tokens
                        else max(pre.stop.min_tokens - len(emitted), 0)))
        output = dataclasses.replace(pre.output, echo_prompt=False)
        return dataclasses.replace(
            pre, token_ids=list(pre.token_ids) + emitted,
            stop=stop, output=output)

    def synthetic_finish(self) -> Any:
        """Finish chunk for the budget-spent edge (every budgeted token
        was emitted, only the finish chunk was lost with the worker)."""
        from ..llm.protocols.common import FINISH_LENGTH, EngineOutput

        return EngineOutput(
            token_ids=[], finish_reason=FINISH_LENGTH,
            prompt_tokens=self.entry.prompt_tokens,
            completion_tokens=len(self.emitted))


# ------------------------------------------------------------------- drain


def drain_timeout_s(timeout_ms: Optional[float] = None) -> float:
    ms = timeout_ms if timeout_ms is not None else \
        (env_float("DYN_DRAIN_TIMEOUT_MS", 10000.0) or 10000.0)
    return max(ms, 0.0) / 1000.0


async def drain_worker(handle, *, engine=None, publisher=None,
                       timeout_s: Optional[float] = None) -> bool:
    """The graceful-drain state machine for one served worker endpoint:

    1. ``begin_drain`` — delete the discovery record (routers stop
       picking this instance; a fresh direct dispatch gets a typed
       ``accepted=False`` nack) while the stats plane keeps answering
       with ``draining=1`` (draining ≠ dead: no breaker opens, no
       eviction);
    2. finish in-flight sequences, bounded by ``DYN_DRAIN_TIMEOUT_MS``
       (engine-level drain when the engine supports it);
    3. flush pending KV events so the router's index reflects the final
       cache state;
    4. full stop — withdraw subscriptions; the caller then releases the
       lease (``drt.shutdown()``).

    Returns True when everything in flight finished inside the budget
    (False = the timeout killed leftovers).
    """
    if timeout_s is None:
        timeout_s = drain_timeout_s()
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    await handle.begin_drain()
    drained = await handle.wait_idle(timeout_s)
    if engine is not None and hasattr(engine, "drain"):
        remaining = max(deadline - loop.time(), 0.0)
        # engine lifecycle drain, itself bounded by `remaining`
        drained = await engine.drain(  # dynalint: disable=unbounded-await
            remaining) and drained
    if publisher is not None and hasattr(publisher, "flush"):
        try:
            await publisher.flush()
        except Exception:  # noqa: BLE001 — flush is best-effort on the way out
            log.debug("KV event flush during drain failed", exc_info=True)
    await handle.stop()
    guard.counter_inc("dyn_revive_drains_total",
                      outcome="clean" if drained else "timeout")
    log.info("worker %s drained (%s)",
             getattr(getattr(handle, "instance", None), "subject", "?"),
             "clean" if drained else "timeout")
    return drained


# -------------------------------------------------------- admission control


@dataclass(frozen=True)
class ShedConfig:
    """Shed thresholds. 0 disables the corresponding signal entirely —
    the default frontend sheds on nothing until configured."""

    queue_depth: int = 0          # waiting requests per live worker
    loop_lag_ms: float = 0.0      # engine loop-lag p99 (worst worker)
    kv_free_blocks: int = 0       # min free KV blocks (worst worker)
    retry_after_cap_s: float = 8.0

    @classmethod
    def from_env(cls) -> "ShedConfig":
        return cls(
            queue_depth=env_int("DYN_SHED_QUEUE_DEPTH", 0) or 0,
            loop_lag_ms=env_float("DYN_SHED_LOOP_LAG_MS", 0.0) or 0.0,
            kv_free_blocks=env_int("DYN_SHED_KV_FREE_BLOCKS", 0) or 0,
            retry_after_cap_s=env_float("DYN_SHED_RETRY_CAP_S", 8.0)
            or 8.0)

    @property
    def enabled(self) -> bool:
        return bool(self.queue_depth or self.loop_lag_ms
                    or self.kv_free_blocks)


@dataclass
class LoadSignals:
    """One snapshot of the signals the stack already exports."""

    queue_depth: int = 0              # summed admission queue depth
    workers: int = 1                  # live workers contributing
    loop_lag_p99_ms: float = 0.0      # worst per-worker loop-lag p99
    kv_free_blocks: Optional[int] = None  # min free blocks; None=unknown


def signals_from_stats(stats: dict) -> LoadSignals:
    """LoadSignals from one engine's ``stats()`` dict (in-process
    frontend serving its own engine)."""
    return LoadSignals(
        queue_depth=int(stats.get("num_requests_waiting", 0) or 0),
        workers=1,
        loop_lag_p99_ms=float(stats.get("loop_lag_p99_seconds", 0.0)
                              or 0.0) * 1000.0,
        kv_free_blocks=stats.get("kv_free_blocks"))


def signals_from_metrics(worker_metrics: Dict[Any, Any]) -> LoadSignals:
    """LoadSignals from an aggregator's per-worker ForwardPassMetrics
    view (standalone frontend over remote workers). Duck-typed so the
    runtime layer never imports llm protocols."""
    metrics = [m for wid, m in sorted(worker_metrics.items(),
                                      key=lambda kv: repr(kv[0]))
               if not getattr(m, "draining", 0)]
    if not metrics:
        return LoadSignals()
    return LoadSignals(
        queue_depth=sum(int(getattr(m, "num_requests_waiting", 0))
                        for m in metrics),
        workers=len(metrics),
        loop_lag_p99_ms=max(
            float(getattr(m, "loop_lag_p99_seconds", 0.0)) * 1000.0
            for m in metrics),
        kv_free_blocks=min(int(getattr(m, "kv_free_blocks", 0))
                           for m in metrics))


class AdmissionController:
    """Shed-before-melt: evaluate the current load signals against the
    thresholds and either admit or answer an early 503 whose
    ``Retry-After`` is derived from the shed pressure with deterministic
    (injectable-rng) jitter.

    ``signals`` is any zero-arg callable returning :class:`LoadSignals`
    — an engine ``stats()`` adapter in-process, an aggregator view on a
    standalone frontend, or a literal in tests.

    Decisions use a **peak-hold window** over recent observations, not
    just the instantaneous read: batched engines complete requests in
    lockstep, so arrival instants anti-correlate with queue depth — an
    instantaneous read admits a whole wave at the exact moment the queue
    drained into the freed slots. ``start()`` runs an optional
    background sampler so the window sees load between arrivals too.
    """

    def __init__(self, signals: Callable[[], LoadSignals],
                 cfg: Optional[ShedConfig] = None,
                 rng: Optional[random.Random] = None,
                 window: int = 32):
        self.signals = signals
        self.cfg = cfg or ShedConfig.from_env()
        self.rng = rng if rng is not None else random.Random()
        self.shed_total = 0
        self.shed_by_signal: Dict[str, int] = {}
        self.admitted_total = 0
        from collections import deque
        self._window: Any = deque(maxlen=max(window, 1))  # guarded-by: loop
        self._task = None

    def start(self, interval_s: float = 0.05) -> None:
        """Run the background signal sampler (fills the peak-hold window
        between request arrivals). Optional: drivers that step time
        themselves just call ``admit()``/``observe()``."""
        from .tasks import spawn_tracked

        if self._task is None:
            self._task = spawn_tracked(self._sample_loop(interval_s),
                                       name="admission-sampler")

    async def stop(self) -> None:
        from .tasks import cancel_join

        task, self._task = self._task, None  # claim before the await
        await cancel_join(task)

    async def _sample_loop(self, interval_s: float) -> None:
        while True:
            self.observe()
            await asyncio.sleep(interval_s)

    def observe(self) -> Optional[LoadSignals]:
        """Read the signal source once into the peak-hold window."""
        try:
            sig = self.signals()
        except Exception:  # noqa: BLE001 — a broken signal source must
            # never turn into a shed storm (or an admit storm): admit
            log.debug("admission signal source failed", exc_info=True)
            return None
        self._window.append(sig)
        return sig

    def _effective(self) -> Optional[LoadSignals]:
        """Fresh read + peak over the recent window."""
        now = self.observe()
        if now is None:
            return None
        window = list(self._window)
        frees = [s.kv_free_blocks for s in window
                 if s.kv_free_blocks is not None]
        return LoadSignals(
            queue_depth=max(s.queue_depth for s in window),
            workers=now.workers,
            loop_lag_p99_ms=max(s.loop_lag_p99_ms for s in window),
            kv_free_blocks=min(frees) if frees else None)

    def evaluate(self) -> Tuple[Optional[str], float]:
        """(shedding signal name | None, pressure). Pressure 1.0 = at
        the threshold; the worst offending signal wins."""
        cfg = self.cfg
        if not cfg.enabled:
            return None, 0.0
        sig = self._effective()
        if sig is None:
            return None, 0.0
        worst: Tuple[Optional[str], float] = (None, 0.0)
        if cfg.queue_depth > 0:
            cap = cfg.queue_depth * max(sig.workers, 1)
            pressure = sig.queue_depth / cap
            if pressure > worst[1]:
                worst = ("queue_depth", pressure)
        if cfg.loop_lag_ms > 0 and sig.loop_lag_p99_ms > 0:
            pressure = sig.loop_lag_p99_ms / cfg.loop_lag_ms
            if pressure > worst[1]:
                worst = ("loop_lag", pressure)
        if cfg.kv_free_blocks > 0 and sig.kv_free_blocks is not None:
            pressure = cfg.kv_free_blocks / max(sig.kv_free_blocks, 1)
            if pressure > worst[1]:
                worst = ("kv_free_blocks", pressure)
        name, pressure = worst
        if name is not None and pressure >= 1.0:
            return name, pressure
        return None, pressure

    def admit(self) -> Optional[int]:
        """None = admit; otherwise the Retry-After (seconds) for the
        shed 503."""
        name, pressure = self.evaluate()
        if name is None:
            self.admitted_total += 1
            return None
        self.shed_total += 1
        self.shed_by_signal[name] = self.shed_by_signal.get(name, 0) + 1
        guard.counter_inc("dyn_shed_requests_total", signal=name)
        return self.retry_after(pressure)

    def retry_after(self, pressure: float = 1.0) -> int:
        return retry_after_s(pressure, rng=self.rng,
                             cap_s=self.cfg.retry_after_cap_s)

    def snapshot(self) -> dict:
        name, pressure = self.evaluate()
        return {
            "enabled": self.cfg.enabled,
            "shedding": name,
            "pressure": round(pressure, 4),
            "shed_total": self.shed_total,
            "shed_by_signal": dict(sorted(self.shed_by_signal.items())),
            "admitted_total": self.admitted_total,
        }


# process-default rng for Retry-After jitter on paths with no controller
_RETRY_RNG = random.Random()


def retry_after_s(pressure: float = 1.0,
                  rng: Optional[random.Random] = None,
                  cap_s: Optional[float] = None) -> int:
    """Load-derived, jittered Retry-After: grows with shed pressure,
    capped, and jittered ±40% so synchronized client retries spread out
    instead of re-stampeding a recovering fleet at one instant. Always
    at least 1 (the HTTP delta-seconds floor)."""
    if cap_s is None:
        cap_s = env_float("DYN_SHED_RETRY_CAP_S", 8.0) or 8.0
    r = rng if rng is not None else _RETRY_RNG
    base = min(max(pressure, 1.0), cap_s)
    return max(1, int(math.ceil(min(base * r.uniform(0.6, 1.4), cap_s))))
