"""Asyncio client for the control-plane service (DCP).

Plays the role of both the etcd client (reference
lib/runtime/src/transports/etcd.rs: ``kv_create``/``kv_put``/
``kv_get_prefix``/``kv_get_and_watch_prefix``, primary lease w/ keep-alive
tied to cancellation) and the NATS client (reference transports/nats.rs:
pub/sub, request/reply, JetStream queues) over the unified DCP wire protocol.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from dataclasses import dataclass
from typing import AsyncIterator, Awaitable, Callable, Dict, List, Optional, Tuple

import msgpack

from . import wire
from .config import env_float
from .dcp_server import pack_frame, read_frame
from .tasks import cancel_join, spawn_tracked


def _io_timeout() -> float:
    return env_float("DYN_IO_TIMEOUT", 30.0) or 30.0

log = logging.getLogger("dynamo_tpu.dcp.client")


@dataclass
class KvItem:
    key: str
    value: bytes
    lease: int = 0
    mod_rev: int = 0


@dataclass
class WatchEvent:
    """Put/Delete event from a prefix watch (reference etcd.rs WatchEvent)."""

    event: str  # "put" | "delete"
    key: str
    value: Optional[bytes]


class DcpError(RuntimeError):
    pass


class NoRespondersError(DcpError):
    pass


class CasConflict(DcpError):
    """kv_cas lost the race: the key's mod_rev moved. Raised off the
    server's structured ``conflict`` flag, not the error text."""


class DcpClient:
    """One connection to the DCP server, usable concurrently from many tasks."""

    def __init__(self) -> None:
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watch_ids = itertools.count(1)
        self._watch_queues: Dict[int, asyncio.Queue] = {}
        self._sub_handlers: Dict[int, Callable[[dict], Awaitable[None]]] = {}
        self._rx_task: Optional[asyncio.Task] = None
        self._wlock = asyncio.Lock()
        self._closed = False
        self.address = ""

    # ------------------------------------------------------------- lifecycle

    @classmethod
    async def connect(cls, address: str) -> "DcpClient":
        self = cls()
        host, _, port = address.rpartition(":")
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), _io_timeout())
        self._rx_task = spawn_tracked(self._rx_loop(),
                                      name=f"dcp-client-rx-{address}")
        self.address = address
        return self

    async def close(self) -> None:
        self._closed = True
        await cancel_join(self._rx_task)
        if self._writer:
            try:
                self._writer.close()
                await asyncio.wait_for(self._writer.wait_closed(),
                                       _io_timeout())
            except Exception:
                pass
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(DcpError("connection closed"))
        self._pending.clear()

    @property
    def connected(self) -> bool:
        return not self._closed and self._writer is not None

    # --------------------------------------------------------------- rx loop

    async def _rx_loop(self) -> None:
        try:
            while True:
                # idle demux read: every RPC bounds its own reply future;
                # this loop lives exactly as long as the connection
                msg = await read_frame(self._reader)  # dynalint: disable=unbounded-await
                if "push" in msg:
                    await self._on_push(msg)
                else:
                    fut = self._pending.pop(msg.get("seq"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        except Exception:
            log.exception("dcp client rx error")
        finally:
            self._closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(DcpError("connection lost"))
            self._pending.clear()
            for q in self._watch_queues.values():
                q.put_nowait(None)

    async def _on_push(self, msg: dict) -> None:
        msg = wire.decoded((wire.DCP_PUSH_WATCH, wire.DCP_PUSH_MSG,
                            wire.DCP_PUSH_REQ), msg)
        kind = msg["push"]
        if kind == "watch":
            q = self._watch_queues.get(msg["watch_id"])
            if q is not None:
                q.put_nowait(WatchEvent(msg["event"], msg["key"], msg.get("value")))
        elif kind in ("msg", "req"):
            handler = self._sub_handlers.get(msg["sid"])
            if handler is not None:
                spawn_tracked(self._run_handler(handler, msg),
                              name=f"dcp-sub-{msg.get('subject')}")
            elif kind == "req":
                await self._send_raw(
                    {"op": "reply", "seq": next(self._seq), "reply": msg["reply"],
                     "ok": False, "error": "no handler"})

    async def _run_handler(self, handler, msg: dict) -> None:
        try:
            await handler(msg)
        except Exception:
            log.exception("subscription handler failed for %s", msg.get("subject"))

    # ------------------------------------------------------------------- rpc

    async def _send_raw(self, msg: dict) -> None:
        async with self._wlock:
            self._writer.write(pack_frame(msg))
            # bounded drain under the frame lock: atomicity needs the
            # lock held across the write, DYN_IO_TIMEOUT bounds it
            await asyncio.wait_for(  # dynalint: disable=lock-across-blocking
                self._writer.drain(), _io_timeout())

    async def _call(self, op: str, timeout: Optional[float] = None, **kw) -> dict:
        if self._closed:
            raise DcpError("client closed")
        seq = next(self._seq)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        await self._send_raw({"op": op, "seq": seq, **kw})
        try:
            resp = await (asyncio.wait_for(fut, timeout) if timeout else fut)
        finally:
            self._pending.pop(seq, None)
        if not resp.get("ok", True):
            err = resp.get("error", "unknown")
            if resp.get("conflict"):
                raise CasConflict(err)
            if "no responders" in str(err):
                raise NoRespondersError(err)
            raise DcpError(err)
        return resp

    # ---------------------------------------------------------------- KV API

    async def kv_put(self, key: str, value: bytes, lease: int = 0) -> int:
        resp = await self._call("kv_put", key=key, value=value, lease=lease)
        return resp["rev"]

    async def kv_create(self, key: str, value: bytes, lease: int = 0) -> bool:
        """Create-if-absent; returns False when the key already exists."""
        try:
            await self._call("kv_create", key=key, value=value, lease=lease)
            return True
        except DcpError as e:
            if "exists" in str(e):
                return False
            raise

    async def kv_get(self, key: str) -> Optional[bytes]:
        resp = await self._call("kv_get", key=key)
        return resp["value"] if resp.get("found") else None

    async def kv_get_item(self, key: str) -> Optional[KvItem]:
        """kv_get with metadata (mod_rev for CAS round-trips)."""
        resp = await self._call("kv_get", key=key)
        if not resp.get("found"):
            return None
        return KvItem(key, resp["value"], resp.get("lease", 0),
                      resp.get("mod_rev", 0))

    async def kv_cas(self, key: str, value: bytes, prev_rev: int,
                     lease: int = 0) -> bool:
        """Compare-and-swap: write only if the key's mod_rev still equals
        ``prev_rev`` (0 = key must not exist).  Returns False on conflict
        (reference etcd.rs transactional guard)."""
        try:
            await self._call("kv_put", key=key, value=value, lease=lease,
                             prev_rev=prev_rev)
            return True
        except CasConflict:
            return False

    async def kv_get_prefix(self, prefix: str) -> List[KvItem]:
        resp = await self._call("kv_get_prefix", prefix=prefix)
        return [KvItem(i["key"], i["value"], i.get("lease", 0), i.get("mod_rev", 0)) for i in resp["items"]]

    async def kv_delete(self, key: str) -> bool:
        return (await self._call("kv_delete", key=key))["deleted"]

    async def kv_delete_prefix(self, prefix: str) -> int:
        return (await self._call("kv_delete_prefix", prefix=prefix))["deleted"]

    async def kv_watch_prefix(
        self, prefix: str
    ) -> Tuple[List[KvItem], "PrefixWatch"]:
        """Returns (current items, watch stream) — reference
        etcd.rs kv_get_and_watch_prefix."""
        wid = next(self._watch_ids)
        q: asyncio.Queue = asyncio.Queue()
        self._watch_queues[wid] = q
        resp = await self._call("watch_prefix", prefix=prefix, watch_id=wid)
        items = [KvItem(i["key"], i["value"], i.get("lease", 0), i.get("mod_rev", 0)) for i in resp["items"]]
        return items, PrefixWatch(self, wid, q)

    # ------------------------------------------------------------- lease API

    async def lease_grant(self, ttl: float = 10.0) -> int:
        return (await self._call("lease_grant", ttl=ttl))["lease"]

    async def lease_keepalive(self, lease: int,
                              timeout: Optional[float] = None) -> None:
        await self._call("lease_keepalive", lease=lease, timeout=timeout)

    async def lease_revoke(self, lease: int) -> None:
        await self._call("lease_revoke", lease=lease)

    # NOTE: there is deliberately no loop-resident keepalive helper. An
    # asyncio-task renewal starves whenever synchronous work blocks the
    # loop for multiples of the TTL (XLA warmup, bulk host transfers) and
    # the lease expires — the exact failure the r3 bench hit. Every lease
    # that must stay alive renews via :class:`KeepaliveThread` (its own
    # thread + connection); DistributedRuntime's primary lease — the one
    # all instance/endpoint records attach to — does.

    # ----------------------------------------------------------- pub/sub API

    async def subscribe(
        self,
        subject: str,
        handler: Callable[["Message"], Awaitable[None]],
        group: Optional[str] = None,
    ) -> int:
        """Subscribe; ``handler(Message)`` runs per delivery. For request-plane
        subjects, use ``msg.respond()`` to send the reply."""

        async def _raw(msg: dict) -> None:
            await handler(Message(self, msg))

        resp = await self._call("sub", subject=subject, group=group)
        sid = resp["sid"]
        self._sub_handlers[sid] = _raw
        return sid

    async def unsubscribe(self, sid: int) -> None:
        self._sub_handlers.pop(sid, None)
        await self._call("unsub", sid=sid)

    async def publish(self, subject: str, payload: bytes) -> None:
        await self._call("pub", subject=subject, payload=payload)

    async def request(self, subject: str, payload: bytes,
                      timeout: float = 30.0) -> bytes:
        resp = await self._call("req", subject=subject, payload=payload,
                                timeout=timeout)
        return resp["payload"]

    # --------------------------------------------------------- work-queue API

    async def queue_put(self, queue: str, payload: bytes) -> None:
        await self._call("q_put", queue=queue, payload=payload)

    async def queue_pull(self, queue: str,
                         timeout: float = 0.0) -> Optional[bytes]:
        resp = await self._call(
            "q_pull", queue=queue, timeout_ms=int(timeout * 1000))
        return resp["payload"] if resp.get("found") else None

    async def queue_len(self, queue: str) -> int:
        return (await self._call("q_len", queue=queue))["len"]

    async def ping(self) -> float:
        return (await self._call("ping"))["time"]


class Message:
    """A delivered pub/sub or request-plane message."""

    __slots__ = ("_client", "subject", "payload", "_reply")

    def __init__(self, client: DcpClient, raw: dict):
        self._client = client
        raw = wire.decoded((wire.DCP_PUSH_MSG, wire.DCP_PUSH_REQ), raw)
        self.subject: str = raw["subject"]
        self.payload: bytes = raw["payload"]
        self._reply: Optional[int] = raw.get("reply")

    @property
    def needs_reply(self) -> bool:
        return self._reply is not None

    async def respond(self, payload: bytes) -> None:
        assert self._reply is not None, "not a request message"
        await self._client._send_raw(
            {"op": "reply", "seq": next(self._client._seq),
             "reply": self._reply, "ok": True, "payload": payload})

    async def respond_error(self, error: str) -> None:
        assert self._reply is not None, "not a request message"
        await self._client._send_raw(
            {"op": "reply", "seq": next(self._client._seq),
             "reply": self._reply, "ok": False, "error": error})


class PrefixWatch:
    """Async iterator of WatchEvents; ``stop()`` to end."""

    def __init__(self, client: DcpClient, watch_id: int, queue: asyncio.Queue):
        self._client = client
        self._watch_id = watch_id
        self._queue = queue
        self._stopped = False

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        if self._stopped:
            raise StopAsyncIteration
        # a watch stream is unbounded by design; server death enqueues a
        # None sentinel (rx loop finally), so this can never wedge
        ev = await self._queue.get()  # dynalint: disable=unbounded-await
        if ev is None:
            raise StopAsyncIteration
        return ev

    async def stop(self) -> None:
        self._stopped = True
        self._client._watch_queues.pop(self._watch_id, None)
        try:
            await self._client._call("unwatch", watch_id=self._watch_id)
        except DcpError:
            pass
        self._queue.put_nowait(None)


def pack(obj) -> bytes:
    """Standard payload serialization for the framework (msgpack)."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes):
    return msgpack.unpackb(data, raw=False)


class KeepaliveThread:
    """Lease keep-alive on a dedicated daemon thread with its OWN
    connection and event loop, immune to main-loop stalls.

    The serving process routinely blocks its event loop for multiples of
    the lease TTL — engine warmup compiles the whole bucket grid
    synchronously, host-staged KV transfers materialize multi-MB arrays —
    and a loop-resident keepalive task then starves until the lease
    expires, deleting every lease-attached key (endpoint instances, the
    disagg transfer endpoint) out from under a live worker. A thread with
    its own socket keeps renewals flowing regardless; with the embedded
    DCP server the renewal frames queue in the socket during a stall and
    are processed before the reaper's timer callback when the loop
    resumes (asyncio runs IO callbacks ahead of timers in an iteration).
    """

    def __init__(self, address: str, lease: int, ttl: float):
        import threading

        self.address = address
        self.lease = lease
        self.ttl = ttl
        self.dead = False          # lease reported gone by the server
        self._stop = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._waker: Optional[asyncio.Event] = None
        self._thread = threading.Thread(
            target=self._run, name=f"dcp-keepalive-{lease:x}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            asyncio.run(self._amain())
        except Exception:  # noqa: BLE001 — best-effort background thread
            log.exception("keepalive thread for lease %x died", self.lease)

    async def _amain(self) -> None:
        interval = max(self.ttl / 3.0, 0.05)
        self._loop = asyncio.get_running_loop()
        self._waker = asyncio.Event()
        client: Optional[DcpClient] = None

        async def _pause() -> None:
            try:
                await asyncio.wait_for(self._waker.wait(), interval)
            except asyncio.TimeoutError:
                pass

        try:
            # connect EAGERLY, before the first interval: once a stall
            # begins, the (possibly loop-embedded) server can no longer
            # accept, and renewals can only queue on an existing socket
            try:
                client = await DcpClient.connect(self.address)
            except OSError:
                pass
            while not self._stop.is_set():
                await _pause()
                if self._stop.is_set():
                    return
                try:
                    if client is None or not client.connected:
                        if client is not None:
                            await client.close()
                        client = await DcpClient.connect(self.address)
                    # bound the wait so a wedged server can't pin the
                    # thread past cancel()
                    await client.lease_keepalive(
                        self.lease, timeout=max(self.ttl, 1.0))
                except DcpError as e:
                    if "lease" in str(e):
                        # the server says the lease is GONE (expired or
                        # revoked) — renewing cannot resurrect it, and the
                        # worker's lease-attached records are already
                        # deleted. Surface loudly and stop; the owner
                        # must re-attach to get a new identity. (During
                        # shutdown the revoke races a final renewal —
                        # that's the expected quiet path, not an error.)
                        if not self._stop.is_set():
                            log.error(
                                "lease %x is gone (%s): keepalive "
                                "stopping — this worker's instance "
                                "records are deleted; re-attach to "
                                "rejoin discovery", self.lease, e)
                        self.dead = True
                        return
                    await self._drop(client)
                    client = None
                except (OSError, asyncio.TimeoutError):
                    # server briefly down/stalled: keep trying until
                    # cancelled — renewals must survive transient faults
                    await self._drop(client)
                    client = None
        finally:
            if client is not None:
                await client.close()

    @staticmethod
    async def _drop(client: Optional[DcpClient]) -> None:
        try:
            if client is not None:
                await client.close()
        except Exception:  # noqa: BLE001
            pass

    def cancel(self) -> None:
        """Stop the thread. Wakes its sleep via its own loop so the join
        returns in milliseconds instead of blocking the caller up to a
        renewal interval."""
        self._stop.set()
        if self._loop is not None and self._waker is not None:
            try:
                self._loop.call_soon_threadsafe(self._waker.set)
            except RuntimeError:
                pass  # thread's loop already closed
        self._thread.join(timeout=2.0)
