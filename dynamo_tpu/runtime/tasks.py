"""Tracked background tasks: the dynalint-mandated replacement for bare
``asyncio.create_task`` / ``asyncio.ensure_future``.

The Rust reference gets this from the type system: an ``AsyncEngine``
task handle must be joined or aborted, and a dropped ``JoinHandle``
detaches loudly. A bare Python task, by contrast, swallows its exception
until the object is GC'd (the "Task exception was never retrieved" log
nobody sees) and keeps only a weak reference in the loop, so it can even
be collected mid-flight. Every background task in this codebase goes
through :func:`spawn_tracked`, which pins a strong reference and logs
crashes at error level the moment they happen, and every ``stop()`` path
goes through :func:`cancel_join`, which bounds how long a wedged task
can stall shutdown.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Coroutine, Optional, Set

log = logging.getLogger("dynamo_tpu.tasks")

# strong refs: the event loop only keeps weak ones, so a fire-and-forget
# task with no other referent can be GC'd before it finishes
_BACKGROUND: Set[asyncio.Task] = set()


def spawn_tracked(coro: Coroutine, *, name: Optional[str] = None,
                  logger: Optional[logging.Logger] = None) -> asyncio.Task:
    """``asyncio.create_task`` + crash logging + GC pinning.

    The returned task is still a plain :class:`asyncio.Task` — await it,
    cancel it, or hand it to :func:`cancel_join` on stop. Exceptions that
    would otherwise vanish are logged (and marked retrieved) the moment
    the task finishes.
    """
    task = asyncio.create_task(coro, name=name)  # dynalint: disable=fire-and-forget-task
    _BACKGROUND.add(task)
    task.add_done_callback(lambda t: _on_task_done(t, logger or log))
    return task


def _on_task_done(task: asyncio.Task, logger: logging.Logger) -> None:
    _BACKGROUND.discard(task)
    if task.cancelled():
        return
    exc = task.exception()  # marks the exception retrieved
    if exc is not None:
        logger.error("background task %r crashed", task.get_name(),
                     exc_info=exc)


async def cancel_join(*tasks: Optional[asyncio.Task],
                      timeout: float = 5.0) -> None:
    """Cancel task(s) and wait for them to actually exit.

    ``None`` entries are skipped so ``await cancel_join(self._task)``
    works before ``start()``. A task that ignores cancellation for
    ``timeout`` seconds is abandoned with a warning instead of wedging
    the caller's shutdown forever.
    """
    live = [t for t in tasks if t is not None]
    for t in live:
        t.cancel()
    if not live:
        return
    _done, pending = await asyncio.wait(live, timeout=timeout)
    for t in pending:
        log.warning("task %r ignored cancellation for %.1fs; abandoning",
                    t.get_name(), timeout)


def backoff_interval(base: float, failures: int, cap: float = 30.0) -> float:
    """Bounded exponential backoff for scrape/poll loops: ``base`` while
    healthy, doubling per consecutive failure up to ``cap`` — a
    persistently-failing dependency gets polled gently, not hammered."""
    if failures <= 0:
        return base
    return min(base * (2.0 ** min(failures, 16)), max(cap, base))
