"""Composable pipeline nodes over AsyncEngine.

Reference lib/runtime/src/pipeline/ (~1,200 LoC: Source/Sink/Operator/
ServiceFrontend/ServiceBackend node graph with SingleIn/ManyOut engine
typedefs). The TPU build keeps the same composition algebra in asyncio
terms: every stage is an AsyncEngine (``generate(request, context) →
async-iterator``), an **Operator** transforms request downward and the
response stream upward, and ``chain(...)`` folds operators onto a sink
engine. The LLM chains (OpenAIPreprocessor → Backend → engine,
llm/engines.py) are instances of this algebra; this module makes the node
graph available to user pipelines and the SDK.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Awaitable, Callable, Optional

from .engine import Context

Engine = Callable[[Any, Context], AsyncIterator[Any]]


class Operator:
    """A bidirectional stage: lowers the request on the way down and
    transforms the response stream on the way up (reference pipeline
    Operator; OpenAIPreprocessor is the canonical instance)."""

    async def lower(self, request: Any, context: Context) -> Any:
        return request

    def raise_stream(self, request: Any, lowered: Any,
                     stream: AsyncIterator[Any],
                     context: Context) -> AsyncIterator[Any]:
        return stream


class FnOperator(Operator):
    """Operator from two functions: ``lower(request, ctx)`` and
    ``raise_item(item, ctx)`` applied per response item."""

    def __init__(self, lower_fn: Optional[Callable[[Any, Context],
                                                   Awaitable[Any]]] = None,
                 raise_fn: Optional[Callable[[Any, Context], Any]] = None):
        self._lower = lower_fn
        self._raise = raise_fn

    async def lower(self, request: Any, context: Context) -> Any:
        if self._lower is None:
            return request
        return await self._lower(request, context)

    async def _gen(self, stream, context):
        async for item in stream:
            yield self._raise(item, context) if self._raise else item

    def raise_stream(self, request, lowered, stream, context):
        return self._gen(stream, context)


class Stage:
    """One operator applied on top of an inner engine; itself an engine."""

    def __init__(self, op: Operator, inner: Engine):
        self.op = op
        self.inner = inner

    def __call__(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._run(request, context)

    async def _run(self, request: Any, context: Context):
        lowered = await self.op.lower(request, context)
        stream = self.inner(lowered, context)
        async for item in self.op.raise_stream(request, lowered, stream,
                                               context):
            yield item


def chain(*ops: Operator, sink: Engine) -> Engine:
    """Fold operators onto a sink engine:
    ``chain(A, B, sink=engine)`` runs A.lower → B.lower → engine →
    B.raise → A.raise (reference ServiceFrontend→…→ServiceBackend link)."""
    engine: Engine = sink
    for op in reversed(ops):
        engine = Stage(op, engine)
    return engine


class SegmentSource:
    """Serve a pipeline segment as a component endpoint: requests arrive
    from the network, flow through the local chain, responses stream back
    (reference SegmentSource/SegmentSink pair + Ingress). Usage:

        handler = SegmentSource(chain(ops..., sink=engine))
        await endpoint.serve(handler)
    """

    def __init__(self, engine: Engine):
        self.engine = engine

    def __call__(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self.engine(request, context)


class RemoteSink:
    """The matching sink: forwards to a remote endpoint's client
    (reference SegmentSink — the network edge of a split pipeline)."""

    def __init__(self, client, mode: str = "round_robin"):
        self.client = client
        self.mode = mode

    def __call__(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._run(request, context)

    async def _run(self, request: Any, context: Context):
        stream = await self.client.generate(request, mode=self.mode,
                                            context=context)
        try:
            async for env in stream:
                yield env
        finally:
            if context.killed:
                await stream.kill()
            elif context.stopped:
                await stream.stop_generating()
