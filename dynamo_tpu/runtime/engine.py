"""AsyncEngine abstraction — the universal streaming-engine interface.

Reference lib/runtime/src/engine.rs: ``AsyncEngine::generate(SingleIn<Req>)
-> ManyOut<Resp>`` with an ``AsyncEngineContext`` carrying the request id and
``stop_generating``/``kill`` controls, and ``Annotated<T>`` (reference
lib/runtime/src/protocols/annotated.rs) as the SSE-shaped envelope every
streamed response travels in.

In this framework an engine is any object with::

    async def generate(self, request, context: Context) -> AsyncIterator[Any]

where the returned async iterator yields JSON/msgpack-serializable items.
``Context.stopped``/``killed`` must be honored by long-running engines.
"""

from __future__ import annotations

import asyncio
import uuid
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Optional, Protocol, runtime_checkable


class Context:
    """Per-request context: id + cancellation controls + deadline.

    ``stop_generating`` asks for a graceful early finish (emit what you have);
    ``kill`` demands immediate termination (reference engine.rs:47-85).
    ``deadline`` (a :class:`~dynamo_tpu.runtime.guard.Deadline`, or None)
    is the request's end-to-end budget: once it expires, ``stopped``
    reports True, so every loop that already polls cancellation — engine
    admission, decode dispatch, the detokenizing backend — enforces the
    deadline with no extra plumbing, and the sequence's pages free on the
    normal cancel path.
    """

    __slots__ = ("id", "_stop", "_kill", "annotations", "deadline",
                 "_kill_cbs")

    def __init__(self, request_id: Optional[str] = None, deadline=None):
        self.id: str = request_id or uuid.uuid4().hex
        self._stop = asyncio.Event()
        self._kill = asyncio.Event()
        self.annotations: dict = {}
        self.deadline = deadline
        # synchronous kill hooks (dynarevive): transports register e.g.
        # a connection close so kill() severs the upstream IMMEDIATELY —
        # a client disconnect must not wait for an abandoned generator
        # chain to be garbage-collected before the worker stops decoding
        self._kill_cbs: list = []

    @property
    def expired(self) -> bool:
        return self.deadline is not None and self.deadline.expired

    @property
    def stopped(self) -> bool:
        return self._stop.is_set() or self._kill.is_set() or self.expired

    @property
    def killed(self) -> bool:
        return self._kill.is_set()

    def cancel_reason(self) -> str:
        """Finish reason for a cancelled request: "timeout" when the
        deadline (not the caller) ended it — the satellite the OpenAI
        finish_reason mapping surfaces to clients."""
        return "timeout" if self.expired else "cancelled"

    def stop_generating(self) -> None:
        self._stop.set()

    def on_kill(self, cb) -> None:
        """Register a SYNC callback run by ``kill()`` (immediately if
        already killed). Used by stream adapters to sever their upstream
        connection the moment the caller abandons the request."""
        if self._kill.is_set():
            self._run_kill_cb(cb)
        else:
            self._kill_cbs.append(cb)

    @staticmethod
    def _run_kill_cb(cb) -> None:
        try:
            cb()
        except Exception:  # noqa: BLE001 — a teardown hook must never
            # mask the kill itself
            pass

    def kill(self) -> None:
        self._stop.set()
        self._kill.set()
        cbs, self._kill_cbs = self._kill_cbs, []
        for cb in cbs:
            self._run_kill_cb(cb)

    async def wait_stopped(self) -> None:
        await self._stop.wait()


@runtime_checkable
class AsyncEngine(Protocol):
    """Structural type for engines; anything with this shape qualifies."""

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        ...


@dataclass
class Annotated:
    """SSE-shaped response envelope: exactly one of data/event-comment forms.

    Reference lib/runtime/src/protocols/annotated.rs — every streamed
    response crosses process boundaries inside this envelope so that
    annotations (events/comments) can ride the same stream as data.
    """

    data: Any = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: Optional[list] = None

    def to_dict(self) -> dict:
        d: dict = {}
        if self.data is not None:
            d["data"] = self.data
        if self.id is not None:
            d["id"] = self.id
        if self.event is not None:
            d["event"] = self.event
        if self.comment is not None:
            d["comment"] = self.comment
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Annotated":
        return cls(data=d.get("data"), id=d.get("id"), event=d.get("event"),
                   comment=d.get("comment"))

    @classmethod
    def from_error(cls, message: str) -> "Annotated":
        return cls(event="error", comment=[message])

    @classmethod
    def from_annotation(cls, name: str, value: Any) -> "Annotated":
        return cls(event=name, comment=[value] if not isinstance(value, list) else value)

    @property
    def is_error(self) -> bool:
        return self.event == "error"

    def error_message(self) -> str:
        return "; ".join(str(c) for c in (self.comment or []))
