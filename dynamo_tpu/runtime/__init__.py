"""Distributed runtime: control plane, component model, streaming plane.

Reference: lib/runtime/src/ (the dynamo-runtime crate).
"""

from .codec import TwoPartMessage, decode_buffer, encode
from .component import (AsyncResponseStream, Client, Component, Endpoint,
                        EndpointAddress, EndpointInstance, Namespace)
from .config import RuntimeConfig
from .dcp_client import (DcpClient, DcpError, KvItem, Message,
                         NoRespondersError, PrefixWatch, WatchEvent, pack,
                         unpack)
from .dcp_server import DcpServer
from .engine import Annotated, AsyncEngine, Context
from .runtime import (DistributedRuntime, Runtime, Worker, dynamo_worker)
from .tasks import backoff_interval, cancel_join, spawn_tracked
from .tcp import TcpCallHome, TcpConnectionInfo, TcpStreamServer

__all__ = [
    "Annotated", "AsyncEngine", "AsyncResponseStream", "Client", "Component",
    "Context", "DcpClient", "DcpError", "DcpServer", "DistributedRuntime",
    "Endpoint", "EndpointAddress", "EndpointInstance", "KvItem", "Message",
    "Namespace", "NoRespondersError", "PrefixWatch", "Runtime",
    "RuntimeConfig", "TcpCallHome", "TcpConnectionInfo", "TcpStreamServer",
    "TwoPartMessage", "WatchEvent", "Worker", "backoff_interval",
    "cancel_join", "decode_buffer", "dynamo_worker", "encode", "pack",
    "spawn_tracked", "unpack",
]
